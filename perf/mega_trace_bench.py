"""Device task tracer bench → perf/MEGA_TRACE.json (ISSUE 8).

Three arms, all CPU-runnable (interpret mesh — same harness convention
as perf/mega_serve_bench.py):

1. **Tracer cost + bit-identity** (tp=1 serving workload): the SAME
   request set through ``ContinuousEngine(mode="mega")`` untraced and
   traced. Outputs must be bit-identical; the added cost of the traced
   arm (in-kernel ring stores + host-side ring decode) is reported as
   median decode-wall per emitted token, with per-run spread — this
   host's wall clock swings, so the platform-independent number (ring
   decode µs/launch, measured separately) rides along.
2. **Measured overlap exposure** (tp=4, the serving megakernel config):
   decode the ring of an ``overlap_ar`` launch and report
   windows/hidden/exposed — the ring-derived replacement for
   perf/MEGA_SERVE.json's ``overlap_exposure_estimate`` (an analytic
   model; ROADMAP item 2 called out that every overlap number was
   analytic). Under interpret the ticks are the logical clock — phase
   *structure* is exact, durations are phase counts; on hardware the
   same decoder yields cycle-true numbers.
3. **Merged timeline**: host ``group_profile`` capture around the
   traced serving run, device task rows injected — one file, host
   spans + device tasks, same request trace id on both.

Usage: JAX_PLATFORMS=cpu python perf/mega_trace_bench.py [--out ...]
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "XLA_FLAGS",
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
)


def _requests():
    # Shared-prefix population, mixed lengths — small enough to finish
    # on the interpret host, big enough for several NS=8 launches.
    base = list(range(1, 9))
    return [
        (base + [10, 11], 12),
        (base + [12, 13, 14], 10),
        (list(range(3, 15)), 12),
        (base, 9),
    ]


def bench_engine_arm(model, *, kernel_trace: bool, runs: int):
    """Median decode wall per emitted token over ``runs`` warm runs."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    eng = ContinuousEngine(
        model, max_batch=2, max_length=64, page_size=16, mode="mega",
        kernel_trace=kernel_trace,
    )
    outs = eng.run(_requests(), results=True)  # warm: compiles
    walls = []
    for _ in range(runs):
        t0 = time.monotonic()
        eng.run(_requests(), results=True)
        emitted = eng.stats["generated_tokens"]
        walls.append((time.monotonic() - t0) / max(emitted, 1))
    return eng, outs, walls


def ring_host_cost_us(launch, repeats: int = 200) -> float:
    """The tracer's FULL per-launch host work — the vectorized inline
    path ``_record_kernel_trace`` pays (gap check, per-opcode duration
    grouping, measured overlap, registry observes) — measured
    deterministically, unlike this host's wall. Record decoding is
    LAZY (summary/merge consumers only) and intentionally excluded."""
    from triton_distributed_tpu.obs import kernel_trace as kt

    arr = launch.ring
    t0 = time.perf_counter()
    for _ in range(repeats):
        kt.observe_launch(kt.KernelTraceLaunch(
            wall_s=launch.wall_s, t0=0.0, nsteps=launch.nsteps,
            ring=arr,
        ))
    return (time.perf_counter() - t0) / repeats * 1e6


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "MEGA_TRACE.json"))
    p.add_argument("--runs", type=int, default=3)
    p.add_argument("--trace-dir", default="/tmp/mega_trace_bench")
    args = p.parse_args(argv)

    import jax

    if jax.default_backend() != "tpu":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.obs import kernel_trace as kt
    from triton_distributed_tpu.runtime import mesh as mesh_mod
    from triton_distributed_tpu.runtime.profiling import group_profile

    result: dict = {
        "metric": "mega_device_task_tracer",
        "platform": jax.default_backend(),
        "workload": {
            "model": "tiny", "requests": len(_requests()),
            "max_batch": 2, "ns": 8, "page_size": 16,
        },
    }

    # -- arm 1: tp=1 engine, tracer on/off -------------------------------
    # No profiler capture around either timing arm — a jax.profiler
    # trace taxes the interpreted kernel far beyond anything the
    # tracer itself costs (the merged-timeline arm below captures ONE
    # separate short run).
    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    eng_off, outs_off, walls_off = bench_engine_arm(
        model, kernel_trace=False, runs=args.runs)
    eng_on, outs_on, walls_on = bench_engine_arm(
        model, kernel_trace=True, runs=args.runs)
    bit_identical = all(
        a.status == b.status == "ok"
        and np.array_equal(a.tokens, b.tokens)
        for a, b in zip(outs_off, outs_on)
    )
    launches = eng_on.kernel_trace_launches()
    rec0 = launches[-1]
    med_off = statistics.median(walls_off)
    med_on = statistics.median(walls_on)
    wall_pct = (med_on - med_off) / med_off * 100.0
    # Op-attributed estimate (the OBS_OVERHEAD.json convention: this
    # host's wall swings far beyond the cost being measured, so the
    # headline prices the tracer's DETERMINISTIC added work over the
    # measured launch wall): the only host-side work the tracer adds
    # per launch is the ring decode, measured standalone below; the
    # in-kernel addition is O(tasks·steps) scalar SMEM stores under a
    # multi-ms launch. On-chip projection uses the relay-measured
    # ~2 ms/step dispatch-tax floor (perf/MEGA_SERVE.json) × NS.
    decode_us = ring_host_cost_us(rec0)
    ns = rec0.nsteps
    launch_wall_off_ms = med_off * 1e3 * ns * 2  # B=2 slots emit 2/step
    onchip_launch_ms = 2.0 * ns
    result["tracer_overhead"] = {
        "ring_host_us_per_launch": round(decode_us, 1),
        "ring_bytes_per_launch": int(rec0.ring[0].nbytes),
        "overhead_pct_of_launch_this_host": round(
            decode_us / 1e3 / launch_wall_off_ms * 100.0, 3),
        "overhead_pct_of_launch_onchip_projection": round(
            decode_us / 1e3 / onchip_launch_ms * 100.0, 3),
        "onchip_launch_ms_basis": onchip_launch_ms,
        "wall_ab_advisory": {
            "decode_wall_per_token_off_ms": round(med_off * 1e3, 3),
            "decode_wall_per_token_on_ms": round(med_on * 1e3, 3),
            "wall_delta_pct": round(wall_pct, 2),
            "runs": args.runs,
            "spread_off_ms": [round(w * 1e3, 3) for w in walls_off],
            "spread_on_ms": [round(w * 1e3, 3) for w in walls_on],
            "note": (
                "interpret-mode wall: the CPU interpreter executes the "
                "ring's scalar stores as host callbacks, a tax real "
                "hardware does not pay (OBS_OVERHEAD.json documents "
                "this host's swing); advisory only"
            ),
        },
        "bar": "< 2% added decode-step cost",
        "meets_bar": bool(
            decode_us / 1e3 / min(launch_wall_off_ms, onchip_launch_ms)
            * 100.0 < 2.0
        ),
    }
    result["bit_identical_on_off"] = bool(bit_identical)

    # Ring validation on the serving launches — fetched with the
    # ENGINE's exact build key (num_pages included), so this reads the
    # cached order of the launches being validated instead of forcing
    # a second build against a possibly different schedule.
    order = eng_on._mega_model().multi_task_order(
        2, 64, eng_on.NS, sampled=False, page=16,
        kv_quant=eng_on.kv_dtype is not None,
        num_pages=int(eng_on.cache.k_pages.shape[1]),
        valid_arg=True, trace=True,
    )
    problems = []
    for launch in launches:
        problems += kt.validate_ring(launch.get_records(), order)
    result["ring_validation"] = {
        "launches_decoded": len(launches),
        "gap_free": True,  # decode_trace(strict) raised otherwise
        "dependency_order_ok": not problems,
        "problems": problems[:5],
    }

    # -- arm 3: merged host+device timeline ------------------------------
    # ONE short captured run (profiling the timing arms would tax them;
    # see arm 1 note): host spans + device task rows in one file.
    n_before = eng_on._trace_launch_n
    with group_profile("mega_trace", out_dir=args.trace_dir, merge=False):
        eng_on.run(_requests()[:2], results=True)
    added = eng_on._trace_launch_n - n_before
    captured = eng_on.kernel_trace_launches()[-added:] if added else []
    merged = kt.merge_with_host_profile(
        "mega_trace", args.trace_dir, captured or launches)
    host_spans = device_rows = 0
    shared_trace_id = None
    if merged:
        import gzip

        with gzip.open(merged, "rt") as f:
            data = json.load(f)
        dev = [e for e in data["traceEvents"]
               if isinstance(e.get("args"), dict)
               and "trace_ids" in e["args"]]
        device_rows = len(dev)
        host_spans = sum(
            1 for e in data["traceEvents"]
            if e.get("ph") == "X"
            and not (isinstance(e.get("args"), dict)
                     and "trace_ids" in e["args"])
        )
        ids = set()
        for e in dev:
            ids.update(x for x in e["args"]["trace_ids"].split(",") if x)
        shared_trace_id = sorted(ids)[0] if ids else None
    result["merged_timeline"] = {
        "path": merged,
        "host_events": host_spans,
        "device_task_rows": device_rows,
        "example_trace_id": shared_trace_id,
    }
    mesh_mod.finalize_distributed()

    # -- arm 2: tp=4 measured overlap exposure ---------------------------
    ctx4 = mesh_mod.initialize_distributed(tp=4, devices=jax.devices()[:4])
    model4 = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    cache = model4.new_cache(1, max_length=64)
    step = model4.decode_fn("xla")
    import jax.numpy as jnp

    for t in (3, 5):
        _, cache = step(model4.params, jnp.asarray([t], jnp.int32), cache)
    mega = MegaQwen3(model4, cfg=MegaConfig(
        fuse_norms=True, cross_prefetch=True, overlap_ar=True))
    NS = 2
    fn = mega.decode_multi_fn(1, 64, NS, trace=True)
    _t, _l, _c, ring = fn(model4.params, jnp.asarray([19], jnp.int32), cache)
    records = kt.decode_trace(np.asarray(ring))
    order4 = mega.multi_task_order(1, 64, NS, trace=True)
    rep = kt.overlap_report(records)
    result["overlap_measured"] = {
        **rep,
        "tp": 4, "nsteps": NS,
        "config": "fuse_norms:cross_prefetch:overlap_ar (serving default)",
        "dependency_order_ok": not kt.validate_ring(records, order4),
        "clock": (
            "logical (interpret): durations are instrumented-phase "
            "counts; window structure — which phases coincide — is "
            "exact, and replaces MEGA_SERVE.json's analytic "
            "overlap_exposure_estimate arm with ring-derived numbers. "
            "On hardware the same fields carry cycles."
        ),
    }
    mesh_mod.finalize_distributed()

    result["provenance"] = {
        "harness": "perf/mega_trace_bench.py",
        "written": "MEGA_TRACE.json",
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
