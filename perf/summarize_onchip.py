"""Summarize an ONCHIP_r*.jsonl log into a per-step digest.

Each queue window appends raw step records (rc, wall, stdout tail);
this collapses them into the latest outcome per step plus the headline
numbers the round log needs (ladder ms/step, tuned config, MFU table,
EP floor, adaptive-order verdict, e2e tok/s).

Usage: python perf/summarize_onchip.py [perf/ONCHIP_r4.jsonl]
"""

import json
import sys


def last_json_line(text: str) -> dict | None:
    """Deepest parseable JSON object line in a stdout tail."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def main(argv=None) -> int:
    path = (argv or sys.argv[1:] or ["perf/ONCHIP_r4.jsonl"])[0]
    latest: dict[str, dict] = {}
    order: list[str] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                step = r.get("step")
                if not step:
                    continue
                if step not in latest:
                    order.append(step)
                # Prefer the latest SUCCESS; else the latest attempt.
                if r.get("rc") == 0 or latest.get(step, {}).get("rc") != 0:
                    latest[step] = r
    except FileNotFoundError:
        print(f"no log at {path}")
        return 1

    for step in order:
        r = latest[step]
        line = {"step": step, "rc": r.get("rc"),
                "wall_s": r.get("wall_s")}
        payload = last_json_line(r.get("stdout_tail", ""))
        if payload:
            # Pull the fields that matter per step kind.
            for key in ("ladder", "value", "metric", "vs_baseline",
                        "platform", "tuned", "ms_per_step",
                        "kernel_overhead_us_n1_lower_bound",
                        "overhead_us_by_block", "one_dma_copy_us",
                        "reacts", "adaptive_order", "ring_order",
                        "tok_s", "deterministic", "summary",
                        "cross_check_ok", "achieved_gbs",
                        "mega_multi_cross_check", "best_rung"):
                if key in payload:
                    line[key] = payload[key]
        if r.get("rc") not in (0, None):
            line["stderr_tail"] = (r.get("stderr_tail") or "")[-200:]
        print(json.dumps(line))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
