"""Shared greedy-chain runners for the megakernel sweep harnesses.

One implementation of the token-chain timing loop, used by both
``mega_ns_sweep.py`` and ``mega_tile_sweep.py`` so their fits and
cross-checks always time the SAME computation shape as each other (and
as ``bench.py``'s mega/mega_multi rungs, which this mirrors — bench.py
keeps its own copy because its worker emits progress lines between
rungs and must stay runnable when ``perf/`` is absent from the
deployment).

Import only after the jax platform is configured (both sweeps select
CPU/TPU inside ``main`` before importing this module).
"""

import jax
import jax.numpy as jnp
import numpy as np


def prepare_decode_state(model, prompt_len: int = 512):
    """The sweeps' common starting state: a prefilled cache and the
    first greedy token — ONE definition so both harnesses (and their
    fits) start every chain from the same computation.

    Returns ``(tok0 [1] i32, cache, s_max)``.
    """
    cache = model.new_cache(1)
    tokens = jnp.asarray(
        np.arange(prompt_len) % model.cfg.vocab_size, jnp.int32
    )
    logits, cache = model.prefill(tokens, cache, "xla")
    tok0 = jnp.argmax(logits)[None].astype(jnp.int32)
    return tok0, cache, int(cache.k.shape[3])


def single_step_chain(mstep, params, tok0, cache0, steps):
    """``steps`` greedy single-step decodes chained in one jit; returns
    ``once()`` yielding the np token chain [steps]."""

    def run_n(params, tok, cache, n):
        def body(i, carry):
            tok, cache, seq = carry
            logits, cache = mstep(params, tok, cache)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            return tok, cache, seq.at[i].set(tok[0])

        seq0 = jnp.zeros((n,), jnp.int32)
        return jax.lax.fori_loop(0, n, body, (tok, cache, seq0))[2]

    jrun = jax.jit(run_n, static_argnums=3)
    return lambda: np.asarray(jrun(params, tok0, cache0, steps))


def multi_step_chain(mmulti, ns, params, tok0, cache0, steps):
    """``steps // ns`` launches of an NS-wide multi-step kernel chained
    in one jit; returns ``once()`` yielding the np token chain [steps]."""
    if steps % ns:
        raise ValueError(f"ns={ns} must divide steps={steps}")

    def run_n(params, tok, cache, nl):
        def body(i, carry):
            tok, cache, seq = carry
            toks, _lg, cache = mmulti(params, tok, cache)
            seq = jax.lax.dynamic_update_slice(seq, toks[:, 0], (i * ns,))
            return toks[ns - 1], cache, seq

        seq0 = jnp.zeros((nl * ns,), jnp.int32)
        return jax.lax.fori_loop(0, nl, body, (tok, cache, seq0))[2]

    jrun = jax.jit(run_n, static_argnums=3)
    return lambda: np.asarray(jrun(params, tok0, cache0, steps // ns))
