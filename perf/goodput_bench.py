"""SLO goodput yardstick → perf/GOODPUT.json.

The capstone artifact of the observability layer (ROADMAP item 5,
docs/observability.md "SLO goodput"): drive production-shaped traffic
(``perf/loadgen.py``) through the STREAMING wire against a live
server and report what a user would see —

- p50/p99 TTFT and TPOT, **stamped wire-side** (the server stamps
  every token frame at its socket write; this bench adds no client
  clock of its own);
- **goodput-vs-arrival-rate** curves (≥3 rates) for a single engine
  AND a supervised process fleet (``--fleet`` arm: 2 stub children
  under ``FleetSupervisor`` behind one front server);
- a **cancellation arm**: a fraction of requests cancel mid-stream;
  reported are the tokens NOT generated (work the teardown saved) and
  a clean pool audit (pages actually came home).

Gates asserted BEFORE any number is recorded (repo convention —
perf artifacts carry only verified numbers):

- every completed streamed request's tokens are IDENTICAL to the
  stub's pure reference generator AND to a non-streaming request for
  the same prompt (the streaming path changes transport, never
  tokens);
- the post-run pool/radix audit is clean in every arm.

The engine is ``models/stub.py`` (real radix/pool control plane, pure
hash "model", seeded wall-time floor) so the bench is CPU-runnable and
deterministic in its token outputs; the latency numbers are
host-advisory (this is a shared CPU container), but the RELATIVE
goodput-vs-rate shape and the wire-side measurement machinery are what
the artifact certifies. Run on real hardware with a real model by
swapping the server launch for ``run_server`` — the driver is
transport-only.

Usage:
    python perf/goodput_bench.py [--out perf/GOODPUT.json] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from perf.loadgen import LoadSpec, generate_trace, replay  # noqa: E402


def _pct(vals, q):
    vals = [v for v in vals if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


def _run_rate(host, port, spec: LoadSpec) -> dict:
    """One arrival-rate point: replay the trace, judge client-visible
    outcomes, collect wire-side latencies."""
    from triton_distributed_tpu.models.stub import stub_generate

    trace = generate_trace(spec)
    records = replay(trace, host, port)
    met = missed = cancelled = errors = 0
    ttfts, tpots, e2es = [], [], []
    tokens_not_generated = 0
    for row, rec in zip(trace, records):
        if rec.get("error"):
            errors += 1
            missed += 1  # the user got nothing: a miss, not a skip
            continue
        wire = rec.get("wire") or {}
        outcome = wire.get("outcome")
        if outcome == "cancelled":
            # The cancellation arm's ledger: tokens the teardown
            # saved. Classified by the SERVER's outcome, not the
            # trace's intent — a cancel that lost the race to a fast
            # completion falls through to the ordinary met/missed +
            # identity gate below (its tokens are all there).
            cancelled += 1
            tokens_not_generated += row["gen_len"] - len(rec["tokens"])
            continue
        # GATE: streamed tokens == the pure reference generator.
        gold = stub_generate(row["prompt"], row["gen_len"])
        assert rec["tokens"] == gold, (
            f"streamed tokens diverged from reference for request "
            f"{row['i']}: {rec['tokens']} != {gold}"
        )
        if outcome == "met":
            met += 1
        else:
            missed += 1
        ttfts.append(wire.get("ttft_s"))
        tpots.append(wire.get("tpot_s"))
        e2es.append(wire.get("e2e_s"))
    judged = met + missed
    return {
        "rate_rps": spec.rate,
        "process": spec.process,
        "n_requests": spec.n_requests,
        "met": met,
        "missed": missed,
        "errors": errors,
        "cancelled": cancelled,
        "tokens_not_generated": tokens_not_generated,
        "goodput": (met / judged) if judged else None,
        "ttft_p50_s": _pct(ttfts, 50),
        "ttft_p99_s": _pct(ttfts, 99),
        "tpot_p50_s": _pct(tpots, 50),
        "tpot_p99_s": _pct(tpots, 99),
        "e2e_p99_s": _pct(e2es, 99),
    }


def _assert_stream_matches_nonstream(host, port, spec: LoadSpec) -> int:
    """GATE: for every unique prompt of a trace, a non-streaming
    request returns the exact token sequence streaming delivered
    (both equal the reference generator, checked independently)."""
    from triton_distributed_tpu.models.stub import stub_generate
    from triton_distributed_tpu.serving.server import (
        request,
        request_stream,
    )

    trace = generate_trace(spec)
    seen = set()
    checked = 0
    for row in trace:
        key = (tuple(row["prompt"]), row["gen_len"])
        if key in seen:
            continue
        seen.add(key)
        payload = {"requests": [row["prompt"]],
                   "gen_lens": [row["gen_len"]]}
        streamed = []
        for fr in request_stream(host, port, dict(payload)):
            if fr.get("frame") == "token":
                streamed.append(fr["token"])
            else:
                summary = fr
        plain = request(host, port, payload)
        gold = stub_generate(row["prompt"], row["gen_len"])
        assert streamed == summary["outputs"][0] == plain["outputs"][0] \
            == gold, f"stream/non-stream divergence on request {row['i']}"
        checked += 1
    return checked


def _single_arm(args, slo_spec, rates) -> dict:
    from triton_distributed_tpu.models.stub import StubEngine
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving.server import ModelServer, request

    eng = StubEngine(num_pages=512, page_size=4,
                     delay_s=args.stub_delay)
    server = ModelServer(eng, max_pending=64, slo=slo_spec).start()
    try:
        identity_checked = _assert_stream_matches_nonstream(
            server.host, server.port,
            LoadSpec(rate=rates[0], n_requests=min(args.n, 12),
                     seed=args.seed),
        )
        curve = []
        for rate in rates:
            obs_metrics.default_registry().clear()
            curve.append(_run_rate(
                server.host, server.port,
                LoadSpec(rate=rate, n_requests=args.n, seed=args.seed),
            ))
        # Cancellation arm: half the requests hang up mid-stream.
        obs_metrics.default_registry().clear()
        cancel = _run_rate(
            server.host, server.port,
            LoadSpec(rate=rates[1], n_requests=args.n,
                     cancel_frac=0.5, cancel_after=2,
                     seed=args.seed + 1),
        )
        slo_view = request(server.host, server.port, {"cmd": "slo"})
        # GATE: pages came home — teardown freed every cancelled
        # request's pages (plus the usual partition invariants).
        problems = eng.audit()
        assert problems == [], f"single-arm audit: {problems}"
        assert cancel["cancelled"] > 0, "cancellation arm cancelled nothing"
        assert cancel["tokens_not_generated"] > 0
        return {
            "rates": curve,
            "cancellation": {
                **cancel,
                "audit_clean": True,
            },
            "stream_matches_nonstream": True,
            "identity_prompts_checked": identity_checked,
            "slo_verb_sample": slo_view["slo"]["classes"],
        }
    finally:
        server.shutdown()


def _fleet_arm(args, slo_spec, rates) -> dict:
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving.server import ModelServer, request
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    sup = FleetSupervisor([
        stub_spec(f"r{i}", delay_s=args.stub_delay, num_pages=512,
                  page_size=4)
        for i in range(args.fleet)
    ])
    router = sup.start()
    server = ModelServer(router, max_pending=64, slo=slo_spec).start()
    try:
        curve = []
        for rate in rates:
            obs_metrics.default_registry().clear()
            curve.append(_run_rate(
                server.host, server.port,
                LoadSpec(rate=rate, n_requests=args.n,
                         seed=args.seed + 2),
            ))
        # One fleet-scope scrape rides the artifact: the merged,
        # replica-labeled exposition is the fleet observability story
        # (docs/scale-out.md "Fleet-scope telemetry").
        fleet_scrape = request(server.host, server.port,
                               {"cmd": "metrics", "scope": "fleet"})
        assert fleet_scrape.get("scope") == "fleet"
        assert 'replica="r0"' in fleet_scrape["prometheus"]
        problems = request(server.host, server.port,
                           {"cmd": "audit"})["problems"]
        assert problems == [], f"fleet-arm audit: {problems}"
        return {
            "replicas": args.fleet,
            "rates": curve,
            "fleet_scrape_replicas": fleet_scrape["replicas"],
            "audit_clean": True,
        }
    finally:
        server.shutdown()
        sup.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "GOODPUT.json"))
    p.add_argument("--n", type=int, default=28,
                   help="requests per arrival-rate point")
    p.add_argument("--rates", type=float, nargs="+",
                   default=[3.0, 8.0, 16.0],
                   help=">= 3 arrival rates (req/s) to sweep")
    p.add_argument("--stub-delay", type=float, default=0.12,
                   help="stub per-batch wall floor (s): sets the "
                   "service rate the sweep saturates")
    p.add_argument("--fleet", type=int, default=2,
                   help="process replicas in the fleet arm (0 skips)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true",
                   help="small n for a smoke run (artifact still "
                   "valid, noisier)")
    p.add_argument("--slo-ttft-s", type=float, default=0.5)
    p.add_argument("--slo-tpot-s", type=float, default=0.10)
    p.add_argument("--slo-e2e-s", type=float, default=4.0)
    args = p.parse_args(argv)
    if args.quick:
        args.n = min(args.n, 10)
    if len(args.rates) < 3:
        p.error("need >= 3 arrival rates for the goodput curve")

    from triton_distributed_tpu.obs.slo import SLOSpec

    slo_spec = SLOSpec("default", ttft_s=args.slo_ttft_s,
                       tpot_s=args.slo_tpot_s, e2e_s=args.slo_e2e_s)

    t0 = time.time()
    single = _single_arm(args, slo_spec, args.rates)
    fleet = (_fleet_arm(args, slo_spec, args.rates)
             if args.fleet > 0 else None)
    out = {
        "bench": "goodput_bench",
        "method": (
            "loadgen streaming replay (Poisson arrivals, Zipf "
            "shared-prefix population, lognormal output lengths) "
            "against a live wire server; every latency stamped "
            "WIRE-side by the server's streaming frame writes; "
            "tokens gated identical to the pure reference generator "
            "and to non-streaming responses before recording; pool "
            "audits gated clean. Stub engine: control-plane-real, "
            "wall-clock advisory on this shared CPU host."
        ),
        "slo": slo_spec.as_dict(),
        "stub_delay_s": args.stub_delay,
        "n_per_rate": args.n,
        "single": single,
        "fleet": fleet,
        "wall_s": round(time.time() - t0, 2),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(
        {"out": args.out, "wall_s": out["wall_s"],
         "single_goodput": [r["goodput"] for r in single["rates"]],
         "fleet_goodput": (
             [r["goodput"] for r in fleet["rates"]] if fleet else None
         )}, indent=2,
    ))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
