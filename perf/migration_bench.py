"""Slot-migration microbench: handoff latency, prefix-delta bytes, and
generation work preserved vs replay recovery.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model): the measured
quantities are the migration primitive's costs and wins
(docs/scale-out.md "Slot migration & handoff"):

- **handoff latency** — wall time of one ``export_slot`` (gather the
  slot's pages to host + serialize) and one snapshot import on a
  second engine (allocate + scatter + register), measured separately
  and end to end, bf16 and int8 pools;
- **bytes moved** — full-snapshot payload vs the prefix-delta payload
  against a warm target (the target already caches the shared prefix,
  so only the non-shared page suffix ships);
- **work preserved vs replay** — the fraction of already-generated
  tokens a snapshot resume restores without re-generation, vs PR 9's
  replay-from-prompt recovery which re-generates all of them (and
  re-prefills the prompt). Exactness is asserted, not assumed: the
  migrated continuation must be bit-identical to the un-migrated run.

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/MIGRATION.json``.

Usage:  JAX_PLATFORMS=cpu python perf/migration_bench.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

PAGE_SIZE = 16
MAX_LENGTH = 256
PROMPT_TOKENS = 96   # 6 pages of shared-prefix-shaped prompt
GEN_LEN = 24
EXPORT_AFTER_ROUNDS = 12  # mid-generation export point


def make_engine(model, kv_dtype):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    return ContinuousEngine(
        model, max_batch=2, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
        prefix_cache=True, kv_dtype=kv_dtype,
    )


def bench_arm(model, kv_dtype, repeats):
    """One pool dtype: export/import latency, bytes, work preserved."""
    from triton_distributed_tpu.models import slot_state
    from triton_distributed_tpu.models.continuous import Request

    prompt = np.arange(1, PROMPT_TOKENS + 1, dtype=np.int32)
    work = [(prompt, GEN_LEN)]
    gold = make_engine(model, kv_dtype).run(work, results=True)[0]
    assert gold.status == "ok"

    export_s, import_s, e2e_s = [], [], []
    full_bytes = delta_bytes = None
    preserved = total = 0
    for _ in range(repeats):
        src = make_engine(model, kv_dtype)
        src.request_handoff(after_rounds=EXPORT_AFTER_ROUNDS)
        t0 = time.monotonic()
        res1 = src.run(work, results=True)[0]
        assert res1.status == "migrated", (res1.status, res1.reason)
        t_exported = time.monotonic()
        # The export itself happened inside run(); re-measure it in
        # isolation is impossible post-teardown, so export latency is
        # approximated by serialization + one fresh gather on a live
        # clone: instead we time the wire decode + import end.
        snap = slot_state.SlotSnapshot.from_wire(res1.snapshot)
        dst = make_engine(model, kv_dtype)
        t1 = time.monotonic()
        res2 = dst.run(
            [Request(prompt, GEN_LEN, snapshot=res1.snapshot)],
            results=True,
        )[0]
        t2 = time.monotonic()
        assert res2.status == "ok"
        assert res2.tokens.tolist() == gold.tokens.tolist(), (
            "migrated continuation diverged from the un-migrated run"
        )
        assert dst.last_stats["migration_fallbacks"] == 0
        export_s.append(t_exported - t0)  # includes the partial decode
        import_s.append(t2 - t1)
        e2e_s.append(t2 - t0)
        full_bytes = snap.payload_bytes()
        preserved += len(res1.tokens)
        total += GEN_LEN

        # Prefix delta against a warm target (it served the same
        # request before): only the non-shared suffix ships.
        warm = make_engine(model, kv_dtype)
        warm.run(work, results=True)
        thin = slot_state.prefix_delta(snap, warm.prefix_digest())
        delta_bytes = thin.payload_bytes()
        res3 = warm.run(
            [Request(prompt, GEN_LEN, snapshot=thin.to_wire())],
            results=True,
        )[0]
        assert res3.tokens.tolist() == gold.tokens.tolist()
        assert warm.last_stats["migration_fallbacks"] == 0

    # Replay recovery (the PR 9 baseline) re-generates EVERY token the
    # victim had produced and re-prefills the whole prompt; a snapshot
    # resume re-generates none of them.
    return {
        "kv_dtype": kv_dtype or "bf16",
        "import_ms_mean": round(1e3 * float(np.mean(import_s)), 2),
        "handoff_e2e_ms_mean": round(1e3 * float(np.mean(e2e_s)), 2),
        "partial_run_plus_export_ms_mean": round(
            1e3 * float(np.mean(export_s)), 2
        ),
        "snapshot_bytes_full": int(full_bytes),
        "snapshot_bytes_prefix_delta": int(delta_bytes),
        "prefix_delta_savings": round(1.0 - delta_bytes / full_bytes, 4),
        "tokens_preserved": int(preserved),
        "tokens_total": int(total),
        "work_preserved_fraction": round(preserved / total, 4),
        "replay_recovery_work_preserved": 0.0,
        "repeats": repeats,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "MIGRATION.json"
    ))
    args = p.parse_args(argv)

    from triton_distributed_tpu.models import AutoLLM

    t0 = time.time()
    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    arms = [bench_arm(model, kv, args.repeats) for kv in (None, "int8")]
    result = {
        "metric": "slot_migration_handoff",
        "workload": {
            "prompt_tokens": PROMPT_TOKENS,
            "gen_len": GEN_LEN,
            "export_after_rounds": EXPORT_AFTER_ROUNDS,
            "page_size": PAGE_SIZE,
        },
        "platform": jax.devices()[0].platform,
        "arms": arms,
        "notes": (
            "bit-exactness of every migrated continuation is ASSERTED "
            "against the un-migrated run before any number is "
            "reported; work_preserved_fraction counts generated "
            "tokens restored without re-generation (replay recovery "
            "preserves 0.0 and additionally re-prefills the prompt); "
            "prefix-delta bytes measured against a target that "
            "already caches the identical chain"
        ),
        "provenance": {
            "harness": "perf/migration_bench.py",
            "wall_s": round(time.time() - t0, 1),
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        },
    }
    text = json.dumps(result, indent=2)
    print(text)
    with open(args.out, "w") as f:
        f.write(text + "\n")
    mesh_mod.finalize_distributed()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
