#!/bin/bash
# Relay watcher: probe every ~20 min; when the relay answers, run the
# still-pending on-chip measurement steps (perf/onchip_session.py) —
# steps that already passed in an earlier window are dropped from the
# queue, so a half-successful window only costs the remainder. Appends
# to perf/onchip_loop.log (gitignored scratch; results land in
# perf/ONCHIP_r4.jsonl via onchip_session).
#
# Usage: nohup bash perf/onchip_watch.sh STEPS... >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
LOG=perf/onchip_loop.log
# Steps may be given as separate args or comma-joined; normalize to the
# comma form pending()/onchip_session expect. Default: the whole
# round-4 queue in priority order.
QUEUE=$(IFS=,; echo "${*:-kernel_smoke,ladder_first,mega_tiles,ladder,decode_profile,gemm_mfu,ep_overhead,adaptive_order,ladder_17,e2e_17,serve_demo,stress,mega_ns,mega_tiles_q8,ladder_4b,ladder_8b_q8,e2e,sweep_full}")
SINCE=$(date +%s)

pending() {
  python - "$QUEUE" "$SINCE" <<'EOF'
import json, sys
queue, since = sys.argv[1].split(","), float(sys.argv[2])
done = set()
try:
    for line in open("perf/ONCHIP_r4.jsonl"):
        try:
            r = json.loads(line)
        except ValueError:
            continue  # partial line from a killed writer — not "done"
        if r.get("rc") == 0 and r.get("t_start", 0) >= since:
            done.add(r["step"])
except FileNotFoundError:
    pass
print(",".join(s for s in queue if s not in done))
EOF
}

probe() {
  timeout 150 python -c "
import jax, numpy as np, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu'
x = jnp.ones((256, 256), jnp.bfloat16)
assert float(np.asarray(x @ x)[0, 0]) == 256.0
" >/dev/null 2>&1
}

# Fail fast on unknown step names: onchip_session --only silently drops
# them, so a typo would loop the watcher forever without ever draining.
# The complaint must land in $LOG — the documented invocation discards
# stderr, and a silent death is the very failure mode this prevents.
if ! python - "$QUEUE" >>"$LOG" 2>&1 <<'EOF'
import sys
sys.path.insert(0, "perf")
from onchip_session import STEPS
known = {entry[0] for entry in STEPS}
bad = [s for s in sys.argv[1].split(",") if s not in known]
if bad:
    sys.exit(f"[watch] unknown step(s) {bad}; known: {sorted(known)}")
EOF
then
  exit 1
fi

echo "[watch $(date -u +%H:%M)] start, queue: $QUEUE" >>"$LOG"
while true; do
  REMAIN=$(pending)
  if [ -z "$REMAIN" ]; then
    echo "[watch $(date -u +%H:%M)] all steps green — done" >>"$LOG"
    exit 0
  fi
  if probe; then
    echo "[watch $(date -u +%H:%M)] relay UP — running: $REMAIN" >>"$LOG"
    python perf/onchip_session.py --only "probe,$REMAIN" >>"$LOG" 2>&1
    echo "[watch $(date -u +%H:%M)] window done (rc=$?)" >>"$LOG"
  else
    echo "[watch $(date -u +%H:%M)] relay down" >>"$LOG"
  fi
  # Short cycle: windows are ~30 min — a ~20-min probe cadence (the
  # old 1140 s sleep + 150 s probe timeout) could burn 2/3 of one
  # before noticing. ~7 min keeps discovery latency small against the
  # window length at negligible probe cost.
  sleep 420
done
