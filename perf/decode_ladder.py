"""Decode ladder benchmark: eager → jit → overlap-AR → megakernel.

Parity: the reference's headline table (``docs/mega_triton_kernel.md:27-37``)
— Qwen3 decode ms/step under torch eager / +cudagraph / triton_dist_AR /
megakernel. TPU rungs:

  eager      un-jitted per-step dispatch (torch-eager analog)
  jit        jitted decode step (CUDA-graph analog)
  pallas     jit + Pallas overlap ops (GEMM+AR decode; triton_dist_AR analog)
  mega       whole step as ONE Pallas kernel (megakernel analog)

Timing through the axon relay follows bench.py's rules: steps are
chained with a data dependency inside one jit where possible, and the
fence is fetching bytes to host (block_until_ready resolves early).
For the eager/mega rungs (host loop per step) we fetch the final token
each iteration batch.

Usage:
    python perf/decode_ladder.py --model tiny --batch 1 --ctx 512 --steps 32
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--ctx", type=int, default=512)
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--ns", type=int, default=8,
                   help="multi-step launch width (steps per kernel launch)")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--cpu", action="store_true", help="simulated CPU mesh")
    p.add_argument("--rungs", default="eager,jit,pallas,mega,mega_multi")
    args = p.parse_args(argv)

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=args.tp, devices=jax.devices()[: args.tp])
    model = AutoLLM.from_pretrained(args.model, ctx=ctx)
    B, S = args.batch, args.ctx

    def fresh_cache():
        c = model.new_cache(B, max_length=max(2 * S, S + args.steps + 8))
        c.kv_len = c.kv_len + S  # pretend S tokens prefilled
        return c

    tok0 = jnp.ones((B,), jnp.int32)
    results = {}
    rungs = args.rungs.split(",")

    def time_host_loop(step_fn, cache, steps):
        tok = tok0
        t0 = time.perf_counter()
        for _ in range(steps):
            logits, cache = step_fn(tok, cache)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
        np.asarray(tok)  # host fetch = the only reliable fence
        return (time.perf_counter() - t0) / steps * 1e3

    if "eager" in rungs:
        f = model.decode_fn("xla")  # shard_map'd, un-jitted

        def eager_step(tok, cache):
            return f(model.params, tok, cache)

        time_host_loop(eager_step, fresh_cache(), 2)  # warm
        results["eager"] = time_host_loop(eager_step, fresh_cache(), max(args.steps // 8, 2))

    for name, mode in (("jit", "xla"), ("pallas", "pallas")):
        if name not in rungs:
            continue

        def jit_step(tok, cache, mode=mode):
            return model.decode_step(tok, cache, mode)

        time_host_loop(jit_step, fresh_cache(), 3)  # warm/compile
        results[name] = time_host_loop(jit_step, fresh_cache(), args.steps)

    if "mega" in rungs:
        mega = MegaQwen3(model)
        time_host_loop(mega.decode_step, fresh_cache(), 3)
        results["mega"] = time_host_loop(mega.decode_step, fresh_cache(), args.steps)

    if "mega_multi" in rungs:
        # NS greedy steps per launch (in-kernel argmax) — the rung that
        # amortizes the per-launch dispatch tax.
        mega = MegaQwen3(model)
        NS = min(args.ns, args.steps)
        c0 = fresh_cache()
        fn = mega.decode_multi_fn(B, int(c0.k.shape[3]), NS)

        def multi_loop(cache, launches):
            tok = tok0
            t0 = time.perf_counter()
            for _ in range(launches):
                toks, _lg, cache = fn(model.params, tok, cache)
                tok = toks[-1]
            np.asarray(tok)
            return (time.perf_counter() - t0) / (launches * NS) * 1e3

        multi_loop(c0, 1)  # warm/compile (c0 reused, then donated away)
        results["mega_multi"] = multi_loop(
            fresh_cache(), max(args.steps // NS, 1)
        )

    print(json.dumps({
        "model": args.model, "batch": B, "ctx": S, "tp": args.tp,
        "ms_per_step": {k: round(v, 3) for k, v in results.items()},
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
