"""Process-fleet failure/recovery bench: detection, recovery, respawn.

Measures the ISSUE-9 acceptance quantities on a real 3-replica LOCAL
process fleet (stub-engine children — models/stub.py — behind the
production wire server, router, and supervisor; the control plane,
wire protocol, ticket recovery, and supervisor machinery are all the
production code paths):

- **detection latency** — SIGKILL (the seeded ``proc.kill`` seam,
  fired the instant a batch hits the wire) → the router marking the
  replica dead (``replica_dead`` event);
- **recovery latency** — kill → the first re-routed ticket completing
  on a survivor;
- **in-flight recovery rate** — re-routed tickets finishing ``ok``
  and BIT-EXACT vs the stub's pure generator, over all tickets
  orphaned by the kill (target 100%: the ticket-id wire dedup makes
  the at-least-once overlap safe);
- **respawn → rejoin** — the supervisor's ``replica_respawn`` event →
  the reborn replica completing a routed request.

A second arm re-runs perf/router_bench.py's shared-prefix workload
(3 groups × 4 arrivals) over REMOTE replicas to confirm the affinity
result survives the process boundary: the fleet radix hit rate must
match ROUTER.json's in-process 0.75 (the radix tree and digest
protocol are the production classes in the children; only the model
is stubbed, and hit rate is a pure control-plane quantity).

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/FLEET.json``.

Usage:  JAX_PLATFORMS=cpu python perf/fleet_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")

import numpy as np  # noqa: E402

REPLICAS = 3
BATCH_DELAY_S = 0.3
PAGE_SIZE = 16

# Affinity arm: the exact perf/router_bench.py workload shape.
GROUPS = 3
ARRIVALS_PER_GROUP = 4
SYSTEM_PROMPT_TOKENS = 64
USER_SUFFIX_TOKENS = 16


def build_prompts() -> list[np.ndarray]:
    rng = np.random.default_rng(0)
    systems = [
        rng.integers(1, 200, size=SYSTEM_PROMPT_TOKENS).astype(np.int32)
        for _ in range(GROUPS)
    ]
    prompts = []
    for _ in range(ARRIVALS_PER_GROUP):
        for g in range(GROUPS):
            prompts.append(np.concatenate([
                systems[g],
                rng.integers(1, 200, size=USER_SUFFIX_TOKENS).astype(
                    np.int32
                ),
            ]))
    return prompts


def failure_arm() -> dict:
    from triton_distributed_tpu.models.stub import stub_generate
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.runtime.faults import FaultPlan
    from triton_distributed_tpu.serving.replica import Ticket
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    obs_events.default_ring().clear()
    sup = FleetSupervisor(
        [stub_spec(f"r{i}", delay_s=BATCH_DELAY_S, page_size=PAGE_SIZE)
         for i in range(REPLICAS)],
        heartbeat_s=0.1, heartbeat_timeout_s=2.0,
        respawn_backoff_s=0.2, spawn_timeout_s=180.0,
    )
    t_up0 = time.monotonic()
    router = sup.start()
    spawn_s = time.monotonic() - t_up0
    rng = np.random.default_rng(1)
    prompts = [
        rng.integers(1, 200, size=24).astype(np.int32) for _ in range(9)
    ]
    gens = [6] * len(prompts)
    golds = [stub_generate(p, g) for p, g in zip(prompts, gens)]
    try:
        # Warm wave: every replica serves, digests publish.
        res = router.run(list(zip(prompts[:3], gens[:3])), results=True)
        assert all(r.status == "ok" for r in res)

        # Kill wave: dispatch tickets individually (round-robin lands
        # work on every replica), arm the seam, record per-ticket
        # completion stamps from waiter threads.
        plan = FaultPlan(seed=7).kill_proc(replica="r0")
        tickets = [Ticket.of((p, g)) for p, g in zip(prompts, gens)]
        done_at = [0.0] * len(tickets)

        def waiter(i: int) -> None:
            tickets[i].wait()
            done_at[i] = time.monotonic()

        threads = [
            threading.Thread(target=waiter, args=(i,), daemon=True)
            for i in range(len(tickets))
        ]
        with plan:
            for th in threads:
                th.start()
            for t in tickets:
                router._dispatch(t)
            for th in threads:
                th.join(timeout=120)
        assert plan.fired, "kill seam never fired"

        # Event-ring stamps share the monotonic clock with done_at.
        evts, _ = obs_events.default_ring().tail(0)
        t_kill = next(
            e.t for e in evts
            if e.kind == "fault" and e.fields.get("seam") == "proc.kill"
        )
        t_dead = next(
            e.t for e in evts if e.kind == "replica_dead"
        )
        rerouted = [
            (t, at, gold) for t, at, gold in zip(tickets, done_at, golds)
            if t.reroutes > 0
        ]
        ok_exact = [
            t for t, _, gold in rerouted
            if t.result.status == "ok"
            and t.result.tokens.tolist() == gold
        ]
        recovery_s = (
            min(at for _, at, _ in rerouted) - t_kill if rerouted
            else None
        )
        # Every non-rerouted ticket must be bit-exact too (survivors).
        survivors_exact = all(
            t.result.status == "ok" and t.result.tokens.tolist() == gold
            for t, at, gold in zip(tickets, done_at, golds)
            if t.reroutes == 0
        )

        # Respawn → rejoin: wait for the slot to come back, then force
        # routing onto the reborn replica and time its first serve.
        assert sup.wait_healthy(REPLICAS, timeout_s=120)
        t_respawn = next(
            e.t for e in obs_events.default_ring().tail(0)[0]
            if e.kind == "replica_respawn"
        )
        reborn = router.replica("r0#1")
        # Survivor audits BEFORE draining them (a drained child exits;
        # there is nothing left to connect to afterwards).
        audit = router.audit()
        for name in ("r1", "r2"):
            router.drain_replica(name, grace_s=30)
        res = router.run([(prompts[0], gens[0])], results=True)
        t_rejoin_serve = time.monotonic()
        assert res[0].status == "ok"
        assert res[0].tokens.tolist() == golds[0]
        assert reborn.served >= 1
        return {
            "replicas": REPLICAS,
            "batch_delay_s": BATCH_DELAY_S,
            "fleet_spawn_s": round(spawn_s, 3),
            "inflight_tickets_at_kill": len(tickets),
            "rerouted_tickets": len(rerouted),
            "rerouted_recovered_ok_bit_exact": len(ok_exact),
            "inflight_recovery_rate": (
                round(len(ok_exact) / len(rerouted), 4) if rerouted
                else None
            ),
            "survivors_bit_exact": bool(survivors_exact),
            "detection_s": round(t_dead - t_kill, 4),
            "recovery_s": round(recovery_s, 4),
            "respawn_to_rejoin_s": round(t_rejoin_serve - t_respawn, 4),
            "kill_to_rejoin_s": round(t_rejoin_serve - t_kill, 4),
            "router": {
                k: v for k, v in router.last_stats["router"].items()
                if isinstance(v, (int, float, str))
            },
            "supervisor": sup.stats()["slots"],
            "survivor_audit_problems": audit,
        }
    finally:
        sup.shutdown()


def affinity_arm(policy: str) -> dict:
    from triton_distributed_tpu.serving.router import Router
    from triton_distributed_tpu.serving.supervisor import (
        spawn_replica,
        stub_spec,
    )

    reps = {}

    def boot(i):
        reps[i] = spawn_replica(
            stub_spec(f"r{i}", delay_s=0.0, page_size=PAGE_SIZE,
                      num_pages=256),
            spawn_timeout_s=180.0,
        )

    threads = [threading.Thread(target=boot, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    router = Router([reps[0], reps[1]], policy=policy)
    prompts = build_prompts()
    try:
        ttfts = []
        for p in prompts:
            t0 = time.perf_counter()
            res = router.run([(p, 1)], results=True)
            ttfts.append(time.perf_counter() - t0)
            assert res[0].status == "ok", res[0]
        # Children report their cumulative tree stats in every batch
        # response; after the last arrival the mirrors are current.
        lookups = hits = hit_tokens = 0
        for r in router.replicas:
            st = r.engine.last_stats.get("prefix_cache", {})
            lookups += st.get("lookups", 0)
            hits += st.get("hits", 0)
            hit_tokens += st.get("hit_tokens", 0)
        rstats = router.last_stats["router"]
        return {
            "policy": policy,
            "radix_hit_rate": round(hits / max(lookups, 1), 4),
            "radix_hit_tokens": int(hit_tokens),
            "prefill_tokens_computed": int(
                router.last_stats["prefill_tokens"]
            ),
            "ttft_s_mean": round(float(np.mean(ttfts)), 4),
            "per_replica_served": [r.served for r in router.replicas],
            "router": {
                k: rstats[k]
                for k in ("routed", "affinity_hits",
                          "affinity_hit_tokens", "least_loaded",
                          "round_robin", "reroutes")
            },
        }
    finally:
        router.shutdown()
        for r in reps.values():
            if r.proc is not None and r.proc.poll() is None:
                r.proc.kill()
                r.proc.wait(timeout=10)


def main() -> int:
    t0 = time.time()
    failure = failure_arm()
    aff = affinity_arm("affinity")
    rr = affinity_arm("round_robin")
    in_process_rate = None
    router_json = os.path.join(os.path.dirname(__file__), "ROUTER.json")
    if os.path.exists(router_json):
        with open(router_json) as f:
            in_process_rate = (
                json.load(f).get("affinity", {}).get("radix_hit_rate")
            )
    out = {
        "metric": "process_fleet_failure_recovery",
        "platform": "cpu",
        "failure": failure,
        "affinity": aff,
        "round_robin": rr,
        "affinity_matches_in_process": (
            in_process_rate is not None
            and abs(aff["radix_hit_rate"] - in_process_rate) <= 0.02
        ),
        "in_process_affinity_hit_rate": in_process_rate,
        "bench_wall_s": round(time.time() - t0, 1),
        "provenance": {
            "harness": (
                "perf/fleet_bench.py — 3 stub-engine replica processes "
                "(run_server --model stub; real radix control plane + "
                "wire server) under FleetSupervisor; SIGKILL via the "
                "seeded proc.kill seam mid-batch; stamps from the "
                "shared-monotonic event ring (fault/replica_dead/"
                "replica_respawn) and per-ticket waiter threads; "
                "affinity arms replay perf/router_bench.py's workload "
                "over 2 remote replicas"
            ),
            "caveat": (
                "wall-clock latencies include the stub's synthetic "
                f"{BATCH_DELAY_S}s batch floor (detection waits for "
                "the in-flight batch's socket to die, exactly as with "
                "a real model — subtract the floor for the pure "
                "supervision overhead); radix hit rates are "
                "control-plane-exact and platform-independent"
            ),
        },
    }
    text = json.dumps(out, indent=1)
    print(text)
    path = os.path.join(os.path.dirname(__file__), "FLEET.json")
    with open(path, "w") as f:
        f.write(text + "\n")
    print(f"\nwrote {path}", file=sys.stderr)
    ok = (
        failure["inflight_recovery_rate"] == 1.0
        and failure["survivors_bit_exact"]
        and not failure["survivor_audit_problems"]
        and out["affinity_matches_in_process"]
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
