"""Telemetry overhead bench: <1% decode cost, bit-identical outputs.

ISSUE 5 acceptance artifact: the obs/ subsystem (metrics registry +
event ring + per-request timelines, docs/observability.md) must cost
under 1% of decode-step time when enabled, and with telemetry disabled
the engine's outputs must be bit-identical to the telemetry-enabled
run — the token path never reads telemetry state, this bench proves
it end-to-end.

Measurements, written to ``perf/OBS_OVERHEAD.json``:

1. **Primitive costs** — ns/op for counter.inc, histogram.observe and
   ring.emit, enabled and disabled, from 100k-iteration loops (the
   disabled numbers show the near-no-op claim). These loops
   self-average, so they are stable even on a noisy host.
2. **Decode overhead, attributed** (the headline ``overhead_pct``) —
   the exact telemetry ops a continuous-batching workload performs
   (counted by wrapping the obs entry points; the engine is
   deterministic, so the counts are too) priced at the measured
   enabled per-op cost, over the median decode wall time. This is the
   estimator with sub-0.1% resolution: on this 1-core container,
   wall clock for IDENTICAL back-to-back runs swings up to ~6x
   (.claude/skills/verify/SKILL.md), orders of magnitude above the
   µs-scale cost being measured.
3. **Decode overhead, wall-clock A/B** — the same workload with
   telemetry on vs off, gc-settled, interleaved, median of paired
   deltas (never min — repo benchmarking rule). Reported for honesty
   with its per-pair spread; on this host it is noise-dominated.
4. **Bit-identity** — per-request token streams from the on/off runs
   compared exactly.

CPU-runnable like the other perf harnesses:

    python perf/obs_overhead_bench.py [--reps 3] [--requests 8]
"""

import argparse
import contextlib
import gc
import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_primitives(n: int = 100_000) -> dict:
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import events, metrics

    reg = metrics.Registry(enabled=True)
    ring = events.EventRing(capacity=2048, enabled=True)
    c = reg.counter("bench_total")
    h = reg.histogram("bench_seconds")

    out = {}
    for state in ("enabled", "disabled"):
        reg.enabled = ring.enabled = state == "enabled"
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        t_inc = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(n):
            h.observe(0.001 * (i % 97))
        t_obs = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(n):
            ring.emit("bench", i=i)
        t_emit = time.perf_counter() - t0
        out[state] = {
            "counter_inc_ns": round(t_inc / n * 1e9, 1),
            "histogram_observe_ns": round(t_obs / n * 1e9, 1),
            "ring_emit_ns": round(t_emit / n * 1e9, 1),
        }
    # Leave the process-global switch alone; only local objects used.
    del obs
    return out


def run_once(make_engine, prompts):
    """One workload run on a FRESH engine (prefix reuse across reps
    would skew later reps): (outputs, wall seconds, last_stats).
    gc is collected before and held off during the timed region —
    telemetry's extra allocations otherwise shift WHICH run absorbs a
    gen-2 pass over jax's object graph (tens of ms, lumpy)."""
    eng = make_engine()
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        res = eng.run(prompts)
        dt = time.perf_counter() - t0
    finally:
        gc.enable()
    return [o.tolist() for o in res], dt, dict(eng.last_stats)


@contextlib.contextmanager
def count_obs_ops():
    """Count calls into the obs mutation entry points (bench-local
    wrappers; restored on exit). The workload is deterministic, so one
    counted run gives THE op profile of the workload."""
    from triton_distributed_tpu.obs import events, metrics

    counts = {"counter_inc": 0, "histogram_observe": 0, "gauge_set": 0,
              "ring_emit": 0}
    originals = {
        "counter_inc": metrics.Counter.inc,
        "histogram_observe": metrics.Histogram.observe,
        "gauge_set": metrics.Gauge.set,
        "ring_emit": events.EventRing.emit,
    }

    def wrap(key):
        orig = originals[key]

        def counted(self, *a, **kw):
            counts[key] += 1
            return orig(self, *a, **kw)

        return counted

    metrics.Counter.inc = wrap("counter_inc")
    metrics.Histogram.observe = wrap("histogram_observe")
    metrics.Gauge.set = wrap("gauge_set")
    events.EventRing.emit = wrap("ring_emit")
    try:
        yield counts
    finally:
        metrics.Counter.inc = originals["counter_inc"]
        metrics.Histogram.observe = originals["histogram_observe"]
        metrics.Gauge.set = originals["gauge_set"]
        events.EventRing.emit = originals["ring_emit"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--gen-len", type=int, default=24)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "OBS_OVERHEAD.json"
    ))
    args = p.parse_args(argv)

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from triton_distributed_tpu import obs
    from triton_distributed_tpu.models import AutoLLM, ContinuousEngine
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    prims = bench_primitives()

    ctx = initialize_distributed(tp=4, devices=jax.devices()[:4])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)

    rng = np.random.default_rng(0)
    shared = rng.integers(1, 200, size=12).tolist()
    prompts = [
        (shared + rng.integers(1, 200, size=4 + i % 5).tolist(),
         args.gen_len)
        for i in range(args.requests)
    ]

    def make_engine():
        return ContinuousEngine(
            model, max_batch=4, page_size=16, max_length=128,
            prefix_cache=True, prefill_chunk=16,
        )

    # Warm the jit caches once so rep 1 is not a compile measurement.
    make_engine().run(prompts[:2])

    # One counted enabled run: the workload's exact telemetry op
    # profile (deterministic engine => deterministic counts).
    obs.set_enabled(True)
    with count_obs_ops() as op_counts:
        outs_counted, _, stats_on = run_once(make_engine, prompts)

    # Interleaved on/off pairs, median of wall times and of paired
    # deltas (never min — repo benchmarking rule, see module docstring).
    outs_on = outs_off = None
    walls = {True: [], False: []}
    pair_deltas = []
    for rep in range(args.reps):
        order = ((True, False) if rep % 2 == 0 else (False, True))
        for enabled in order:
            obs.set_enabled(enabled)
            outs, dt, _ = run_once(make_engine, prompts)
            walls[enabled].append(dt)
            if enabled:
                outs_on = outs
            else:
                outs_off = outs
    obs.set_enabled(True)
    for t_on_i, t_off_i in zip(walls[True], walls[False]):
        pair_deltas.append((t_on_i - t_off_i) / t_off_i * 100.0)

    t_on = statistics.median(walls[True])
    t_off = statistics.median(walls[False])
    decode_steps = stats_on["decode_steps"]

    # Attributed overhead: ops x measured enabled per-op cost over the
    # median decode wall. Gauge.set does the same key+lock+store work
    # as a counter inc; timeline stamps (one monotonic read + attr
    # store per lifecycle edge) are priced at counter cost too, via
    # 5 stamps per request.
    en = prims["enabled"]
    obs_ns = (
        (op_counts["counter_inc"] + op_counts["gauge_set"]
         + 5 * args.requests) * en["counter_inc_ns"]
        + op_counts["histogram_observe"] * en["histogram_observe_ns"]
        + op_counts["ring_emit"] * en["ring_emit_ns"]
    )
    overhead_pct = obs_ns * 1e-9 / t_off * 100.0
    wall_delta_pct = statistics.median(pair_deltas)

    bit_identical = outs_on == outs_off == outs_counted

    report = {
        "bench": "obs_overhead",
        "workload": {
            "requests": args.requests,
            "gen_len": args.gen_len,
            "decode_steps": decode_steps,
            "reps": args.reps,
            "platform": jax.devices()[0].platform,
        },
        "primitive_ns_per_op": prims,
        "obs_op_counts": dict(op_counts),
        "obs_total_ms": round(obs_ns * 1e-6, 4),
        "decode_wall_s": {"enabled_median": round(t_on, 4),
                          "disabled_median": round(t_off, 4)},
        "per_step_ms": {
            "enabled": round(t_on / max(decode_steps, 1) * 1e3, 4),
            "disabled": round(t_off / max(decode_steps, 1) * 1e3, 4),
        },
        "overhead_pct": round(overhead_pct, 4),
        "overhead_under_1pct": overhead_pct < 1.0,
        "walltime_ab": {
            "median_paired_delta_pct": round(wall_delta_pct, 3),
            "paired_deltas_pct": [round(d, 2) for d in pair_deltas],
            "note": ("noise-dominated on this host: identical "
                     "back-to-back runs swing far beyond the µs-scale "
                     "telemetry cost; overhead_pct above is the "
                     "op-attributed estimator"),
        },
        "outputs_bit_identical": bit_identical,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    if not bit_identical:
        print("FAIL: outputs differ between telemetry on/off",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
