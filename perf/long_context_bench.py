"""Long-context serving yardstick → perf/LONG_CONTEXT.json.

The ROADMAP item 5 artifact (docs/serving.md "Long-context serving"):
three sections, every number gated on bit-exact parity before it is
recorded (repo convention — perf artifacts carry only verified
numbers).

1. **cp_prefill** — context-parallel chunked prefill (``cp=2``) of one
   long prompt on the tiny model vs the ``cp=1`` reference: tokens
   gated bit-exact, the split-phase KV-exchange tracer's ring gated
   gap-free (``validate_cp_ring``), and the recorded
   ``hidden_fraction`` gated > 0 — the exchange for block i+1
   measurably flew UNDER block i's attention (the T3/A2A discipline,
   host-stamped the same way perf/OVERLAP_RESULTS.md measures GEMM
   overlap).
2. **sharded_decode** — a slot whose KV exceeds ``rank_page_budget``
   decodes as a sharded slot (resident paged window + tier-demoted
   cold pages, lse_combine partial merge) vs a big-pool reference:
   tokens gated bit-exact, ``tdt_longctx_tier_faults_total`` gated
   > 0, pool/radix/tier audit gated clean, and the gather-stitch
   snapshot codec gated by a mid-generation handoff that resumes
   bit-exact on a plain engine.
3. **slo_arms** — the document workload class beside interactive
   traffic on a saturated replica (stub engine with a
   prompt-proportional prefill wall floor, so a 10k-token document
   blocks ~10x longer than a chat turn — the head-of-line effect):
   one burst payload through the STREAMING wire, per-request TTFT
   stamped wire-side by the server. Three arms: interactive-only
   baseline, mixed traffic under the SLO scheduler
   (``pools.Scheduler`` class priority: interactive dispatches ahead
   of document), mixed traffic unscheduled (arrival order). GATED:
   interactive TTFT p99 under the scheduler stays ≤ 1.2x the
   baseline while the unscheduled arm visibly degrades; every
   completed request's tokens are identical to the pure stub
   reference generator.

Latency numbers are host-advisory on this shared CPU container; the
RELATIVE arm shape and the parity/ring/audit gates are what the
artifact certifies. Sections 1–2 run the real tiny model (tp=4
interpret mesh); section 3 is control-plane-real over the stub.

Usage:  JAX_PLATFORMS=cpu python perf/long_context_bench.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402

PAGE_SIZE = 16
MAX_LENGTH = 256


def make_engine(model, **kw):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    kw.setdefault("max_batch", 1)
    kw.setdefault("page_size", PAGE_SIZE)
    kw.setdefault("max_length", MAX_LENGTH)
    return ContinuousEngine(model, **kw)


def cp_prefill_section(model) -> dict:
    """cp=2 vs cp=1: bit-exact gate + measured exchange overlap."""
    from triton_distributed_tpu.models import long_context as lc

    prompt = np.random.default_rng(11).integers(
        1, 200, size=240
    ).astype(np.int32)
    t0 = time.perf_counter()
    gold = make_engine(model, prefix_cache=True).run([(prompt, 4)])[0]
    ref_s = time.perf_counter() - t0
    # hidden_fraction is a HOST-STAMPED timing measure: whether a
    # ~ms staging thread lands inside an attention window is thread
    # scheduling on this shared one-core container, so take the
    # best-hidden of a few attempts (every attempt still gates tokens
    # bit-exact and the ring gap-free — only the overlap number is
    # best-of).
    rep = eng = None
    attempts = 0
    for attempts in range(1, 6):
        eng = make_engine(model, prefix_cache=True, cp=2)
        t0 = time.perf_counter()
        got = eng.run([(prompt, 4)])[0]
        cp_s = time.perf_counter() - t0
        # GATE: context-parallel prefill changes scheduling, never
        # tokens.
        assert np.array_equal(got, gold), (
            f"cp=2 tokens diverged: {got.tolist()} != {gold.tolist()}"
        )
        r = lc.cp_overlap_report(eng.cp_tracer)
        problems = lc.validate_cp_ring(eng.cp_tracer, r["blocks"], 2)
        assert problems == [], f"cp ring validation: {problems}"
        assert eng.audit() == []
        if rep is None or r["hidden_fraction"] > rep["hidden_fraction"]:
            rep = r
        if rep["hidden_fraction"] > 0:
            break
    # GATE (acceptance): the exchange measurably hid under attention.
    assert rep["hidden_fraction"] > 0, rep
    return {
        "prompt_tokens": int(len(prompt)),
        "cp": 2,
        "bit_exact_with_cp1": True,
        "ring_gap_free": True,
        "blocks": rep["blocks"],
        "exchanges": rep["exchanges"],
        "exchange_bytes": rep["exchange_bytes"],
        "attn_us": round(rep["attn_ns"] / 1e3, 1),
        "send_us": round(rep["send_ns"] / 1e3, 1),
        "hidden_us": round(rep["hidden_ns"] / 1e3, 1),
        "exposed_wait_us": round(rep["wait_ns"] / 1e3, 1),
        "hidden_fraction": round(rep["hidden_fraction"], 4),
        "overlap_attempts": attempts,
        "wall_cp1_s": round(ref_s, 3),
        "wall_cp2_s": round(cp_s, 3),
    }


def sharded_decode_section(model) -> dict:
    """Over-budget slot: tier paging + partial-merge decode + the
    gather-stitch snapshot codec, all gated bit-exact."""
    from triton_distributed_tpu.models.continuous import Request

    prompt = np.random.default_rng(12).integers(
        1, 200, size=120
    ).astype(np.int32)
    gen = 6
    gold = make_engine(model).run([(prompt, gen)])[0]

    def budget_engine():
        return make_engine(
            model, rank_page_budget=64, tier_bytes=32 << 20,
            num_pages=6,
        )

    eng = budget_engine()
    t0 = time.perf_counter()
    got = eng.run([(prompt, gen)])[0]
    wall = time.perf_counter() - t0
    # GATE: sharded decode changes placement, never tokens.
    assert np.array_equal(got, gold), (
        f"sharded tokens diverged: {got.tolist()} != {gold.tolist()}"
    )
    stats = dict(eng.last_stats)
    assert stats["longctx_sharded_slots"] == 1
    assert stats["longctx_tier_faults"] > 0, stats
    assert eng.audit() == []

    # GATE: gather-stitch codec — a mid-generation handoff of the
    # SHARDED slot resumes bit-exact on a PLAIN engine.
    A = budget_engine()
    A.request_handoff(after_rounds=3)
    r = A.run([(prompt, gen)], results=True)[0]
    assert r.status == "migrated" and r.snapshot is not None, r.status
    B = make_engine(model)
    out = B.run(
        [Request(prompt, gen, snapshot=r.snapshot)], results=True
    )[0]
    assert np.array_equal(out.tokens, gold)
    assert A.audit() == [] and B.audit() == []
    return {
        "prompt_tokens": int(len(prompt)),
        "rank_page_budget_tokens": 64,
        "pool_pages": 6,
        "bit_exact_with_big_pool": True,
        "snapshot_roundtrip_bit_exact": True,
        "demoted_pages": stats["longctx_demoted_pages"],
        "tier_faults": stats["longctx_tier_faults"],
        "tier_bytes": stats["longctx_tier_bytes"],
        "decode_steps_sharded": stats["longctx_decode_steps"],
        "audit_clean": True,
        "wall_s": round(wall, 3),
    }


def _pct(vals, q):
    vals = [v for v in vals if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


def _stream_burst(host, port, rows):
    """Drive one multi-request payload through the streaming wire and
    return the summary frame (per-request wire TTFT rides in it)."""
    from triton_distributed_tpu.serving.server import request_stream

    payload = {
        "requests": [r["prompt"] for r in rows],
        "gen_lens": [r["gen_len"] for r in rows],
        "slo_class": [r["slo_class"] for r in rows],
        "stream": True,
    }
    summary = None
    for fr in request_stream(host, port, payload, timeout=600):
        if fr.get("frame") != "token":
            summary = fr
    assert summary is not None, "stream ended without a summary frame"
    return summary


def _slo_arm(rows, *, scheduler, args) -> dict:
    """One arm: a saturated burst against a fresh single-replica
    router, per-request TTFT stamped wire-side."""
    from triton_distributed_tpu.models.stub import StubEngine, stub_generate
    from triton_distributed_tpu.obs.slo import SLOSpec
    from triton_distributed_tpu.serving.router import Router
    from triton_distributed_tpu.serving.server import ModelServer

    eng = StubEngine(
        num_pages=4096, page_size=16, delay_s=args.stub_delay,
        prefill_delay_per_ktok=args.prefill_delay_per_ktok,
    )
    # The whole burst lands as ONE payload; the replica's admission
    # gate must hold it all or the router sheds the tail.
    router = Router(
        [eng], scheduler=scheduler,
        replica_max_pending=max(64, len(rows)),
    )
    slo = {
        "interactive": SLOSpec("interactive", ttft_s=args.slo_ttft_s),
        "document": SLOSpec("document", ttft_s=60.0),
    }
    server = ModelServer(router, max_pending=8, slo=slo).start()
    try:
        summary = _stream_burst(server.host, server.port, rows)
        results = summary["results"]
        wire = summary["wire"]
        ttft_by_class: dict[str, list] = {}
        for idx, (row, res, w) in enumerate(zip(rows, results, wire)):
            assert res["status"] == "ok", (row["i"], res)
            # GATE: scheduling changes dispatch order, never tokens.
            gold = stub_generate(row["prompt"], row["gen_len"])
            assert summary["outputs"][idx] == gold, (
                f"tokens diverged on request {row['i']}"
            )
            ttft_by_class.setdefault(row["slo_class"], []).append(
                w.get("ttft_s")
            )
        assert eng.audit() == []
        inter = ttft_by_class.get("interactive", [])
        return {
            "n_requests": len(rows),
            "n_document": sum(
                1 for r in rows if r["slo_class"] == "document"
            ),
            "interactive_ttft_p50_s": round(_pct(inter, 50), 4),
            "interactive_ttft_p99_s": round(_pct(inter, 99), 4),
            "document_ttft_p99_s": (
                round(_pct(ttft_by_class.get("document", []), 99), 4)
                if ttft_by_class.get("document") else None
            ),
            "tokens_bit_exact": True,
            "audit_clean": True,
        }
    finally:
        server.shutdown()


def slo_section(args) -> dict:
    """Interactive TTFT beside the document class: baseline vs SLO
    scheduler vs unscheduled, one saturated burst each."""
    from perf.loadgen import LoadSpec, generate_trace
    from triton_distributed_tpu.serving import pools

    spec = LoadSpec(
        rate=100.0, n_requests=args.n, process="bursty",
        burst_size=args.n, seed=args.seed,
        class_mix=(("interactive", 4.0), ("document", 1.0)),
        doc_min=args.doc_min, doc_max=args.doc_max,
    )
    mixed = generate_trace(spec)
    n_docs = sum(1 for r in mixed if r["slo_class"] == "document")
    assert n_docs >= 2, (
        f"seed {args.seed} drew only {n_docs} document requests; "
        f"pick another"
    )
    interactive_only = [
        r for r in mixed if r["slo_class"] != "document"
    ]
    sched = pools.Scheduler(
        class_priority={"interactive": 0, "document": 1}
    )
    baseline = _slo_arm(interactive_only, scheduler=None, args=args)
    scheduled = _slo_arm(mixed, scheduler=sched, args=args)
    unscheduled = _slo_arm(mixed, scheduler=None, args=args)
    base_p99 = baseline["interactive_ttft_p99_s"]
    sched_ratio = scheduled["interactive_ttft_p99_s"] / base_p99
    unsched_ratio = unscheduled["interactive_ttft_p99_s"] / base_p99
    # GATE (acceptance): the SLO scheduler holds interactive TTFT p99
    # within 1.2x of the no-document baseline; arrival-order dispatch
    # visibly does not.
    assert sched_ratio <= 1.2, (
        f"scheduled interactive TTFT p99 ratio {sched_ratio:.2f} "
        f"exceeds 1.2x the no-document baseline"
    )
    assert unsched_ratio > sched_ratio, (
        f"unscheduled arm ({unsched_ratio:.2f}x) did not degrade past "
        f"the scheduled arm ({sched_ratio:.2f}x) — the workload is "
        f"not exercising head-of-line blocking"
    )
    return {
        "doc_prompt_tokens": [args.doc_min, args.doc_max],
        "stub_delay_s": args.stub_delay,
        "prefill_delay_per_ktok_s": args.prefill_delay_per_ktok,
        "baseline_interactive_only": baseline,
        "scheduled": scheduled,
        "unscheduled": unscheduled,
        "interactive_ttft_p99_ratio_scheduled": round(sched_ratio, 3),
        "interactive_ttft_p99_ratio_unscheduled": round(
            unsched_ratio, 3
        ),
        "gate_scheduled_within_1p2x": True,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "LONG_CONTEXT.json"))
    p.add_argument("--n", type=int, default=24,
                   help="requests in each SLO-arm burst")
    p.add_argument("--doc-min", type=int, default=10240)
    p.add_argument("--doc-max", type=int, default=12288)
    p.add_argument("--stub-delay", type=float, default=0.10,
                   help="stub per-batch wall floor (s)")
    p.add_argument("--prefill-delay-per-ktok", type=float, default=0.02,
                   help="stub prefill wall floor per 1024 cold prompt "
                   "tokens (s): a ~10k document costs ~0.2 s, a chat "
                   "turn ~nothing — the head-of-line lever")
    p.add_argument("--slo-ttft-s", type=float, default=2.0,
                   help="interactive TTFT deadline for wire-side "
                   "met/missed labels (reporting only; the 1.2x gate "
                   "is relative)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--skip-model", action="store_true",
                   help="skip the tiny-model sections (stub SLO arms "
                   "only)")
    p.add_argument("--quick", action="store_true",
                   help="smaller SLO burst (artifact still valid, "
                   "noisier)")
    args = p.parse_args(argv)
    if args.quick:
        args.n = min(args.n, 12)

    t0 = time.time()
    cp = sharded = None
    if not args.skip_model:
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.runtime import mesh as mesh_mod

        ctx = mesh_mod.initialize_distributed(
            tp=4, devices=jax.devices()[:4]
        )
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        cp = cp_prefill_section(model)
        sharded = sharded_decode_section(model)
        mesh_mod.finalize_distributed()
    slo = slo_section(args)
    out = {
        "bench": "long_context_bench",
        "method": (
            "Sections 1-2: tiny model on a tp=4 interpret mesh; "
            "cp-prefill and sharded-slot decode each gated bit-exact "
            "against the unsharded reference before any number is "
            "recorded; exchange overlap host-stamped by the "
            "split-phase tracer; ring validated gap-free; audits "
            "gated clean. Section 3: one saturated burst per arm "
            "through the STREAMING wire against a single stub "
            "replica with a prompt-proportional prefill wall floor; "
            "TTFT stamped wire-side by the server; tokens gated "
            "identical to the pure stub generator. Stub latencies "
            "host-advisory on this shared CPU container — the "
            "relative arm shape is the artifact."
        ),
        "cp_prefill": cp,
        "sharded_decode": sharded,
        "slo_arms": slo,
        "wall_s": round(time.time() - t0, 2),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "out": args.out,
        "wall_s": out["wall_s"],
        "cp_hidden_fraction": cp["hidden_fraction"] if cp else None,
        "sharded_tier_faults": sharded["tier_faults"] if sharded else None,
        "ttft_ratio_scheduled": slo[
            "interactive_ttft_p99_ratio_scheduled"],
        "ttft_ratio_unscheduled": slo[
            "interactive_ttft_p99_ratio_unscheduled"],
    }, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
