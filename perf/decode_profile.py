"""Per-component decode-step profile — locate the ms/step gap on chip.

The round-3 ladder measured (v5e, chained 32-step jit):
jit 5.10 / pallas 5.43 / mega 4.31 / mega_multi 4.27 ms/step, vs the
~1.8 ms HBM floor (1.19 GB bf16 weights at the probe-measured
667 GB/s). This harness times each weight-streaming component of the
Qwen3-0.6B decode step IN ISOLATION (chained in one fori_loop with a
data dependency so per-launch relay tax amortizes and XLA cannot CSE
the iterations), yielding achieved GB/s per matvec shape. The sum of
component floors vs the measured full-step rungs splits the gap into
"shape-level inefficiency" (XLA/Mosaic matvec quality per weight
matrix) vs "step-level overhead" (everything between the matmuls:
norms, rope, attention, collectives, scheduling).

Decode analog of the reference's per-op perf models
(``kernels/nvidia/gemm_perf_model.py:247`` — analytic floors used to
explain measured ladders, ``docs/mega_triton_kernel.md:27-37``).

Usage: python perf/decode_profile.py [--steps 64] [--out -]
"""

import argparse
import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Qwen3-0.6B geometry (models/config.py PRESETS) at tp=1.
D, F, HQ, HKV, HD, L = 1024, 3072, 16, 8, 128, 28
# lm_head width at tp=1: 151936 is already 128-aligned, so _pad_lm_head
# (models/qwen.py) adds nothing. (tp>1 pads to a 128·tp multiple —
# recompute, don't reuse this constant, for a tp>1 profile.)
V_PAD = 151936

COMPONENTS = {
    # name: (d_in, d_out, per-layer count)
    "qkv": (D, HQ * HD + 2 * HKV * HD, L),
    "o_proj": (HQ * HD, D, L),
    "mlp_in": (D, 2 * F, L),   # fused gate+up
    "mlp_down": (F, D, L),
    "lm_head": (D, V_PAD, 1),
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=64,
                   help="chained iterations per component")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--out", default="-")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (smoke only; the env's "
                        "sitecustomize pins the TPU plugin, which HANGS "
                        "during a relay outage — env vars are ignored, "
                        "only jax.config reaches it in time)")
    args = p.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from triton_distributed_tpu.runtime.utils import median_time

    out = sys.stdout if args.out == "-" else open(args.out, "a")

    def emit(rec):
        out.write(json.dumps(rec) + "\n")
        out.flush()

    platform = jax.devices()[0].platform
    emit({"profile": "decode_components",
          "device": jax.devices()[0].device_kind, "platform": platform,
          "steps": args.steps, "batch": args.batch})

    # Fixed per-execution cost (relay round-trip + dispatch + fetch):
    # timed on a trivial program. Every "total/steps" number in the
    # round carries RT/steps of this on top of true device time; the
    # slope timings below subtract it out instead.
    triv = jax.jit(lambda x: x + 1)
    x8 = jnp.zeros((8, 128))
    np.asarray(triv(x8))
    rt = median_time(lambda: np.asarray(triv(x8)))
    emit({"component": "fixed_dispatch_roundtrip", "ms": round(rt * 1e3, 3)})

    # Device-side init: bulk host->device transfers over the axon relay
    # are slow and have wedged it (round-3 session notes; same reason
    # Qwen3._set_params_jit exists).
    key = jax.random.PRNGKey(0)

    def timed_matvec(d_in, d_out):
        w = jax.jit(
            lambda k: jax.random.normal(k, (d_in, d_out), jnp.bfloat16) * 0.02
        )(key)
        x0 = jax.jit(
            lambda k: jax.random.normal(k, (args.batch, d_in), jnp.bfloat16)
        )(key)
        jax.block_until_ready((w, x0))

        @functools.partial(jax.jit, static_argnums=2)
        def chain(x, w, steps):
            def body(_, x):
                y = jnp.dot(x, w, preferred_element_type=jnp.float32)
                # Data dependency: next x depends on the FULL product
                # (sum fences every output column) but stays d_in-wide.
                return x + (jnp.sum(y) * jnp.bfloat16(1e-8)).astype(x.dtype)

            return jax.lax.fori_loop(0, steps, body, x)

        # Slope timing: (T(2s) - T(s)) / s cancels the fixed dispatch
        # round-trip that total/steps folds in.
        t1 = median_time(lambda: np.asarray(chain(x0, w, args.steps)))
        t2 = median_time(lambda: np.asarray(chain(x0, w, 2 * args.steps)))
        sec = (t2 - t1) / args.steps
        # Relay noise can push the slope to ~0 or negative; flag it
        # rather than report absurd bandwidth.
        return sec, int(w.size * 2), sec * args.steps < 0.2 * t1

    total_floor_ms = 0.0
    any_noisy = False
    for name, (d_in, d_out, count) in COMPONENTS.items():
        sec, wbytes, noisy = timed_matvec(d_in, d_out)
        ms_step = max(sec, 0.0) * 1e3 * count
        total_floor_ms += ms_step
        rec = {"component": name, "shape": [d_in, d_out], "count": count,
               "ms_per_call": round(sec * 1e3, 4),
               "achieved_gbs": (None if noisy or sec <= 0
                                else round(wbytes / sec / 1e9, 1)),
               "ms_per_step_total": round(ms_step, 4)}
        if noisy:
            any_noisy = True
            rec["unreliable"] = "slope < 20% of base time — relay noise"
        emit(rec)

    # Attention component (the one non-matvec weight-class cost in the
    # step): flash-decode over the 0.6B ctx=512 cache, slope-timed the
    # same way — completes the floor split (norms/rope are VPU-bound
    # and fold into whatever they fuse with).
    from triton_distributed_tpu.ops.attention.flash_decode import (
        flash_decode,
    )

    S = 512
    q0 = jax.jit(lambda k: jax.random.normal(
        k, (args.batch, HQ, HD), jnp.bfloat16))(key)
    kc = jax.jit(lambda k: jax.random.normal(
        k, (args.batch, HKV, S, HD), jnp.bfloat16))(key)
    vc = jax.jit(lambda k: jax.random.normal(
        k, (args.batch, HKV, S, HD), jnp.bfloat16))(key)
    klen = jnp.full((args.batch,), S, jnp.int32)
    jax.block_until_ready((q0, kc, vc))

    @functools.partial(jax.jit, static_argnums=4)
    def attn_chain(q, kc, vc, kl, steps):
        def body(_, q):
            o = flash_decode(q, kc, vc, kl)
            return q + (jnp.sum(o) * jnp.bfloat16(1e-8)).astype(q.dtype)

        return jax.lax.fori_loop(0, steps, body, q)

    ta1 = median_time(
        lambda: np.asarray(attn_chain(q0, kc, vc, klen, args.steps)))
    ta2 = median_time(
        lambda: np.asarray(attn_chain(q0, kc, vc, klen, 2 * args.steps)))
    a_sec = (ta2 - ta1) / args.steps
    attn_bytes = int(kc.size + vc.size) * 2  # K+V read once per step
    a_ms_step = max(a_sec, 0.0) * 1e3 * L
    a_noisy = a_sec * args.steps < 0.2 * ta1
    rec = {"component": "attention", "shape": [HQ, HKV, S, HD],
           "count": L,
           "ms_per_call": round(a_sec * 1e3, 4),
           # Same convention as the matvec records: a noise-dominated
           # slope must not report an absurd bandwidth.
           "achieved_gbs": (None if a_noisy or a_sec <= 0
                            else round(attn_bytes / a_sec / 1e9, 1)),
           "ms_per_step_total": round(a_ms_step, 4)}
    total_floor_ms += a_ms_step
    if a_noisy:
        any_noisy = True
        rec["unreliable"] = "slope < 20% of base time — relay noise"
    emit(rec)

    # Per-grid-iteration overhead of a Pallas kernel: the megakernel
    # dispatches ~200 task iterations per decode step, so N µs/iter is
    # N*0.2 ms/step of pure scheduling. Slope over two grid sizes on a
    # near-empty arbitrary-semantics kernel (same dispatch machinery as
    # the megakernel's task loop, none of its work).
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _tick_kernel(o_ref):
        o_ref[0, 0] = (pl.program_id(0) + 1).astype(jnp.float32)

    def grid_run(t):
        call = pl.pallas_call(
            _tick_kernel,
            grid=(t,),
            out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
            ),
            interpret=platform == "cpu",
        )
        f = jax.jit(call)
        return lambda: np.asarray(f())  # median_time warms up itself

    g1, g2 = (64, 192) if platform == "cpu" else (512, 1536)
    tg1 = median_time(grid_run(g1))
    tg2 = median_time(grid_run(g2))
    rec = {"component": "pallas_grid_iter_overhead",
           "us_per_iter": round((tg2 - tg1) / (g2 - g1) * 1e6, 2),
           "grid_sizes": [g1, g2]}
    # The matvec guard (slope vs base) doesn't transfer here: tg1 is
    # dominated by the fixed dispatch round-trip, not the measured
    # work, so a small TRUE per-iter overhead would trip it every run.
    # Only a non-positive slope is definitely noise; it does not taint
    # the (independent) matvec floor.
    if tg2 <= tg1:
        rec["unreliable"] = "non-positive slope — relay noise"
    emit(rec)

    # HBM stream anchor: one big reduction (pure read bandwidth, no MXU).
    big = jax.jit(
        lambda k: jax.random.normal(k, (64, 1024, 4096), jnp.bfloat16)
    )(key)
    jax.block_until_ready(big)

    @jax.jit
    def stream(x):
        return jnp.sum(x, dtype=jnp.float32)

    sec = median_time(lambda: np.asarray(stream(big)))
    emit({"component": "hbm_stream", "bytes": int(big.size * 2),
          "achieved_gbs": round(big.size * 2 / sec / 1e9, 1)})

    # KV-attention bytes are small at ctx=512 (~30 MB) but the gather +
    # softmax pipeline has fixed cost; time one flash-decode call class.
    summary = {
        "component_floor_ms_per_step": round(total_floor_ms, 3),
        "note": ("floor = sum of isolated matvec + attention times; "
                 "the full-step rungs add norms/rope/feedback/"
                 "scheduling — compare with bench.py ladder"),
    }
    if any_noisy:
        summary["unreliable"] = (
            "one or more component slopes were noise-dominated; "
            "the floor understates — re-run in a quieter window"
        )
    emit({"summary": summary})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
