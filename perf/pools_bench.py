"""Elastic pool control-plane yardstick → perf/POOLS.json.

The capstone artifact of the elastic serving tier (docs/scale-out.md
"Disaggregated pools & autoscaling"): drive BURSTY, mixed-SLO-class
traffic (``perf/loadgen.py`` ``process="bursty"`` + ``class_mix``)
through the streaming wire against two live fleets and compare what a
user sees —

- **static arm**: the PR 13 shape — a fixed 2-replica mixed fleet
  under ``FleetSupervisor``, prefix-affinity routing, no elasticity;
- **elastic arm**: a role-split fleet (1 prefill + 1 decode slot,
  ``policy="pools"`` + the SLO-aware :class:`Scheduler`) with the
  goodput-driven :class:`Autoscaler` resizing each pool live through
  the supervisor's spawn/drain path while the trace replays.

Gates asserted BEFORE any number is recorded (repo convention —
perf artifacts carry only verified numbers):

- every completed streamed request's tokens are IDENTICAL to the
  stub's pure reference generator (both arms, every rate);
- the post-run audits are clean in both arms;
- the elastic fleet took at least one **scale-up** AND at least one
  **lossless scale-down** (the drain handed generation off, nothing
  was killed), and both decisions are visible through the fleet-scope
  events verb (``{"cmd": "events", "scope": "fleet"}``);
- at the sweep's degrading rate (the first rate where the static
  fleet's goodput drops below 1.0), the elastic fleet's goodput is
  STRICTLY higher — the headline claim of the artifact.

A second section times the batched handoff-sweep export
(``models/slot_state.export_slots_batch``: ONE device gather for a
whole sweep) against per-slot serial exports on the real tiny-model
``ContinuousEngine``, gating that the batched snapshots resume
bit-exact before reporting the wall delta.

The serving engines are ``models/stub.py`` (real radix/pool/handoff
control plane, pure hash "model", seeded wall-time floor) so the bench
is CPU-runnable and deterministic in its token outputs; latency
numbers are host-advisory (shared CPU container), but the RELATIVE
goodput shape and the control-plane decisions are what the artifact
certifies. ``--stub-max-batch`` (the stub's decode-slot capacity
model) is what makes saturation REAL: each replica serves at most
``max_batch`` requests per ``--stub-delay`` round, so a fixed fleet
has a hard request/second ceiling and queueing past it lands in
wire-visible first-token latency.

Usage:
    python perf/pools_bench.py [--out perf/POOLS.json] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from perf.goodput_bench import _run_rate  # noqa: E402
from perf.loadgen import LoadSpec  # noqa: E402

CLASS_MIX = (("interactive", 3.0), ("bulk", 1.0))


def _spec(args, rate, seed_off=0, **kw):
    return LoadSpec(rate=rate, n_requests=args.n, process="bursty",
                    burst_size=args.burst, class_mix=CLASS_MIX,
                    seed=args.seed + seed_off, **kw)


def _child(name, args, role="mixed"):
    from triton_distributed_tpu.serving.supervisor import stub_spec

    return stub_spec(name, delay_s=args.stub_delay, num_pages=256,
                     page_size=4, role=role,
                     max_batch=args.stub_max_batch)


def _static_arm(args, slo_spec, rates) -> dict:
    """The PR 13 baseline: fixed mixed fleet, no pools, no scaling."""
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving.server import ModelServer, request
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor([_child(f"r{i}", args)
                           for i in range(args.static_fleet)])
    router = sup.start()
    server = ModelServer(router, max_pending=args.front_pending,
                         slo=slo_spec).start()
    try:
        curve = []
        for rate in rates:
            obs_metrics.default_registry().clear()
            curve.append(_run_rate(server.host, server.port,
                                   _spec(args, rate)))
        problems = request(server.host, server.port,
                           {"cmd": "audit"})["problems"]
        assert problems == [], f"static-arm audit: {problems}"
        return {"replicas": args.static_fleet, "policy": "affinity",
                "rates": curve, "audit_clean": True}
    finally:
        server.shutdown()
        sup.shutdown()


def _elastic_arm(args, slo_spec, rates) -> dict:
    """Role-split pools + live autoscaler, same traffic."""
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving import pools
    from triton_distributed_tpu.serving.autoscaler import Autoscaler
    from triton_distributed_tpu.serving.server import ModelServer, request
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor(
        [_child("p0", args, role="prefill"),
         _child("d0", args, role="decode")],
        policy="pools",
    )
    router = sup.start()
    # SLO-aware admission: interactive ahead of bulk, past-deadline
    # work shed instead of served dead.
    router.scheduler = pools.Scheduler(
        class_priority={"interactive": 0, "bulk": 1})
    server = ModelServer(router, max_pending=args.front_pending,
                         slo=slo_spec).start()
    target_ups = (args.pool_max_prefill - 1) + (args.pool_max_decode - 1)
    scaler = Autoscaler(
        sup, lambda role, name: _child(name, args, role=role),
        pool_bounds={"prefill": (1, args.pool_max_prefill),
                     "decode": (1, args.pool_max_decode)},
        # down_ticks × interval = 20 s of sustained calm before a
        # drain: longer than any one recorded replay, so the fleet
        # only shrinks in the deliberate cool tail below — a
        # mid-sweep flap would hand the top rate a half-grown pool.
        interval_s=0.25, cooldown_s=1.0,
        up_occupancy=0.5, down_occupancy=0.2, down_ticks=80,
        drain_grace_s=60.0,
    )
    scaler.start()
    try:
        # Warm-up: replay the top rate (unrecorded) until the
        # autoscaler has grown both pools to their ceilings — the
        # recorded sweep then measures the SCALED steady state, which
        # is the artifact's claim (elasticity converges; the transient
        # is visible in the autoscale event log, not the curve).
        top = max(rates)
        for i in range(5):
            if scaler.stats["scale_ups"] >= target_ups:
                break
            _run_rate(server.host, server.port,
                      _spec(args, top, seed_off=50 + i))
        assert scaler.stats["scale_ups"] >= target_ups, (
            f"warm-up never reached the pool ceilings: {scaler.stats}"
        )
        curve = []
        for rate in rates:
            obs_metrics.default_registry().clear()
            curve.append(_run_rate(server.host, server.port,
                                   _spec(args, rate)))
        # Cool tail: an idle fleet must shrink back toward the floor
        # (the ≥1-scale-down half of the elasticity gate).
        deadline = time.monotonic() + 60.0
        while (time.monotonic() < deadline
               and scaler.stats["scale_downs"] < 1):
            time.sleep(0.1)
        # GATES: decisions actually happened, the drain was lossless,
        # and everything is visible through the FLEET-scope events
        # verb — the operator's one-stop stream.
        fe = request(server.host, server.port,
                     {"cmd": "events", "scope": "fleet"})
        decisions = [e for e in fe["events"]
                     if e.get("kind") == "autoscale"]
        ups = [e for e in decisions
               if e["fields"].get("action") == "scale_up"]
        downs = [e for e in decisions
                 if e["fields"].get("action") == "scale_down"]
        assert scaler.stats["scale_ups"] >= 1, scaler.stats
        assert scaler.stats["scale_downs"] >= 1, scaler.stats
        assert ups, "no scale_up visible via fleet-scope events"
        assert downs, "no scale_down visible via fleet-scope events"
        assert any(e["fields"].get("drained") for e in downs), (
            "no LOSSLESS scale-down: every drain fell to the timeout "
            f"path: {[e['fields'] for e in downs]}"
        )
        problems = request(server.host, server.port,
                           {"cmd": "audit"})["problems"]
        assert problems == [], f"elastic-arm audit: {problems}"
        pool_view = request(server.host, server.port,
                            {"cmd": "stats"})["stats"]["server"]["pools"]
        return {
            "floor": {"prefill": 1, "decode": 1},
            "pool_max": {"prefill": args.pool_max_prefill,
                         "decode": args.pool_max_decode},
            "policy": "pools",
            "rates": curve,
            "scale_ups": scaler.stats["scale_ups"],
            "scale_downs": scaler.stats["scale_downs"],
            "autoscale_events": [
                {"replica": e.get("replica"), **e["fields"]}
                for e in decisions
                if e["fields"].get("action") in ("scale_up",
                                                 "scale_down")
            ],
            "final_pools": pool_view,
            "audit_clean": True,
        }
    finally:
        scaler.stop()
        server.shutdown()
        sup.shutdown()


def _handoff_export_section(args) -> dict:
    """Batched vs serial handoff-sweep export on the REAL engine: one
    gather per sweep vs one per slot, gated bit-exact on resume."""
    import numpy as np

    import jax

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import (
        ContinuousEngine,
        Request,
    )
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    try:
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        rng = np.random.default_rng(args.seed)
        work = [
            (rng.integers(1, 200, size=int(rng.integers(8, 24)))
             .astype(np.int32), int(rng.integers(8, 16)))
            for _ in range(args.handoff_slots)
        ]

        def engine(**kw):
            return ContinuousEngine(model, max_batch=args.handoff_slots,
                                    page_size=16, prefix_cache=True,
                                    **kw)

        golds = [r.tokens.tolist()
                 for r in engine().run(work, results=True)]
        walls, snaps = {}, {}
        for batched in (True, False):
            eng = engine(handoff_batch=batched)
            eng.request_handoff(after_rounds=3)
            t0 = time.perf_counter()
            res = eng.run(work, results=True)
            walls[batched] = time.perf_counter() - t0
            assert all(r.status == "migrated" for r in res), [
                (r.status, r.reason) for r in res
            ]
            assert eng.audit() == []
            snaps[batched] = [r.snapshot for r in res]
        # GATE: the batched snapshots resume bit-exact.
        resumed = engine().run(
            [Request(p, g, snapshot=s)
             for (p, g), s in zip(work, snaps[True])], results=True)
        for r, g in zip(resumed, golds):
            assert r.status == "ok" and r.tokens.tolist() == g, (
                r.status, r.reason)
        return {
            "slots": args.handoff_slots,
            "batched_sweep_s": round(walls[True], 4),
            "serial_sweep_s": round(walls[False], 4),
            "delta_s": round(walls[False] - walls[True], 4),
            "resume_bit_exact": True,
        }
    finally:
        mesh_mod.finalize_distributed()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "POOLS.json"))
    p.add_argument("--n", type=int, default=32,
                   help="requests per arrival-rate point")
    p.add_argument("--rates", type=float, nargs="+",
                   default=[3.0, 6.0, 16.0],
                   help=">= 3 arrival rates (req/s) to sweep "
                   "(defaults bracket the static arm's ~8 req/s "
                   "capacity: 2 replicas x 2 slots / 0.5 s)")
    p.add_argument("--burst", type=int, default=6,
                   help="bursty-arrival burst size")
    p.add_argument("--stub-delay", type=float, default=0.5,
                   help="stub per-round wall floor (s)")
    p.add_argument("--stub-max-batch", type=int, default=2,
                   help="stub decode slots per round: with --stub-delay "
                   "this fixes each replica's request/second ceiling "
                   "(defaults: 2/0.5 s = 4 req/s per replica)")
    p.add_argument("--front-pending", type=int, default=64,
                   help="front server admission bound (max_pending)")
    p.add_argument("--static-fleet", type=int, default=2,
                   help="replicas in the static baseline arm")
    p.add_argument("--pool-max-prefill", type=int, default=2,
                   help="autoscaler prefill-pool ceiling (elastic arm)")
    p.add_argument("--pool-max-decode", type=int, default=3,
                   help="autoscaler decode-pool ceiling (elastic arm)")
    p.add_argument("--handoff-slots", type=int, default=4,
                   help="slots in the batched-export timing section")
    p.add_argument("--skip-handoff-section", action="store_true",
                   help="skip the tiny-model export timing (stub arms "
                   "only)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--quick", action="store_true",
                   help="small n for a smoke run (artifact still "
                   "valid, noisier)")
    p.add_argument("--slo-ttft-s", type=float, default=2.5)
    p.add_argument("--slo-tpot-s", type=float, default=0.6)
    p.add_argument("--slo-e2e-s", type=float, default=9.0)
    args = p.parse_args(argv)
    if args.quick:
        args.n = min(args.n, 16)
    if len(args.rates) < 3:
        p.error("need >= 3 arrival rates for the goodput comparison")

    from triton_distributed_tpu.obs.slo import SLOSpec

    # Two-class deployment: interactive (and unlabelled) traffic under
    # the tight spec, bulk at 2× headroom — the shape the Scheduler's
    # class priorities are for.
    slo_spec = {
        "default": SLOSpec("default", ttft_s=args.slo_ttft_s,
                           tpot_s=args.slo_tpot_s, e2e_s=args.slo_e2e_s),
        "interactive": SLOSpec("interactive", ttft_s=args.slo_ttft_s,
                               tpot_s=args.slo_tpot_s,
                               e2e_s=args.slo_e2e_s),
        "bulk": SLOSpec("bulk", ttft_s=2 * args.slo_ttft_s,
                        tpot_s=2 * args.slo_tpot_s,
                        e2e_s=2 * args.slo_e2e_s),
    }

    t0 = time.time()
    static = _static_arm(args, slo_spec, args.rates)
    elastic = _elastic_arm(args, slo_spec, args.rates)
    # THE headline gate: at the first rate where the static fleet
    # degrades, the autoscaled role-split fleet holds STRICTLY higher
    # goodput.
    degrade = None
    for s, e in zip(static["rates"], elastic["rates"]):
        if s["goodput"] is not None and s["goodput"] < 1.0:
            degrade = {"rate_rps": s["rate_rps"],
                       "static_goodput": s["goodput"],
                       "elastic_goodput": e["goodput"]}
            break
    assert degrade is not None, (
        "static arm never degraded — raise --rates or --burst so the "
        "comparison means something: "
        f"{[r['goodput'] for r in static['rates']]}"
    )
    assert degrade["elastic_goodput"] > degrade["static_goodput"], (
        f"elastic fleet did not beat static at the degrading rate: "
        f"{degrade}"
    )
    handoff = (None if args.skip_handoff_section
               else _handoff_export_section(args))
    out = {
        "bench": "pools_bench",
        "method": (
            "bursty mixed-SLO-class loadgen streaming replay against "
            "two live supervised fleets: a static mixed-role baseline "
            "and a role-split prefill/decode fleet resized live by "
            "the goodput-driven autoscaler (spawn on saturation, "
            "lossless handoff drain on calm). Tokens gated identical "
            "to the pure reference generator at every rate in both "
            "arms; audits gated clean; scaling decisions gated "
            "visible through the fleet-scope events verb. Stub "
            "engines: control-plane-real, wall-clock advisory on "
            "this shared CPU host."
        ),
        "slo": {name: s.as_dict() for name, s in slo_spec.items()},
        "class_mix": [list(c) for c in CLASS_MIX],
        "stub_delay_s": args.stub_delay,
        "stub_max_batch": args.stub_max_batch,
        "burst_size": args.burst,
        "n_per_rate": args.n,
        "static": static,
        "elastic": elastic,
        "degrading_rate": degrade,
        "handoff_export": handoff,
        "wall_s": round(time.time() - t0, 2),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(
        {"out": args.out, "wall_s": out["wall_s"],
         "static_goodput": [r["goodput"] for r in static["rates"]],
         "elastic_goodput": [r["goodput"] for r in elastic["rates"]],
         "degrading_rate": degrade,
         "scale_ups": elastic["scale_ups"],
         "scale_downs": elastic["scale_downs"],
         "handoff_export": handoff}, indent=2,
    ))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
