"""Quantized paged KV cache microbench: bytes/token, accuracy, capacity.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model, interpret-mode
kernels). Steady-state decode streams the whole paged pool per step, so
the platform-independent lever is **KV bytes per cached token** — int8
codes + per-page-per-head f32 scales vs the full-width pool — and the
accuracy cost of reading attention through int8. Four quantities land
in ``perf/KV_QUANT.json``:

- ``kv_bytes_per_token`` both arms + the reduction ratio vs the
  measured full-width pool AND vs an arithmetic bf16 pool (the tiny
  test model stores f32; production serves bf16, so the honest
  headline is the bf16 ratio, labeled as arithmetic),
- ``decode_ms_per_step`` both arms (CPU interpret-mode wall-clock —
  advisory only; the chip-level claim is the bytes ratio, decode being
  KV-bandwidth-bound per the decode ladder in docs/RESULTS.md),
- max |Δlogits| and greedy argmax agreement vs full-width under
  teacher forcing (the documented accuracy tolerance),
- capacity head-room: tokens one pool byte holds, int8 vs full-width
  (the factor by which the radix prefix cache's retention and the
  continuous engine's admissible slots grow at fixed HBM).

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/KV_QUANT.json``.

Usage:  JAX_PLATFORMS=cpu python perf/kv_quant_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

BATCH = 2
PROMPT_LEN = 24
PAGE_SIZE = 16
MAX_LENGTH = 128
TEACHER_STEPS = 16
TIMED_STEPS = 8


def build_caches(model, ctx, prompt, kv_dtype):
    """Prefill BATCH rows into a fresh paged pool; returns (first
    logits, cache)."""
    from triton_distributed_tpu.models.paged_kv_cache import (
        init_paged_cache,
        write_prefill,
    )

    cache, _pool = init_paged_cache(
        model.cfg, BATCH, ctx, "tp", max_length=MAX_LENGTH,
        page_size=PAGE_SIZE, kv_dtype=kv_dtype,
    )
    dense1 = model.new_cache(1, MAX_LENGTH)
    logits = []
    for i in range(BATCH):
        lg, dense1 = model.prefill_batched(
            jnp.asarray(prompt[i : i + 1]), dense1, "xla",
            jnp.asarray([PROMPT_LEN], np.int32),
        )
        cache = write_prefill(cache, i, dense1.k, dense1.v, PROMPT_LEN)
        logits.append(lg[0])
    return jnp.stack(logits), cache


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.paged_kv_cache import (
        kv_bytes_per_token,
    )

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 200, size=(BATCH, PROMPT_LEN)).astype(np.int32)

    lf, cache_full = build_caches(model, ctx, prompt, None)
    lq, cache_q = build_caches(model, ctx, prompt, "int8")

    bytes_full = kv_bytes_per_token(cache_full)
    bytes_q = kv_bytes_per_token(cache_q)
    cfg = model.cfg
    # Arithmetic bf16 baseline: production pools store bf16 (2 B/elem);
    # the tiny test model stores f32, which would flatter the ratio.
    bytes_bf16 = float(
        2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 2
    )

    # Teacher-forced accuracy: identical token stream into both caches.
    tok = jnp.argmax(lf, -1).astype(jnp.int32)
    max_dlogits, agree = 0.0, 0
    for _ in range(TEACHER_STEPS):
        lgf, cache_full = model.decode_step(tok, cache_full, "xla")
        lgq, cache_q = model.decode_step(tok, cache_q, "xla")
        max_dlogits = max(max_dlogits, float(jnp.max(jnp.abs(lgf - lgq))))
        agree += int((jnp.argmax(lgf, -1) == jnp.argmax(lgq, -1)).sum())
        tok = jnp.argmax(lgf, -1).astype(jnp.int32)
    agree_frac = agree / (BATCH * TEACHER_STEPS)

    # Decode step time, both arms (programs are warm from the loop
    # above for full-width; warm the int8 program shape too).
    def time_steps(cache):
        nonlocal_tok = jnp.argmax(lf, -1).astype(jnp.int32)
        lg, cache = model.decode_step(nonlocal_tok, cache, "xla")
        jax.block_until_ready(lg)
        t0 = time.perf_counter()
        for _ in range(TIMED_STEPS):
            lg, cache = model.decode_step(nonlocal_tok, cache, "xla")
        jax.block_until_ready(lg)
        return (time.perf_counter() - t0) / TIMED_STEPS * 1e3

    ms_full = time_steps(cache_full)
    ms_q = time_steps(cache_q)

    result = {
        "metric": "kv_quant_bytes_accuracy_capacity",
        "workload": {
            "model": "tiny",
            "batch": BATCH,
            "prompt_len": PROMPT_LEN,
            "page_size": PAGE_SIZE,
            "teacher_forced_steps": TEACHER_STEPS,
        },
        "platform": jax.default_backend(),
        "kv_bytes_per_token": {
            "full_width": bytes_full,
            "int8": bytes_q,
            "bf16_arithmetic": bytes_bf16,
        },
        "reduction_vs_full_width": round(bytes_full / bytes_q, 3),
        "reduction_vs_bf16": round(bytes_bf16 / bytes_q, 3),
        "capacity_headroom": {
            "tokens_per_pool_byte_ratio": round(bytes_full / bytes_q, 3),
            "note": "pages the same HBM holds grow by this factor — the "
            "radix tree retains that many more prefix tokens and the "
            "continuous engine admits proportionally more slots before "
            "shedding",
        },
        "accuracy": {
            "max_abs_dlogits": round(max_dlogits, 5),
            "greedy_argmax_agreement": round(agree_frac, 4),
            "tolerance_documented": "atol 0.25 on logits; flips only "
            "where full-width top1-top2 gap < quant noise "
            "(random-init tiny model has near-uniform logits — real "
            "checkpoints have far larger gaps)",
        },
        "decode_ms_per_step": {
            "full_width": round(ms_full, 2),
            "int8": round(ms_q, 2),
        },
        "provenance": {
            "harness": "perf/kv_quant_bench.py — paged tiny-model decode "
            "with teacher-forced token stream; int8 pool via "
            "init_paged_cache(kv_dtype='int8'), in-kernel dequant "
            "(interpret mode on CPU)",
            "caveat": "CPU wall-clock is interpret-mode-taxed and "
            "advisory (the int8 arm pays dequant FLOPs the interpreter "
            "does not hide); the platform-independent levers are "
            "bytes/token and capacity_headroom — on-chip decode is "
            "KV-bandwidth-bound (docs/RESULTS.md decode ladder), so "
            "the bytes ratio bounds the step-time win",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "KV_QUANT.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
