"""Prefix-cache microbench: TTFT + prefill work avoided, cold vs warm.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model, interpret-mode
kernels): the measured quantity is the serving-path ALGORITHMIC win —
prefill tokens actually computed and time-to-first-token — on a
shared-system-prompt workload, the traffic shape the radix cache exists
for. Wall-clock numbers on CPU are indicative only (interpret-mode tax);
``prefill_work_avoided_frac`` is platform-independent and transfers to
the chip directly (prefill cost grows linear-plus in prefix length).

TTFT is measured exactly: each request is served with ``gen_len=1``, so
``run()`` returns right after admission emits the first token — prefill
plus one sampling step, the part the prefix cache shortens.

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/PREFIX_CACHE.json``.

Usage:  JAX_PLATFORMS=cpu python perf/prefix_cache_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

# Workload shape: one shared system prompt, per-user suffixes.
SYSTEM_PROMPT_TOKENS = 96
USER_SUFFIX_TOKENS = 16
NUM_USERS = 4
PAGE_SIZE = 16
MAX_LENGTH = 256
PREFILL_CHUNK = 32


def serve_arrivals(eng, prompts):
    """Serve each prompt as its own arrival (one ``run()`` per request,
    ``gen_len=1``), timing each and summing the per-run counters."""
    ttfts, prefilled, hits = [], 0, 0
    for p in prompts:
        t0 = time.perf_counter()
        eng.run([(p, 1)])
        ttfts.append(time.perf_counter() - t0)
        st = eng.last_stats
        prefilled += st["prefill_tokens"]
        hits += st["prefix_hit_tokens"]
    return ttfts, prefilled, hits


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    rng = np.random.default_rng(0)
    system = rng.integers(1, 200, size=SYSTEM_PROMPT_TOKENS).astype(np.int32)
    prompts = [
        np.concatenate(
            [system,
             rng.integers(1, 200, size=USER_SUFFIX_TOKENS).astype(np.int32)]
        )
        for _ in range(NUM_USERS)
    ]
    prompt_tokens = sum(len(p) for p in prompts)

    def build(prefix_cache: bool) -> ContinuousEngine:
        return ContinuousEngine(
            model, max_batch=2, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
            prefix_cache=prefix_cache, prefill_chunk=PREFILL_CHUNK,
        )

    # Warmup both arms with the FULL arrival set: chunk programs are
    # keyed on (width, kv-gather bucket), and warm arrivals hit bucket
    # combinations cold arrivals never do — every shape must compile
    # outside the timings (the jit cache lives on the model, so it
    # carries to the timed engines).
    serve_arrivals(build(True), prompts)
    serve_arrivals(build(False), prompts[:1])

    # COLD: no prefix cache — every arrival prefills its full prompt.
    cold_ttfts, cold_prefill, _ = serve_arrivals(build(False), prompts)

    # WARM: arrival 1 seeds the radix tree; 2..N map the shared system
    # prompt's pages and prefill only their user suffix.
    warm = build(True)
    warm_ttfts, warm_prefill, warm_hits = serve_arrivals(warm, prompts)

    avoided = 1.0 - warm_prefill / max(cold_prefill, 1)
    steady_cold = float(np.mean(cold_ttfts[1:]))
    steady_warm = float(np.mean(warm_ttfts[1:]))  # [0] is the cold seed
    result = {
        "metric": "prefix_cache_ttft_and_prefill_work",
        "workload": {
            "system_prompt_tokens": SYSTEM_PROMPT_TOKENS,
            "user_suffix_tokens": USER_SUFFIX_TOKENS,
            "num_users": NUM_USERS,
            "page_size": PAGE_SIZE,
            "prefill_chunk": PREFILL_CHUNK,
        },
        "platform": jax.default_backend(),
        "cold": {
            "ttft_s_mean": round(float(np.mean(cold_ttfts)), 4),
            "ttft_s_steady": round(steady_cold, 4),
            "prefill_tokens": int(cold_prefill),
            "prompt_tokens": int(prompt_tokens),
        },
        "warm": {
            "ttft_s_mean": round(float(np.mean(warm_ttfts)), 4),
            "ttft_s_steady": round(steady_warm, 4),
            "prefill_tokens": int(warm_prefill),
            "hit_tokens": int(warm_hits),
            "cow_pages": int(warm.prefix.stats["cow_pages"]),
            "hit_rate": round(warm.prefix.hit_rate, 3),
            "tree_pages": warm.prefix.node_count,
        },
        "prefill_work_avoided_frac": round(avoided, 4),
        "ttft_speedup_steady": round(steady_cold / max(steady_warm, 1e-9), 3),
        "provenance": {
            "harness": "perf/prefix_cache_bench.py — per-arrival "
            "ContinuousEngine.run(gen_len=1) calls against a persistent "
            "radix tree (tiny model, chunked prefill); ttft_s_steady "
            "drops the first arrival (cold seed / residual compile)",
            "caveat": "CPU wall-clock is interpret-mode-taxed and "
            "advisory; prefill_work_avoided_frac is the "
            "platform-independent lever (prefill cost ∝ prefix length)",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "PREFIX_CACHE.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
