"""Slope-timed plain-GEMM MFU at multiple shapes — explain the 33%.

VERDICT r3 weak #3 / task 3: the round-3 anchored perf model solved
65.2 TF/s effective bf16 from ONE measured north-star GEMM (~33% of the
v5e's ~197 TF/s peak), and that single near-circular point silently
caps every overlap projection. This harness measures ≥3 INDEPENDENT
shapes with slope timing — T(2n)-T(n) over chained, data-dependent
iterations inside one jit — so the relay's fixed per-execution
round-trip cancels, and A/Bs the levers that usually explain a TPU MFU
deficit:

  * accumulation dtype (``preferred_element_type`` f32 vs bf16),
  * ``jax.lax.Precision`` (DEFAULT vs HIGHEST),
  * operand layout (contracting-dim order: ``a @ b`` vs ``(bT.T) @ b``).

Output: one JSON line per (shape, variant) with achieved TF/s and MFU,
plus a summary with the best-variant MFU per shape — either ≥70% MFU
is reachable with some variant (then the perf model and overlap
projections move to THAT configuration), or the deficit reproduces
across shapes/variants and is a platform cap to document.

Methodology matches the reference's analytic-vs-measured GEMM framing
(``kernels/nvidia/gemm_perf_model.py``) and de-circularizes the ≤15%
validation gate (VERDICT r3 task 6) by adding non-anchor points.

Usage: python perf/gemm_mfu.py [--shapes 4096,4096,4096;8192,4096,12288]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (M, K, N) — north-star GEMM+RS anchor, its AG+GEMM mirror, a square
# anchor, two Qwen3-TP decode/prefill shapes (non-anchor points for
# the perf-model validation), and a tall-M shape (does feeding the MXU
# a longer M dimension move the MFU?).
DEFAULT_SHAPES = ("4096,4096,4096;8192,4096,12288;8192,12288,4096;"
                  "2048,2048,8192;512,1024,3072;16384,4096,4096")

_PEAK_TFS = {
    # bf16 dense peak per chip. v5e: 197 TF/s (public spec, also
    # BASELINE.json); v5p: 459; v4: 275; v6e: 918.
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shapes", default=DEFAULT_SHAPES,
                   help="semicolon list of M,K,N")
    p.add_argument("--iters", type=int, default=8,
                   help="chained iterations for the base timing (the "
                        "slope uses iters and 2*iters)")
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--quick", action="store_true",
                   help="first two shapes, DEFAULT-precision variants "
                        "only (short relay windows)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from triton_distributed_tpu.runtime.utils import median_time

    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in _PEAK_TFS.items() if k in kind), 197.0)
    platform = jax.devices()[0].platform
    print(json.dumps({"profile": "gemm_mfu", "device": kind,
                      "platform": platform, "peak_tfs": peak}), flush=True)

    key = jax.random.PRNGKey(0)

    def chained(iters, m, k, n, *, acc, prec, layout):
        """Build a runner: ``iters`` GEMMs chained by a non-foldable
        scalar carry (``jnp.sum(out)`` fences every output element —
        carrying one element lets XLA DCE-slice the GEMM, see
        perf/OVERLAP_RESULTS.md)."""
        # Device-side init: no bulk host->device transfer on the relay.
        a = jax.jit(lambda s: jax.random.normal(
            s, (m, k), jnp.bfloat16) * 0.02)(key)
        if layout == "kt":
            bmat = jax.jit(lambda s: jax.random.normal(
                s, (n, k), jnp.bfloat16) * 0.02)(jax.random.fold_in(key, 1))
        else:
            bmat = jax.jit(lambda s: jax.random.normal(
                s, (k, n), jnp.bfloat16) * 0.02)(jax.random.fold_in(key, 1))
        jax.block_until_ready((a, bmat))

        import functools

        @functools.partial(jax.jit, static_argnums=2)
        def run(a, b, iters):
            def body(_, carry):
                x, s = carry
                bm = b.T if layout == "kt" else b
                out = jnp.dot(x, bm, preferred_element_type=acc,
                              precision=prec)
                s2 = jnp.sum(out, dtype=jnp.float32)
                # Fold the previous sum back into ONE input element so
                # iterations are data-dependent but the operand dtype,
                # shape, and magnitude are unchanged.
                x = x.at[0, 0].add((s2 * 1e-20).astype(x.dtype))
                return x, s + s2

            return jax.lax.fori_loop(
                0, iters, body, (a, jnp.float32(0)))[1]

        return lambda: np.asarray(run(a, bmat, iters))

    shapes = []
    for tok in args.shapes.split(";"):
        m, k, n = (int(v) for v in tok.split(","))
        shapes.append((m, k, n))
    if args.quick:
        shapes = shapes[:2]

    variants = [
        ("f32acc", dict(acc=jnp.float32, prec=None, layout="kn")),
        ("bf16acc", dict(acc=jnp.bfloat16, prec=None, layout="kn")),
        ("f32acc_kt", dict(acc=jnp.float32, prec=None, layout="kt")),
    ]
    if not args.quick:
        variants.append(
            ("highest", dict(acc=jnp.float32,
                             prec=jax.lax.Precision.HIGHEST, layout="kn")))

    best = {}
    for (m, k, n) in shapes:
        flops = 2.0 * m * k * n
        for vname, kw in variants:
            try:
                r1 = chained(args.iters, m, k, n, **kw)
                r2 = chained(2 * args.iters, m, k, n, **kw)
                t1 = median_time(r1, reps=args.reps)
                t2 = median_time(r2, reps=args.reps)
                sec = (t2 - t1) / args.iters
                rec = {"shape": [m, k, n], "variant": vname,
                       "ms": round(sec * 1e3, 3),
                       "base_ms": round(t1 * 1e3, 1)}
                if sec <= 0 or sec * args.iters < 0.1 * t1:
                    rec["unreliable"] = "slope noise-dominated"
                else:
                    tfs = flops / sec / 1e12
                    rec["tfs"] = round(tfs, 1)
                    rec["mfu"] = round(tfs / peak, 3)
                    cur = best.get((m, k, n))
                    if cur is None or tfs > cur[1]:
                        best[(m, k, n)] = (vname, tfs, sec * 1e3)
                print(json.dumps(rec), flush=True)
            except Exception as e:
                print(json.dumps({
                    "shape": [m, k, n], "variant": vname,
                    "error": f"{type(e).__name__}: {e}"[:200],
                }), flush=True)

    summary = {
        "best_mfu_by_shape": {
            f"{m}x{k}x{n}": {"variant": v, "tfs": round(t, 1),
                             "mfu": round(t / peak, 3)}
            for (m, k, n), (v, t, _ms) in best.items()
        },
        "note": ("mfu >= 0.7 for some variant => retune the perf model "
                 "to that variant; a uniform deficit across shapes and "
                 "variants => platform cap, document in "
                 "perf/OVERLAP_RESULTS.md"),
    }
    # Self-contained perf-model validation (VERDICT r3 task 6 / r4
    # next #3): predicted vs best-variant measured per shape, so a
    # window that runs after the session still produces the full
    # de-circularized table on its own. Only meaningful on the chip
    # the anchors describe.
    if platform != "cpu" and best:
        # Best-effort: a post-processing failure (malformed anchors
        # file etc.) must never discard the measurements of a rare
        # relay window — the per-variant loop above catches exceptions
        # for exactly this reason.
        try:
            from triton_distributed_tpu.tools.perf_model import (
                anchored_spec,
                estimate_gemm_time_ms,
                measured_anchors,
            )

            spec, meta = anchored_spec()
            ga = (measured_anchors() or {}).get("gemm_anchor") or {}
            anchor_mkn = (ga.get("m"), ga.get("k"), ga.get("n"))
            validation = {}
            for (m, k, n), (_v, _t, ms) in best.items():
                if not ms or ms <= 0:
                    continue  # only slope-reliable rows reach best
                model_ms = estimate_gemm_time_ms(m, n, k, spec=spec)
                rel = abs(model_ms - ms) / ms
                row = {"measured_ms": round(ms, 3),
                       "model_ms": round(model_ms, 3),
                       "rel_err": round(rel, 3),
                       "within_15pct_gate": rel <= 0.15}
                if (m, k, n) == anchor_mkn:
                    # The shape the model's TF/s was solved from —
                    # listed for reference, excluded from
                    # independent-point counts (stays correct if the
                    # anchors file is retuned to another shape).
                    row["anchor_shape"] = True
                validation[f"{m}x{k}x{n}"] = row
            summary["model_validation"] = {
                "anchored": meta.get("anchored", False),
                "points": validation,
                "note": ("model rates were solved from a "
                         "relay-inclusive anchor; these measurements "
                         "are slope-timed (relay round-trip "
                         "cancelled), so a systematic model-slow bias "
                         "means the anchor absorbed relay tax — "
                         "retune anchors from slope numbers then"),
            }
        except Exception as e:
            summary["model_validation"] = {
                "error": f"{type(e).__name__}: {e}"[:200],
            }
    print(json.dumps({"summary": summary}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
