"""Single-chip proof that the arrival-adaptive AG schedule REACTS.

VERDICT r3 task 7: the adaptive schedule compiled and ran on chip in
round 3, but nothing ever showed the realized order actually diverging
from ring order under a straggler. True multi-rank arrival skew needs
chips we don't have, so this probe VIRTUALIZES it on one chip: N
"chunks" land via local async DMAs whose start times the kernel
staggers on purpose (the straggler chunk's copy is issued only after
half the picks have been made, behind a ``pl.delay`` — the same
device-side delay the straggler fixtures use), while the production
pick logic — ``ops.overlap.ag_gemm.adaptive_pick``, imported, not
copied — selects the next chunk each step and records it.

Expected: ring mode picks chunks in index order regardless of arrival
(the realized order IS 1..N-1); adaptive mode defers the straggler
chunk until after its DMA has been issued (realized position >
straggle-issue step). The divergence between the two recorded orders
is the reaction evidence.

Usage: python perf/adaptive_order_probe.py [--chunks 8] [--rows 256]
"""

import argparse
import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--chunks", type=int, default=8)
    p.add_argument("--rows", type=int, default=256)
    p.add_argument("--straggler", type=int, default=2,
                   help="chunk whose arrival is deferred")
    p.add_argument("--cpu", action="store_true",
                   help="interpret mode (semaphore_read has no "
                        "interpret lowering — ring mode only)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from triton_distributed_tpu.ops.overlap.ag_gemm import adaptive_pick

    n, rows, strag = args.chunks, args.rows, args.straggler
    if not (1 <= strag < n):
        raise SystemExit(f"--straggler must be in [1, {n})")
    # The straggler's DMA is issued after this many picks; with at
    # least one non-straggler chunk still pending at that point the
    # adaptive pick always has a ready alternative beforehand. Clamped
    # to the straggler's ring position: ring mode WAITS on chunk t at
    # step t, so an issue step later than `strag` would deadlock the
    # ring run (wait on a never-started DMA).
    issue_step = min(max(1, (n - 1) // 2), strag)
    chunk_bytes = rows * 128 * 4

    def kernel(x_ref, o_ref, order_ref, recv_sems, done_smem, *, adaptive):
        # Chunk 0 plays "own shard": processed at step 0, like the
        # overlap kernel's zero-latency own-chunk start.
        def init(c, carry):
            done_smem[c] = jnp.where(c == 0, 1, 0)
            return carry

        jax.lax.fori_loop(0, n, init, None)
        order_ref[0] = 0

        def copy(c):
            return pltpu.make_async_copy(
                x_ref.at[c], o_ref.at[c], recv_sems.at[c]
            )

        # Stagger arrivals: every chunk except the straggler starts now.
        for c in range(1, n):
            if c != strag:
                copy(c).start()
        # Let the issued DMAs land so "ready" is observable before the
        # first pick (the arrival skew this probe virtualizes).
        # (pl.delay has no interpret lowering — CPU runs ring-only.)
        if not args.cpu:
            pl.delay(200_000)

        for t in range(1, n):
            if adaptive:
                nxt = adaptive_pick(
                    done_smem, recv_sems, chunk_bytes, jnp.int32(0), n
                )
            else:
                nxt = jnp.int32(t)
            done_smem[nxt] = 1
            order_ref[t] = nxt
            if t == issue_step:
                # The laggard finally sends. (After the pick at this
                # step, so its earliest adaptive position is
                # issue_step+1; ring mode would have committed to it at
                # position `strag` no matter what.)
                if not args.cpu:
                    pl.delay(50_000)
                copy(strag).start()
            pltpu.make_async_copy(
                x_ref.at[nxt], o_ref.at[nxt], recv_sems.at[nxt]
            ).wait()

    def run(adaptive: bool):
        x = jnp.arange(n * rows * 128, dtype=jnp.float32).reshape(
            n, rows, 128
        )
        out, order = pl.pallas_call(
            functools.partial(kernel, adaptive=adaptive),
            out_shape=(
                jax.ShapeDtypeStruct((n, rows, 128), jnp.float32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((n,)),
                pltpu.SMEM((n,), jnp.int32),
            ],
            interpret=args.cpu,
        )(x)
        out, order = np.asarray(out), np.asarray(order)
        # Chunk 0 is never copied (own shard); all others must have
        # landed intact regardless of schedule.
        gold = np.asarray(x)
        if not (out[1:] == gold[1:]).all():
            raise RuntimeError("probe DMA corrupted chunk data")
        return order.tolist()

    ring = run(adaptive=False)
    rec = {
        "chunks": n,
        "straggler_chunk": strag,
        "straggler_issued_after_pick": issue_step,
        "ring_order": ring,
        "platform": jax.devices()[0].platform,
    }
    if args.cpu:
        rec["adaptive_order"] = None
        rec["note"] = ("interpret mode: semaphore_read unsupported; "
                       "ring-order path only (compile/data check)")
    else:
        adap = run(adaptive=True)
        pos = adap.index(strag)
        rec["adaptive_order"] = adap
        rec["straggler_position_ring"] = ring.index(strag)
        rec["straggler_position_adaptive"] = pos
        rec["diverged"] = adap != ring
        rec["straggler_deferred"] = pos > issue_step
        rec["reacts"] = bool(rec["diverged"] and rec["straggler_deferred"])
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
