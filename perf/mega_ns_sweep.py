"""Megakernel launch-width sweep: split per-launch vs per-step cost.

The r3 ladder measured mega (NS=1) 4.31 and mega_multi (NS=8) 4.27
ms/step — nearly equal, which contradicts the r2 working model of a
~2 ms per-LAUNCH dispatch tax (NS=8 should then save ~1.7 ms/step).
This sweep times the SAME 32-step greedy chain at several launch
widths NS and fits

    T(NS) = fixed + (32/NS) * per_launch + 32 * per_step

by least squares over the (32/NS, 32) design — separating what wider
launches can still amortize (per_launch) from the kernel's own
per-step time (per_step), which only kernel-body tuning can move.
All widths must produce token-identical chains (the multi-step
cross-check, widened to every NS) — a mismatch invalidates the fit.

Usage: python perf/mega_ns_sweep.py [--ns 1,4,8,16] [--steps 32]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ns", default="1,4,8,16")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--model", default="Qwen/Qwen3-0.6B")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed
    from triton_distributed_tpu.runtime.utils import median_time

    widths = [int(x) for x in args.ns.split(",")]
    steps = args.steps
    bad = [w for w in widths if steps % w]
    if bad:
        raise SystemExit(f"--ns {bad} must divide --steps {steps}")

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained(args.model, ctx=ctx, max_length=1024)
    jax.block_until_ready(model.params)

    from perf._chain import (
        multi_step_chain,
        prepare_decode_state,
        single_step_chain,
    )

    tok0, cache0, s_max = prepare_decode_state(model)

    from triton_distributed_tpu.megakernel import MegaQwen3

    mega = MegaQwen3(model)

    results = []
    chains = {}
    for ns in widths:
        if ns == 1:
            once = single_step_chain(
                mega.decode_fn(1, s_max), model.params, tok0, cache0, steps
            )
        else:
            once = multi_step_chain(
                mega.decode_multi_fn(1, s_max, ns), ns,
                model.params, tok0, cache0, steps,
            )

        chains[ns] = once()  # warm + token chain
        sec = median_time(lambda: once())
        results.append({"ns": ns, "ms_total": round(sec * 1e3, 2),
                        "ms_per_step": round(sec / steps * 1e3, 3)})
        print(json.dumps(results[-1]), flush=True)

    ref = chains[widths[0]]
    tokens_match = all(bool((chains[w] == ref).all()) for w in widths)
    # (The chain runners mirror bench.py's mega/mega_multi cross-check
    # runners on purpose — the sweep must time exactly what the ladder
    # times; token equality across widths re-checks that here.)

    # Least-squares fit: T = fixed + launches*per_launch + steps*per_step.
    # With steps fixed, per_step and fixed are not separable — fold them
    # (reported per_step includes fixed/32, small for chained runs).
    A = np.array([[steps / w, 1.0] for w in widths])
    y = np.array([r["ms_total"] for r in results])
    (per_launch, rest), *_ = np.linalg.lstsq(A, y, rcond=None)
    print(json.dumps({
        "platform": jax.devices()[0].platform,
        "tokens_match_across_ns": tokens_match,
        "fit_ms_per_launch": round(float(per_launch), 3),
        "fit_ms_per_step_incl_fixed": round(float(rest) / steps, 3),
        "note": ("per_launch = amortizable by wider NS; "
                 "per_step = kernel-body time, tune the kernel to move it"),
    }))
    # A mismatch means some width mis-executes — the timings then
    # compare different computations and the fit is invalid (docstring
    # contract); fail the step so the on-chip log can't record a green
    # run around an invalid fit.
    return 0 if tokens_match else 1


if __name__ == "__main__":
    raise SystemExit(main())
