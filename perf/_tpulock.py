"""Advisory single-holder lock for the (one) TPU chip.

Two measurement drivers exist — the driver's end-of-round ``bench.py``
and the relay watcher's ``perf/onchip_session.py`` queue. If both touch
the chip at once, both measurements degrade (shared relay, shared HBM).
This flock serializes them. ADVISORY with a proceed-anyway timeout:
the driver's bench must never deadlock behind a wedged queue step, so
after ``timeout_s`` the caller proceeds without the lock (logged).
"""

import fcntl
import os
import time

LOCK_PATH = os.environ.get("TDT_TPU_LOCK", "/tmp/tdt_tpu.lock")
# Set in the environment of child processes spawned UNDER a held lock
# (the queue's ladder step runs bench.py, which also acquires): the
# child is already covered by its parent's hold and must not poll
# against it.
HELD_ENV = "TDT_TPU_LOCK_HELD"


def acquire(timeout_s: float, poll_s: float = 5.0):
    """Try to hold the chip lock for up to ``timeout_s``. Returns the
    open fd on success (keep it alive; close to release) or None on
    timeout/any error — callers proceed either way, None just means
    'contended/unavailable, numbers may be noisy'."""
    if os.environ.get(HELD_ENV):
        return None  # parent already holds it on our behalf
    try:
        fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o666)
        try:
            os.chmod(LOCK_PATH, 0o666)  # umask-proof for other users
        except OSError:
            pass
    except OSError:
        return None
    deadline = time.time() + timeout_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return fd
        except OSError:
            if time.time() >= deadline:
                os.close(fd)
                return None
            time.sleep(poll_s)


def release(fd) -> None:
    if fd is not None:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)
