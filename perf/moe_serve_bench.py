"""MoE serving fast path bench: Qwen3MoE through the paged/continuous
stack, unfused vs megakernel, with the EP combine's overlap MEASURED by
the device task tracer.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny-moe model, interpret-mode
kernels). This harness CONSOLIDATES the two retired EP probes —
``perf/ep_a2a_overhead.py`` (n=1 kernel-floor measurement of the
device-push exchange) and ``perf/ep_a2a_projection.py`` (analytic wire
pricing of the reference's 137 µs 32-rank headline) — into the number
that actually matters now that the serving stack runs the workload
end-to-end: what the expert all-to-all costs ON THE DECODE PATH and how
much of it the split-phase A2A_SEND/A2A_WAIT schedule hides under the
expert grouped GEMMs (docs/megakernel.md "MoE serving"). The wire
projection survives as one analytic section below; the kernel-floor
probe's role is superseded by the tracer, which stamps the REAL windows
inside the serving megakernel.

Asserted before any number is recorded (the acceptance gates):

- **greedy bit-exactness, mega vs unfused** on the bf16 arm: same
  admission path, token-for-token equality (the int8 arm reports an
  agreement fraction instead — inside an NS-launch the attention band
  reads the launch's own rows at full precision while the unfused path
  re-reads them quantized, the PR 7 band-precision semantics).
- **int8 bytes/token parity vs KV_QUANT.json**: quantization's byte win
  must survive both the MoE model and the fusion.
- **measured A2A hidden fraction > 0** under the ``overlap_ar`` serving
  schedule, from the decoded trace rings (logical-clock ticks on CPU;
  cycle-true on hardware — docs/profiling.md "Device task tracer").

Usage:  JAX_PLATFORMS=cpu python perf/moe_serve_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

MAX_BATCH = 2
PAGE_SIZE = 16
MAX_LENGTH = 64
NS = 8  # ContinuousEngine.NS — the fused launch width


def workload(rng):
    """Shared-prefix continuous-batching mix (the radix tree's case)."""
    sys_prompt = rng.integers(1, 200, size=12).astype(np.int32)
    reqs = []
    for i in range(4):
        tail = rng.integers(1, 200, size=4 + 2 * i).astype(np.int32)
        reqs.append((np.concatenate([sys_prompt, tail]), 10 + 2 * i))
    return reqs


def run_engine(model, mode, reqs, kv_dtype=None, kernel_trace=False):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    eng = ContinuousEngine(
        model, max_batch=MAX_BATCH, page_size=PAGE_SIZE,
        max_length=MAX_LENGTH, mode=mode, kv_dtype=kv_dtype,
        prefix_cache=True, prefill_chunk=16, seed=7,
        kernel_trace=kernel_trace,
    )
    # Warm off the clock with a workload-disjoint prompt (ids 200+): a
    # warm request's retired pages must not skew the measured arms'
    # radix trees against each other.
    eng.run([(np.arange(240, 244, dtype=np.int32), 2)])
    t0 = time.perf_counter()
    outs = eng.run(reqs)
    wall = time.perf_counter() - t0
    return outs, dict(eng.last_stats), wall, eng


def measured_a2a_overlap(eng):
    """Aggregate the tracer's A2A windows over every traced launch of
    ``eng`` — the ring-measured replacement for the retired analytic
    probes (obs/kernel_trace.py::overlap_report, A2A family)."""
    from triton_distributed_tpu.obs import kernel_trace as kt

    tot = {"a2a_windows": 0, "a2a_comm_ticks": 0, "a2a_hidden_ticks": 0,
           "a2a_exposed_ticks": 0}
    launches = eng.kernel_trace_launches()
    for launch in launches:
        rep = kt.overlap_report(launch.get_records())
        for k in tot:
            tot[k] += rep[k]
    tot["launches"] = len(launches)
    tot["a2a_hidden_fraction"] = (
        tot["a2a_hidden_ticks"] / tot["a2a_comm_ticks"]
        if tot["a2a_comm_ticks"] else None
    )
    return tot


def capacity_drop_surface(ctx):
    """Capacity-mode EP a2a under adversarial routing skew: the
    detected drop count surfaces through ``DispatchState.num_dropped``
    (``ep_moe_ffn(return_state=True)``) — the value the serving
    ledger's ``a2a_dropped`` key carries when a capacity-mode EP path
    runs. Nonzero HERE by construction (every token targets rank 0's
    experts at capacity_factor=1), 0 on the lossless serving arms
    above — proving the counter detects overflow rather than relying
    on it never happening."""
    import functools

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.moe.ep_a2a import ep_moe_ffn

    n = ctx.axis_size("tp")
    rng = np.random.default_rng(3)
    e, d, f, k, t_loc = 8, 32, 64, 2, 8
    x = jnp.asarray(
        np.abs(rng.standard_normal((n * t_loc, d))) * 0.1, jnp.float32
    )
    w_router = jnp.asarray(rng.standard_normal((d, e)) * 0.1, jnp.float32)
    # Bias every top-k onto the first two experts (rank 0's).
    w_router = w_router.at[:, 2:].add(-100.0).at[:, :2].add(100.0)
    w1 = jnp.asarray(rng.standard_normal((e, d, 2 * f)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((e, f, d)) * 0.1, jnp.float32)

    def body(x_loc, wr, w1_loc, w2_loc):
        out, state = ep_moe_ffn(
            x_loc, wr, w1_loc, w2_loc, k, capacity_factor=1.0,
            axis="tp", method="xla", return_state=True,
        )
        return out, state.num_dropped[None]

    fn = ctx.shard_map(
        body,
        in_specs=(P("tp", None), P(), P("tp", None, None),
                  P("tp", None, None)),
        out_specs=(P("tp", None), P("tp")),
    )
    _out, dropped = fn(x, w_router, w1, w2)
    return {
        "ranks": n, "tokens_per_rank": t_loc, "topk": k,
        "capacity_factor": 1.0,
        "detected_dropped_assignments": int(np.asarray(dropped).sum()),
        "note": "adversarial skew at capacity_factor=1 MUST drop and "
        "MUST be counted (DispatchState.num_dropped via "
        "return_state=True) — the a2a_dropped surface is live, not "
        "a constant",
    }


def wire_projection():
    """The reference's flagship-config wire pricing (consolidated from
    the retired perf/ep_a2a_projection.py): 128 tok/rank · topk 8 ·
    hidden 7168 fp8+scales over 32 ranks — the analytic floor for
    ``ep_dispatch(payload="fp8")`` at that geometry, next to the
    measured 137 µs (triton-distributed) / 182 µs (DeepEP) baselines."""
    from triton_distributed_tpu.tools.perf_model import (
        _ring_bw_gbs,
        chip_spec,
    )

    tokens, topk, hidden, ranks, local = 128, 8, 7168, 32, 4
    spec = chip_spec("v5e")
    row_bytes = hidden * 1 + 4  # fp8 payload + one f32 scale per row
    routed = tokens * topk
    off_rank = routed * (ranks - 1) / ranks
    off_slice = (ranks - local) / max(ranks - 1, 1)
    ici_us = (off_rank * (1 - off_slice) * row_bytes
              / (_ring_bw_gbs(spec, True) * 1e9) * 1e6)
    dcn_us = (off_rank * off_slice * row_bytes * local
              / (spec.dcn_gbs * 1e9) * 1e6)
    return {
        "config": {"tokens_per_rank": tokens, "topk": topk,
                   "hidden": hidden, "payload": "fp8+scales",
                   "ranks": ranks, "ranks_per_slice": local,
                   "chip": spec.name},
        "wire_bytes_per_rank": int(off_rank * row_bytes),
        "projection_us": {"ici": round(ici_us, 1), "dcn": round(dcn_us, 1),
                          "total": round(max(ici_us, 1.0) + dcn_us, 1)},
        "reference_us": {"triton_distributed_32xH800": 137,
                         "deepep_32xH800": 182},
    }


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained(
        "tiny-moe", ctx=ctx, max_length=MAX_LENGTH
    )
    cfg = model.cfg
    rng = np.random.default_rng(0)
    reqs = workload(rng)
    toks_total = sum(g for _, g in reqs)

    # Gate 1 — greedy bit-exactness, mega vs unfused (bf16 arm).
    outs_x, st_x, wall_x, _ = run_engine(model, "xla", reqs)
    outs_m, st_m, wall_m, eng_m = run_engine(
        model, "mega", reqs, kernel_trace=True
    )
    for i, (a, b) in enumerate(zip(outs_x, outs_m)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            raise SystemExit(
                f"mega vs unfused greedy mismatch on request {i}: "
                f"{np.asarray(a).tolist()} vs {np.asarray(b).tolist()}"
            )

    # Gate 2 — measured A2A overlap from the serving launches' rings.
    overlap = measured_a2a_overlap(eng_m)
    if not overlap["a2a_windows"] or not overlap["a2a_hidden_fraction"]:
        raise SystemExit(f"no measured A2A overlap windows: {overlap}")

    # int8 arms: bytes/token parity + NS-launch agreement fraction.
    outs_xq, st_xq, _, _ = run_engine(model, "xla", reqs, kv_dtype="int8")
    outs_mq, st_mq, _, _ = run_engine(model, "mega", reqs, kv_dtype="int8")
    agree = sum(
        int(np.sum(np.asarray(a) == np.asarray(b)))
        for a, b in zip(outs_xq, outs_mq)
    ) / max(toks_total, 1)
    bytes_q = st_mq["kv_bytes_per_token"]
    bytes_full = float(
        2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim
        * np.dtype(np.float32).itemsize  # tiny-moe stores f32
    )
    # Gate 3 — int8 bytes/token parity vs KV_QUANT.json's method (full
    # width / (codes + per-page scales)).
    kv_quant_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "KV_QUANT.json"
    )
    with open(kv_quant_path) as f:
        kv_quant = json.load(f)
    ratio = bytes_full / bytes_q
    ref_ratio = kv_quant["reduction_vs_full_width"]
    if abs(ratio - ref_ratio) / ref_ratio > 0.05:
        raise SystemExit(
            f"int8 bytes/token ratio {ratio:.3f} diverged from "
            f"KV_QUANT.json's {ref_ratio:.3f}"
        )

    # Capacity-mode drop surface: detected, never silent (asserted).
    drop_surface = capacity_drop_surface(ctx)
    if not drop_surface["detected_dropped_assignments"]:
        raise SystemExit(
            f"capacity-mode skew produced no detected drops: "
            f"{drop_surface}"
        )

    result = {
        "metric": "moe_serving_fast_path",
        "workload": {
            "model": "tiny-moe",
            "num_experts": cfg.num_experts,
            "experts_per_tok": cfg.num_experts_per_tok,
            "requests": len(reqs), "generated_tokens": toks_total,
            "max_batch": MAX_BATCH, "page_size": PAGE_SIZE, "ns": NS,
            "config": "prefix cache + chunked prefill, mode=mega "
            "(fuse_norms+cross_prefetch+overlap_ar — A2A split-phase "
            "EP combine) vs mode=xla (tp_moe_fwd AR decode)",
        },
        "platform": jax.default_backend(),
        "greedy_bit_exact_vs_unfused_bf16": True,
        "greedy_agreement_vs_unfused_int8": round(agree, 4),
        "greedy_agreement_note": "bf16 arm asserted token-for-token; "
        "the int8 NS-launch arm carries the PR 7 band-precision "
        "semantics (the in-launch attention band reads the launch's "
        "own rows at full precision — strictly MORE accurate than the "
        "quantized pool roundtrip the unfused path re-reads)",
        "decode_ms_per_step": {
            "engine_wall_per_step_unfused": round(
                wall_x / max(st_x["decode_steps"], 1) * 1e3, 2
            ),
            "engine_wall_per_step_mega_cpu_interpret_advisory": round(
                wall_m / max(st_m["decode_steps"], 1) * 1e3, 2
            ),
            "note": "CPU interpret wall — advisory; the "
            "platform-independent levers are dispatches/token "
            "(mega_launches below) and the measured A2A overlap",
        },
        "host_dispatches": {
            "unfused_decode_programs": st_x["decode_steps"],
            "mega_launches": st_m["mega_launches"],
            "mega_single_step_fallbacks": st_m["mega_fallback_steps"],
            "amortization_x": round(
                st_x["decode_steps"]
                / max(st_m["mega_launches"]
                      + st_m["mega_fallback_steps"], 1), 2
            ),
        },
        "measured_a2a_overlap": {
            **overlap,
            "clock": "logical" if jax.default_backend() != "tpu"
            else "cycle",
            "note": "ONE window per gate layer: opens at the phase-0 "
            "A2A_SEND's puts-in-flight mark (mid), closes at "
            "A2A_WAIT's end; hidden = the second half of the expert "
            "grouped GEMMs + the wait's pre-block tile-0 fire — "
            "measured from the device ring, not modeled",
        },
        "kv_bytes_per_token": {
            "int8_mega_moe": bytes_q,
            "full_width_arithmetic": bytes_full,
            "reduction_x": round(ratio, 3),
            "kv_quant_json_reduction_vs_full_width": ref_ratio,
            "matches_kv_quant_json": True,
        },
        "moe_ledger": {
            "routed_tokens_unfused": st_x["moe_routed_tokens"],
            "routed_tokens_mega": st_m["moe_routed_tokens"],
            "a2a_dropped": st_m["a2a_dropped"],
            "note": "a2a_dropped surfaces DispatchState.num_dropped — "
            "0 by construction on the lossless serving paths; the "
            "capacity-mode arm below proves the counter is live",
        },
        "capacity_mode_drop_surface": drop_surface,
        "a2a_wire_projection_32rank": wire_projection(),
        "provenance": {
            "harness": "perf/moe_serve_bench.py — consolidates the "
            "retired perf/ep_a2a_overhead.py (n=1 kernel floor; "
            "superseded by the tracer's in-situ windows) and "
            "perf/ep_a2a_projection.py (wire pricing, kept as the "
            "a2a_wire_projection_32rank section); same shared-prefix "
            "continuous-batching workload through ContinuousEngine "
            "mode=xla and mode=mega on the tiny-moe Qwen3MoE",
            "caveat": "CPU wall-clock is interpret-mode-taxed and "
            "advisory; tick durations become cycle-true only on "
            "hardware (the tracer clock is logical in-container)",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "MOE_SERVE.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
