"""Speculative-decode microbench: target forwards per emitted token.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model): the measured quantity
is the ALGORITHMIC win — how many target-model forward steps the engine
pays per emitted token — on a repetitive/structured workload, the
traffic shape self-drafting speculation exists for (templated answers,
code, greedy cycles). Plain decode pays exactly one forward per token;
speculative decode pays one forward per ACCEPTED-RUN of tokens, so
``steps_per_token`` drops toward ``1 / (K + 1)`` as acceptance rises.
Wall-clock on CPU is advisory (each verify chunk is a wider forward
than a single decode step — the chip-level win needs the chunk forward
to cost ~one decode step, which holds when decode is
memory-bandwidth-bound); ``step_reduction`` is the platform-independent
lever.

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/SPEC_DECODE.json`` (same shape as ``perf/PREFIX_CACHE.json`` so
the bench-trajectory tooling picks both up).

Usage:  JAX_PLATFORMS=cpu python perf/spec_decode_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

# Workload shape: a multi-turn session — each turn's prompt is the
# conversation so far (system motif + every previous answer), the
# canonical prompt-lookup traffic: continuation/regeneration output
# overlaps spans ALREADY IN THE PROMPT, so the n-gram drafter reads the
# future out of the history. Turn 1 is cold (no overlap — measures the
# drafter's graceful degradation too).
MOTIF_TOKENS = 8
MOTIF_REPEATS = 3
NUM_TURNS = 3
GEN_LEN = 64
SPEC_K = 6
PAGE_SIZE = 16
MAX_LENGTH = 256
# Tree arm: a linear K=6 verify already pays a 16-row chunk
# (round_chunk(7) — bf16 sublane tile), so the tree spends the SAME
# chunk's pad rows on real draft nodes: budget round_chunk(K+1) = 16
# nodes, per-branch depth up to TREE_K, up to TREE_WIDTH branches.
# Equal verify-program width makes steps-per-token comparable 1:1.
TREE_K = 15
TREE_WIDTH = 4


def serve_session(eng):
    """Serve NUM_TURNS turns (clean per-step accounting: one active
    slot ⇒ one emitted token per target forward in the baseline arm),
    each turn asked TWICE — the agent-loop shape (perf/loadgen.py's
    "agentic" class): a retry/regeneration re-derives an answer whose
    chain the radix tree already holds, so the radix drafter reads the
    continuation straight out of the cache while the n-gram drafter
    still works from self-repetition alone. The next turn's prompt
    extends the conversation with the (first) answer. Greedy serving
    makes every arm walk the identical token stream (asserted in
    ``main`` — the speedup only counts if the bits match), so the arms
    stay comparable token-for-token."""
    rng = np.random.default_rng(0)
    motif = rng.integers(1, 200, size=MOTIF_TOKENS).astype(np.int32)
    prompt = np.tile(motif, MOTIF_REPEATS)
    steps = emitted = 0
    streams = []
    t0 = time.perf_counter()
    for _turn in range(NUM_TURNS):
        for _ask in range(2):  # ask, then the agent-loop re-ask
            outs = eng.run([(prompt, GEN_LEN)])
            st = eng.last_stats
            steps += st.get("target_steps",
                            st["decode_steps"] + st["spec_verify_steps"])
            emitted += len(outs[0])
            streams.append([int(t) for t in outs[0]])
        prompt = np.concatenate([prompt, outs[0].astype(np.int32)])
    return steps, emitted, time.perf_counter() - t0, streams


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)

    def build(speculative: int, width: int = 1) -> ContinuousEngine:
        # Every arm runs the prefix cache + chunked prefill (turn i+1's
        # prompt extends turn i's — the radix tree eats the prefill,
        # speculation eats the decode; the arms differ ONLY in
        # speculation, and arbitrary-length turn prompts admit through
        # the chunk path).
        return ContinuousEngine(
            model, max_batch=1, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
            prefix_cache=True, prefill_chunk=32, speculative=speculative,
            spec_width=width,
        )

    # Warmup all arms (chunk/decode program compiles stay out of the
    # timings; the jit cache lives on the model and carries over).
    serve_session(build(SPEC_K))
    serve_session(build(0))
    serve_session(build(TREE_K, TREE_WIDTH))

    base_steps, base_tokens, base_s, base_streams = serve_session(build(0))
    spec = build(SPEC_K)
    spec_steps, spec_tokens, spec_s, spec_streams = serve_session(spec)
    st = spec.last_stats
    tree = build(TREE_K, TREE_WIDTH)
    assert tree._spec_tree, "tree arm must run the tree path"
    tree_steps, tree_tokens, tree_s, tree_streams = serve_session(tree)
    tt = tree.last_stats

    # The gate: a speedup only counts over the EXACT token stream plain
    # greedy decode emits — any divergence voids the measurement.
    assert spec_streams == base_streams, "linear arm diverged from greedy"
    assert tree_streams == base_streams, "tree arm diverged from greedy"
    assert tt["spec_tree_rounds"] > 0, "tree arm never drafted a tree"

    base_spt = base_steps / max(base_tokens, 1)
    spec_spt = spec_steps / max(spec_tokens, 1)
    tree_spt = tree_steps / max(tree_tokens, 1)
    reduction = base_spt / max(spec_spt, 1e-9)
    tree_reduction = base_spt / max(tree_spt, 1e-9)
    result = {
        "metric": "spec_decode_target_steps_per_token",
        "workload": {
            "motif_tokens": MOTIF_TOKENS,
            "motif_repeats": MOTIF_REPEATS,
            "num_turns": NUM_TURNS,
            "gen_len": GEN_LEN,
            "speculative_k": SPEC_K,
            "tree_k": TREE_K,
            "tree_width": TREE_WIDTH,
            "page_size": PAGE_SIZE,
        },
        "platform": jax.default_backend(),
        "baseline": {
            "target_steps": int(base_steps),
            "emitted_tokens": int(base_tokens),
            "steps_per_token": round(base_spt, 4),
            "wall_s": round(base_s, 3),
        },
        "speculative": {
            "target_steps": int(spec_steps),
            "emitted_tokens": int(spec_tokens),
            "steps_per_token": round(spec_spt, 4),
            "tokens_per_step": round(1.0 / max(spec_spt, 1e-9), 3),
            "accept_rate": round(st["spec_accept_rate"], 3),
            "draft_tokens": int(st["spec_draft_tokens"]),
            "rollback_tokens": int(st["spec_rollback_tokens"]),
            "wall_s": round(spec_s, 3),
        },
        "tree": {
            "target_steps": int(tree_steps),
            "emitted_tokens": int(tree_tokens),
            "steps_per_token": round(tree_spt, 4),
            "tokens_per_step": round(1.0 / max(tree_spt, 1e-9), 3),
            "accept_rate": round(tt["spec_accept_rate"], 3),
            "draft_tokens": int(tt["spec_draft_tokens"]),
            "rollback_tokens": int(tt["spec_rollback_tokens"]),
            "tree_rounds": int(tt["spec_tree_rounds"]),
            "tree_nodes": int(tt["spec_tree_nodes"]),
            "branch_accepts": int(tt["spec_tree_branch_accepts"]),
            "wall_s": round(tree_s, 3),
        },
        "step_reduction": round(reduction, 3),
        "tree_step_reduction": round(tree_reduction, 3),
        "provenance": {
            "harness": "perf/spec_decode_bench.py — a multi-turn "
            "session (each turn's prompt = conversation so far) served "
            "turn-per-run with max_batch=1 (one emitted token per "
            "target forward in the baseline arm); the speculative arm "
            "drafts K=6 from each request's own n-gram history — turn "
            "1 is cold, later turns draft from answer spans already in "
            "the prompt (the canonical prompt-lookup traffic); the "
            "tree arm drafts K=15×width-4 tries from the radix tree in "
            "the SAME 16-row verify chunk the linear arm pads (budget "
            "round_chunk(K+1)) — both speculative arms are gated on "
            "asserted bit-identity with the baseline's token streams",
            "caveat": "CPU wall-clock is advisory (a verify chunk is a "
            "wider forward than one decode step); step_reduction is "
            "the platform-independent lever — it bounds the chip-level "
            "speedup when decode steps are launch/bandwidth-bound",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "SPEC_DECODE.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
