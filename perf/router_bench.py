"""Router-policy microbench: prefix-affinity vs round-robin hit rate.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model): the measured quantity
is the serving-tier ALGORITHMIC win — the aggregate radix hit rate the
replica fleet sustains, and the prefill work actually computed — on the
shared-system-prompt workload from ``perf/prefix_cache_bench.py``,
scaled out to G distinct prompt groups arriving interleaved across N
replicas. Round-robin splits each group across every replica, so every
replica pays its own cold miss per group (and holds a redundant copy of
every prefix); affinity routing pins each group to the replica whose
radix tree already caches it, so the fleet pays ONE cold miss per group
— the scale-out behavior that preserves PREFIX_CACHE.json's 64%
prefill-work saving (docs/scale-out.md).

Arrivals are served one at a time (``router.run`` per arrival, gen_len=1
— the TTFT shape), so routing decisions see a current prefix mirror and
both arms execute identical workloads deterministically.

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/ROUTER.json``.

Usage:  JAX_PLATFORMS=cpu python perf/router_bench.py [--replicas 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

# Workload shape: G groups, each one shared system prompt + per-user
# suffixes, arrivals interleaved round-robin across groups (the worst
# case for a router with no affinity: consecutive arrivals never share
# a prefix).
GROUPS = 3
ARRIVALS_PER_GROUP = 4
SYSTEM_PROMPT_TOKENS = 64
USER_SUFFIX_TOKENS = 16
PAGE_SIZE = 16
MAX_LENGTH = 256
PREFILL_CHUNK = 32


def build_prompts() -> list[np.ndarray]:
    rng = np.random.default_rng(0)
    systems = [
        rng.integers(1, 200, size=SYSTEM_PROMPT_TOKENS).astype(np.int32)
        for _ in range(GROUPS)
    ]
    prompts = []
    for _ in range(ARRIVALS_PER_GROUP):
        for g in range(GROUPS):
            prompts.append(np.concatenate([
                systems[g],
                rng.integers(1, 200, size=USER_SUFFIX_TOKENS).astype(
                    np.int32
                ),
            ]))
    return prompts


def serve_policy(model, prompts, policy: str, replicas: int) -> dict:
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving.router import Router

    router = Router(
        [
            ContinuousEngine(
                model, max_batch=2, page_size=PAGE_SIZE,
                max_length=MAX_LENGTH, prefix_cache=True,
                prefill_chunk=PREFILL_CHUNK,
            )
            for _ in range(replicas)
        ],
        policy=policy,
    )
    ttfts = []
    for p in prompts:
        t0 = time.perf_counter()
        res = router.run([(p, 1)], results=True)
        ttfts.append(time.perf_counter() - t0)
        assert res[0].status == "ok", res[0]
    # Cumulative across the whole arm (replica totals are monotone).
    prefilled = router.last_stats["prefill_tokens"]
    # Tree-level counters are cumulative across the whole arm, per
    # replica; sum them for the fleet-wide hit rate.
    lookups = hits = hit_tokens = tree_pages = 0
    for r in router.replicas:
        st = r.engine.prefix.stats
        lookups += st["lookups"]
        hits += st["hits"]
        hit_tokens += st["hit_tokens"]
        tree_pages += r.engine.prefix.node_count
    rstats = router.last_stats["router"]
    out = {
        "policy": policy,
        "radix_hit_rate": round(hits / max(lookups, 1), 4),
        "radix_hit_tokens": int(hit_tokens),
        "prefill_tokens_computed": int(prefilled),
        "tree_pages_total": int(tree_pages),
        "ttft_s_mean": round(float(np.mean(ttfts)), 4),
        "per_replica_served": [r.served for r in router.replicas],
        "router": {
            k: rstats[k]
            for k in ("routed", "affinity_hits", "affinity_hit_tokens",
                      "least_loaded", "round_robin", "reroutes")
        },
    }
    router.shutdown()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, default=2,
                    help="replica count (the ISSUE-6 acceptance bar is "
                    "affinity strictly above round-robin at >= 2)")
    args = ap.parse_args(argv)

    from triton_distributed_tpu.models import AutoLLM

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    prompts = build_prompts()
    prompt_tokens = int(sum(len(p) for p in prompts))

    # Warmup: compile every (chunk width, kv-gather bucket) program the
    # arrivals will hit — the jit cache lives on the model, so it
    # carries to the measured routers.
    serve_policy(model, prompts, "affinity", 1)

    rr = serve_policy(model, prompts, "round_robin", args.replicas)
    aff = serve_policy(model, prompts, "affinity", args.replicas)

    result = {
        "metric": "router_policy_radix_hit_rate",
        "workload": {
            "groups": GROUPS,
            "arrivals_per_group": ARRIVALS_PER_GROUP,
            "system_prompt_tokens": SYSTEM_PROMPT_TOKENS,
            "user_suffix_tokens": USER_SUFFIX_TOKENS,
            "page_size": PAGE_SIZE,
            "prefill_chunk": PREFILL_CHUNK,
            "prompt_tokens_total": prompt_tokens,
        },
        "platform": jax.default_backend(),
        "replicas": args.replicas,
        "round_robin": rr,
        "affinity": aff,
        "affinity_hit_rate_advantage": round(
            aff["radix_hit_rate"] - rr["radix_hit_rate"], 4
        ),
        "prefill_work_avoided_frac": {
            "round_robin": round(
                1.0 - rr["prefill_tokens_computed"] / prompt_tokens, 4
            ),
            "affinity": round(
                1.0 - aff["prefill_tokens_computed"] / prompt_tokens, 4
            ),
        },
        "provenance": {
            "harness": "perf/router_bench.py — per-arrival "
            "Router.run(gen_len=1) over fresh ContinuousEngine "
            "replicas per policy arm (tiny model, chunked prefill, "
            "shared jit cache warmed first); radix_hit_rate sums each "
            "replica tree's cumulative lookups/hits",
            "caveat": "CPU wall-clock (ttft_s_mean) is interpret-mode-"
            "taxed and advisory; radix_hit_rate and prefill tokens "
            "computed are platform-independent (prefill cost ∝ prefix "
            "length)",
        },
    }
    ok = aff["radix_hit_rate"] > rr["radix_hit_rate"]
    result["affinity_strictly_higher"] = bool(ok)
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "ROUTER.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
