"""KV fabric bench: fleet-wide prefill-work avoidance with hot sets
SHARDED across replicas, and warm-boot scale-up from a shared tier —
every number gated on an asserted bit-exact output.

Three arms (docs/scale-out.md "KV fabric"):

1. **Sharded fleet, fabric on**: two engines, each seeded with its OWN
   hot-prefix shard (rotation on an 8-page pool + evictor flushes, so
   every hot chain lives fully in the owner's TIER), fronted by real
   ``ModelServer``s and cross-wired with ``WireFabricPeer``s — the
   actual ``tier_probe``/``tier_get`` wire path, not an in-process
   shortcut. Phase 2 routes every hot prefix to the replica that does
   NOT own it: round 1 measures pure cross-replica pulls, round 2
   measures adoption (the pulled entries now answer from the LOCAL
   tier — fabric pull count must not grow).
2. **Sharded fleet, tier-less** (the reference): identical topology
   and phase-2 arrival stream without tiers — the cross-replica
   portion re-prefills everything (~0% avoided).
3. **Scale-up boots warm**: an engine over a SHARED tier dir (the
   ``--tier-shared`` shape) spills its hot set; a freshly constructed
   engine over the same dir serves its FIRST batch with
   ``tier_hits > 0`` instead of cold prefill.

The acceptance bar is the single-engine KV_TIER.json ample-tier
baseline (prefill_work_avoided_frac 0.6154): the fleet number with hot
sets sharded across 2 replicas must hold ≥ it.

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/KV_FABRIC.json``.

Usage:  JAX_PLATFORMS=cpu python perf/kv_fabric_bench.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

# Mirror perf/kv_tier_bench.py's regime: 48-token (3-page) hot
# prefixes rotating on an 8-page pool, so chains keep getting evicted
# to the tier — but here each replica owns a DISJOINT hot shard and
# phase 2 revisits every prefix on the OTHER replica.
PAGE_SIZE = 16
MAX_LENGTH = 128
NUM_PAGES = 8
PREFIX_TOKENS = 48       # 3 full pages per hot prefix
EVICTOR_TOKENS = 64      # 4 full pages: flushes the tree into the tier
SUFFIX_TOKENS = 4
HOTS_PER_REPLICA = 3
SEED_ROUNDS = 2          # phase-1 rotation rounds per replica
CROSS_ROUNDS = 2         # phase-2: round 1 = cross-replica, 2 = adoption
BASELINE_AVOIDED = 0.6154  # KV_TIER.json ample-tier single-engine frac


def _suffix(rng):
    return rng.integers(1, 200, size=SUFFIX_TOKENS).astype(np.int32)


def _arrival(prefix, rng):
    return (np.concatenate([prefix, _suffix(rng)]), 1)


class Gold:
    """Tier-less golden oracle: every recorded arrival is asserted
    bit-exact against it BEFORE counting."""

    def __init__(self, model):
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        self.eng = ContinuousEngine(
            model, max_batch=1, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
            prefix_cache=True,
        )

    def check(self, eng, req):
        out = eng.run([req])[0]
        np.testing.assert_array_equal(out, self.eng.run([req])[0])
        return eng.last_stats


def _mk_engine(model, *, tier: bool, fabric=None):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    kw = dict(tier_bytes=32 << 20, fabric=fabric) if tier else {}
    return ContinuousEngine(
        model, max_batch=1, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
        prefix_cache=True, num_pages=NUM_PAGES, **kw,
    )


def _seed_shard(eng, gold, hots, rng):
    """Phase 1: rotate the replica's own hot shard, then flush the
    tree with two 4-page evictors so every hot chain lives FULLY in
    the tier (a chain whose first page is still tree-resident cannot
    be pulled contiguously by a peer)."""
    from triton_distributed_tpu.models.kv_tier import (
        PREFIX_KIND,
        chain_digest,
    )

    prefill = prompt = 0
    for _ in range(SEED_ROUNDS):
        for h in hots:
            req = _arrival(h, rng)
            st = gold.check(eng, req)
            prefill += st["prefill_tokens"]
            prompt += len(req[0])
    for _ in range(2):
        ev = rng.integers(1, 200, size=EVICTOR_TOKENS).astype(np.int32)
        req = _arrival(ev, rng)
        st = gold.check(eng, req)
        prefill += st["prefill_tokens"]
        prompt += len(req[0])
    if eng.tier is not None:
        for h in hots:
            toks = [int(t) for t in h]
            for i in range(PAGE_SIZE, PREFIX_TOKENS + 1, PAGE_SIZE):
                assert eng.tier.contains(
                    PREFIX_KIND, chain_digest(toks[:i])
                ), "seed phase left a hot chain partly tree-resident"
    return prefill, prompt


def _phase2(engines, shards, rng):
    """Route every hot prefix to the replica that does NOT own it;
    returns per-round (prefill, prompt, stats) sums."""
    rounds = []
    for rnd in range(CROSS_ROUNDS):
        prefill = prompt = remote_pages = tier_hits = 0
        for owner, hots in enumerate(shards):
            target = engines[1 - owner]  # the NON-owner
            for h in hots:
                req = _arrival(h, rng)
                st = target.gold.check(target.eng, req)
                prefill += st["prefill_tokens"]
                prompt += len(req[0])
                remote_pages += st["tier_remote_pages"]
                tier_hits += st["tier_hits"]
        rounds.append({
            "prefill_tokens": int(prefill),
            "prompt_tokens": int(prompt),
            "prefill_work_avoided_frac": round(1.0 - prefill / prompt, 4),
            "tier_remote_pages": int(remote_pages),
            "tier_hits": int(tier_hits),
        })
    return rounds


class _Replica:
    def __init__(self, eng, gold):
        self.eng = eng
        self.gold = gold


def arm_sharded_fleet(model, gold, *, fabric_on: bool):
    """Arms 1 and 2: same shards, same arrival stream (fresh
    deterministic rng per arm), with/without tiers+fabric."""
    from triton_distributed_tpu.models.kv_tier import FabricClient
    from triton_distributed_tpu.serving.server import ModelServer, request

    rng = np.random.default_rng(7)
    shards = [
        [rng.integers(1, 200, size=PREFIX_TOKENS).astype(np.int32)
         for _ in range(HOTS_PER_REPLICA)]
        for _ in range(2)
    ]
    clients = [FabricClient(pull_timeout_s=5.0) if fabric_on else None
               for _ in range(2)]
    engines = [_mk_engine(model, tier=fabric_on, fabric=clients[i])
               for i in range(2)]

    seed_prefill = seed_prompt = 0
    for eng, hots in zip(engines, shards):
        pf, pm = _seed_shard(eng, gold, hots, rng)
        seed_prefill += pf
        seed_prompt += pm

    servers = []
    try:
        if fabric_on:
            # The REAL wire: each engine behind a ModelServer, each
            # client pulling through the peer's tier_probe/tier_get.
            servers = [ModelServer(e).start() for e in engines]
            for i, fc in enumerate(clients):
                peer = servers[1 - i]
                fc.set_wire_peers([
                    {"name": f"r{1 - i}", "host": peer.host,
                     "port": peer.port},
                ])
        reps = [_Replica(e, gold) for e in engines]
        rounds = _phase2(reps, shards, rng)
    finally:
        for srv in servers:
            try:
                request(srv.host, srv.port, {"cmd": "shutdown"},
                        timeout=10.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            srv.shutdown()

    prefill = sum(r["prefill_tokens"] for r in rounds)
    prompt = sum(r["prompt_tokens"] for r in rounds)
    arm = {
        "replicas": 2,
        "hot_prefixes_per_replica": HOTS_PER_REPLICA,
        "seed_prefill_tokens": int(seed_prefill),
        "cross_replica_round": rounds[0],
        "adoption_round": rounds[1],
        "prefill_tokens": int(prefill),
        "prompt_tokens": int(prompt),
        "prefill_work_avoided_frac": round(1.0 - prefill / prompt, 4),
        "bit_exact": True,  # asserted per arrival in Gold.check
    }
    if fabric_on:
        arm["fabric"] = {f"r{i}": fc.snapshot()
                         for i, fc in enumerate(clients)}
        pulls = [fc.stats["pulls"] for fc in clients]
        assert all(fc.stats["remote_hits"] >= HOTS_PER_REPLICA
                   for fc in clients), "fabric never pulled — dead arm"
        # Adoption: round 2 is served from the LOCAL tier; the pull
        # count must not have grown after round 1's pulls.
        arm["adoption_pulls_delta"] = int(
            sum(pulls) - rounds[0]["tier_remote_pages"]
        )
        assert rounds[1]["tier_remote_pages"] == 0, (
            "adopted entries still crossing the wire"
        )
    for eng in engines:
        assert eng.audit() == []
    return arm


def arm_scale_up(model, gold, shared_dir):
    """Arm 3: spill a hot set under a SHARED tier dir, then construct
    a FRESH engine over it — its first batch must hit the tier."""
    rng = np.random.default_rng(11)
    hots = [rng.integers(1, 200, size=PREFIX_TOKENS).astype(np.int32)
            for _ in range(HOTS_PER_REPLICA)]

    from triton_distributed_tpu.models.continuous import ContinuousEngine

    def build():
        return ContinuousEngine(
            model, max_batch=1, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
            prefix_cache=True, num_pages=NUM_PAGES,
            tier_bytes=32 << 20, tier_dir=shared_dir,
        )

    veteran = build()
    _seed_shard(veteran, gold, hots, rng)

    fresh = build()
    from triton_distributed_tpu.models.kv_tier import PREFIX_KIND

    assert fresh.tier.may_contain(PREFIX_KIND), "disk prescan found nothing"
    req = _arrival(hots[0], rng)
    st = gold.check(fresh, req)
    assert st["tier_hits"] > 0, "scale-up replica booted cold"
    assert st["prefill_tokens"] < len(req[0])
    assert veteran.audit() == [] and fresh.audit() == []
    return {
        "first_batch_tier_hits": int(st["tier_hits"]),
        "first_batch_faulted_pages": int(st["tier_faults"]),
        "first_batch_prefill_tokens": int(st["prefill_tokens"]),
        "first_batch_prompt_tokens": int(len(req[0])),
        "warm_boot": True,
        "bit_exact": True,
    }


def main() -> int:
    import tempfile

    from triton_distributed_tpu.models import AutoLLM

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    gold = Gold(model)

    fabric = arm_sharded_fleet(model, gold, fabric_on=True)
    tierless = arm_sharded_fleet(model, gold, fabric_on=False)
    with tempfile.TemporaryDirectory(prefix="tdt-fabric-") as d:
        scale_up = arm_scale_up(model, gold, d)
    mesh_mod.finalize_distributed()

    # The acceptance gates (ISSUE 17): the fleet number with hot sets
    # SHARDED across replicas holds the single-engine baseline, the
    # tier-less fleet avoids ~nothing on the cross-replica portion,
    # and a fresh replica boots warm.
    assert fabric["prefill_work_avoided_frac"] >= BASELINE_AVOIDED
    assert (fabric["cross_replica_round"]["prefill_work_avoided_frac"]
            >= BASELINE_AVOIDED)
    assert (tierless["cross_replica_round"]["prefill_work_avoided_frac"]
            <= 0.05)
    assert scale_up["first_batch_tier_hits"] > 0

    result = {
        "metric": "kv_fabric_fleet_prefill_avoidance_and_warm_boot",
        "workload": {
            "page_size": PAGE_SIZE,
            "num_pages": NUM_PAGES,
            "prefix_tokens": PREFIX_TOKENS,
            "suffix_tokens": SUFFIX_TOKENS,
            "hot_prefixes_per_replica": HOTS_PER_REPLICA,
            "seed_rounds": SEED_ROUNDS,
            "cross_rounds": CROSS_ROUNDS,
        },
        "platform": jax.default_backend(),
        "single_engine_baseline_avoided_frac": BASELINE_AVOIDED,
        "sharded_fleet_fabric": fabric,
        "sharded_fleet_tierless": tierless,
        "scale_up_warm_boot": scale_up,
        "provenance": {
            "harness": "perf/kv_fabric_bench.py — two ContinuousEngines "
            "with disjoint hot-prefix shards spilled to their tiers, "
            "cross-wired over REAL ModelServer tier_probe/tier_get "
            "(WireFabricPeer); phase 2 routes every prefix to the "
            "non-owner replica; scale-up arm boots a fresh engine over "
            "a shared tier dir (the --tier-shared shape)",
            "gates": "EVERY recorded arrival asserted bit-exact "
            "against a tier-less golden before counting; fabric arm "
            "asserts remote pulls happened and that round-2 adoption "
            "crossed the wire zero times; scale-up asserts "
            "first-batch tier_hits > 0",
            "caveat": "prefill tokens computed/avoided is the "
            "platform-independent lever (CPU interpret wall-clock is "
            "advisory); the tier-less arm shares the arrival stream "
            "so the avoided-frac delta is the fabric's contribution",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "KV_FABRIC.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
