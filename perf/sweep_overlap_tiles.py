"""Tile-config sweep for the fused overlap kernels on the real chip.

The tp=1 compute path of ``ag_gemm`` is a pure staged GEMM — the rung
where manual staging can lose to XLA. B is streamed once per (step,
M-tile) pair, so HBM traffic scales with ``m_per / tile_m``; this sweep
finds the (tile_m, tile_n) that closes the gap to the XLA GEMM.

Timing follows the axon-relay rules (chained iterations inside one jit,
host fetch as fence — see bench.py).

Usage:
    python perf/sweep_overlap_tiles.py [--m 8192 --k 4096 --n 12288]
"""

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--m", type=int, default=8192)
    p.add_argument("--k", type=int, default=4096)
    p.add_argument("--n", type=int, default=12288)
    p.add_argument("--op", default="ag_gemm", choices=["ag_gemm", "gemm_rs"])
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--deadline-s", type=float, default=1800,
                   help="stop starting new configs past this wall "
                        "budget and report best-so-far (this step has "
                        "twice burned a whole relay window compiling "
                        "every config; 0 disables)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)
    t_start = time.time()

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.ops.overlap import (
        AGGemmConfig,
        GemmRSConfig,
        ag_gemm_op,
        gemm_rs_op,
    )
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    m, k, n = args.m, args.k, args.n
    dt = jnp.float32 if args.cpu else jnp.bfloat16
    key = jax.random.key(0)
    a = jax.random.normal(key, (m, k), jnp.float32).astype(dt)
    b = jax.random.normal(key, (k, n), jnp.float32).astype(dt)

    def timed(f, iters=args.iters):
        def chained(a, b):
            def body(_, acc):
                # Sub-ulp perturbation: data-dependent but not foldable.
                # Sum (not element-pick) carry: every output element stays
                # live, so XLA can't DCE-slice the GEMM (see
                # overlap_efficiency.py).
                out = f(a + (acc * 1e-30).astype(a.dtype), b)
                return jnp.sum(out.astype(jnp.float32))

            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

        run = jax.jit(chained)
        np.asarray(run(a, b))  # compile + warm
        # Median, not min: the relay occasionally leaks one call's device
        # work into the next measurement window (an inflated rep followed
        # by an impossibly fast one) — min() latches onto the leak. See
        # perf/OVERLAP_RESULTS.md methodology notes.
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(run(a, b))
            ts.append((time.perf_counter() - t0) / iters)
        return sorted(ts)[len(ts) // 2] * 1e3

    t_xla = timed(
        lambda a, b: jnp.dot(a, b, preferred_element_type=jnp.float32).astype(dt)
    )
    print(json.dumps({"config": "xla", "ms": round(t_xla, 3)}), flush=True)

    results = []
    tile_ms = [256, 512, 1024, 2048]
    tile_ns = [512, 1024, 1536]
    for tile_m, tile_n in itertools.product(tile_ms, tile_ns):
        if args.deadline_s and time.time() - t_start > args.deadline_s:
            print(json.dumps({"deadline_s": args.deadline_s,
                              "stopped_at": f"tm{tile_m}_tn{tile_n}"}),
                  flush=True)
            break
        if m % tile_m or n % tile_n:
            continue
        itemsize = jnp.dtype(dt).itemsize
        vmem = (2 * tile_m * k + 2 * k * tile_n + 2 * tile_m * tile_n) * itemsize
        if vmem > 110 * 1024 * 1024:
            continue
        if args.op == "ag_gemm":
            cfg = AGGemmConfig(tile_n=tile_n, tile_m=tile_m)
            f = lambda a, b, cfg=cfg: ag_gemm_op(a, b, "tp", cfg, ctx)
        else:
            # force_kernel: without it the tp=1 path short-circuits to a
            # plain XLA dot and the sweep times XLA at every config.
            cfg = GemmRSConfig(tile_n=tile_n, tile_m=tile_m,
                               force_kernel=True)
            f = lambda a, b, cfg=cfg: gemm_rs_op(a, b, "tp", cfg, ctx)
        try:
            ms = timed(f)
        except Exception as e:
            print(
                json.dumps(
                    {
                        "config": f"tm{tile_m}_tn{tile_n}",
                        "error": f"{type(e).__name__}: {e}"[:200],
                    }
                ),
                flush=True,
            )
            continue
        rec = {
            "config": f"tm{tile_m}_tn{tile_n}",
            "ms": round(ms, 3),
            "efficiency": round(t_xla / ms, 4),
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)

    if results:
        best = min(results, key=lambda r: r["ms"])
        print(json.dumps({"best": best, "xla_ms": round(t_xla, 3)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
