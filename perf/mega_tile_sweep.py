"""Megakernel weight-stream sweep: (tile_n/tile_k, nbuf) on the chip.

The decode ladder's floor is the per-step weight stream (~1.2 GB at
0.6B); the r3 ladder ran it at ~280 GB/s effective vs the 667 GB/s
probe-measured HBM rate. Two levers target the gap (see
``MegaConfig``): wider tiles (fewer per-tile control gaps) and a
deeper staging pipeline (``nbuf`` > 2 keeps DMAs in flight through
those gaps). This sweep times the SAME 32-step greedy chain (NS=8
launches, the ladder's mega_multi configuration) across configs and
cross-checks token equality against the baseline config.

Usage: python perf/mega_tile_sweep.py [--configs 1024:1024:2,1024:1024:4,...]
"""

import argparse
import json
import os
import sys
import time


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# tn:tk:nbuf[:fuse_norms[:cross_prefetch]] — baseline (the library
# defaults) first; then each lever added cumulatively so the deltas
# attribute: staging depth, tile width, norm fusion, cross-task
# prefetch. Tail candidates probe the edges of the space (deeper
# staging, wider K tiles, max-width N tiles) on top of the full lever
# stack — VMEM stays under the derived limit at 0.6B dims.
DEFAULT = ("1024:1024:2,1024:1024:4,2048:1024:4,"
           "1024:1024:4:1,1024:1024:4:1:1,2048:1024:4:1:1,"
           "1024:1024:6:1:1,2048:2048:4:1:1,3072:1024:4:1:1")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--configs", default=DEFAULT,
                   help="comma list of tile_n:tile_k:nbuf[:fuse_norms]")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--ns", type=int, default=8)
    p.add_argument("--model", default="Qwen/Qwen3-0.6B")
    p.add_argument("--q8", action="store_true",
                   help="sweep with weight-only int8 streams "
                        "(wq8=True on every config; results are NOT "
                        "written to MEGA_TUNED.json, which tunes the "
                        "bf16 headline rungs)")
    p.add_argument("--deadline-s", type=float, default=1800,
                   help="stop starting new configs past this wall "
                        "budget and finalize with what's measured — a "
                        "relay window must never end with ZERO tuning "
                        "because the sweep was killed mid-flight "
                        "(0 disables)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)
    t_start = time.time()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed
    from triton_distributed_tpu.runtime.utils import median_time

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained(args.model, ctx=ctx, max_length=1024)
    jax.block_until_ready(model.params)

    steps, ns = args.steps, args.ns
    if steps % ns:
        raise SystemExit(f"--ns {ns} must divide --steps {steps}")

    from perf._chain import multi_step_chain, prepare_decode_state

    tok0, cache0, s_max = prepare_decode_state(model)

    ref_chain = None
    all_match = True
    any_ok = False
    rows = []
    truncated = False
    for i, spec in enumerate(args.configs.split(",")):
        if (args.deadline_s and i > 0
                and time.time() - t_start > args.deadline_s):
            # Stop starting configs; rc stays 0 so the window queue
            # moves on to the ladder rather than re-paying the sweep.
            print(json.dumps({
                "deadline_s": args.deadline_s,
                "skipped_configs": args.configs.split(",")[i:],
            }), flush=True)
            truncated = True
            break
        label = spec
        try:
            cfg = MegaConfig.from_spec(spec)
            if args.q8:
                import dataclasses as _dc

                cfg = _dc.replace(cfg, wq8=True)
        except ValueError as e:
            # A malformed spec is an OPERATOR error, not a chip
            # failure: record it AND fail the run (bench.py's explicit-
            # override philosophy — a silently thinner A/B is invalid).
            print(json.dumps({"config": spec, "error": str(e)}), flush=True)
            all_match = False
            continue
        label = (f"tn{cfg.tile_n}_tk{cfg.tile_k}_nb{cfg.nbuf}"
                 + ("_fn" if cfg.fuse_norms else "")
                 + ("_xp" if cfg.cross_prefetch else "")
                 + ("_q8" if cfg.wq8 else ""))
        try:
            mega = MegaQwen3(model, cfg=cfg)
            once = multi_step_chain(
                mega.decode_multi_fn(1, s_max, ns), ns,
                mega._step_params(), tok0, cache0, steps,
            )
            chain = once()  # compile + warm
            if ref_chain is None:
                ref_chain = chain
            match = bool((chain == ref_chain).all())
            all_match = all_match and match
            any_ok = True
            sec = median_time(lambda: once())
            rows.append((cfg.spec(), sec / steps * 1e3, match, i == 0))
            print(json.dumps({
                "config": label,
                "ms_per_step": round(sec / steps * 1e3, 3),
                "tokens_match_baseline": match,
            }), flush=True)
        except Exception as e:  # keep sweeping past a failed compile
            print(json.dumps({
                "config": label,
                "error": f"{type(e).__name__}: {e}"[:220],
            }), flush=True)
            if i == 0:
                # The FIRST config is the trusted baseline every other
                # chain is checked against; without it, "matches" would
                # mean "matches an unverified candidate". Keep timing
                # the rest (data is still useful) but fail the run.
                all_match = False
    # Persist the winner for bench.py's mega rungs (TPU timings only —
    # a CPU smoke must never touch chip tuning). The file is
    # write-OR-REMOVE on every run with a valid baseline: a stale
    # winner that stopped qualifying (mismatch after a kernel change,
    # or no longer faster) must not keep steering the ladder.
    if (jax.devices()[0].platform != "cpu" and rows and rows[0][3]
            and not args.q8):  # q8 timings must not tune the bf16 rungs
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "MEGA_TUNED.json")
        base_ms = rows[0][1]
        best = min((r for r in rows if r[2]), key=lambda r: r[1])
        # A deadline-TRUNCATED sweep saw only a subset of the space: it
        # may improve an existing record but must never delete or
        # downgrade one a FULL sweep wrote (the remove below exists to
        # drop stale winners, and "stale" can only be judged by a full
        # re-measure).
        prior_ms = None
        if truncated:
            try:
                with open(path) as f:
                    prior = json.load(f)
                # Only an APPLICABLE prior can block the write — a
                # record for another chip/model is ignored by bench's
                # reader anyway (same gate as _tuned_mega_config).
                if (prior.get("device") == jax.devices()[0].device_kind
                        and prior.get("model") == args.model):
                    prior_ms = float(prior["ms_per_step"])
            except (OSError, ValueError, KeyError, TypeError):
                prior_ms = None
        if best[1] < base_ms * 0.98 and (  # >2% win, not noise
                prior_ms is None or best[1] < prior_ms):
            with open(path, "w") as f:
                json.dump({
                    "config": best[0],
                    "ms_per_step": round(best[1], 3),
                    "baseline_ms_per_step": round(base_ms, 3),
                    "written_by": "perf/mega_tile_sweep.py",
                    "truncated": truncated,
                    "device": jax.devices()[0].device_kind,
                    "model": args.model,
                }, f)
            print(json.dumps({"tuned": best[0], "written": path}), flush=True)
        elif os.path.exists(path) and not truncated:
            os.remove(path)
            print(json.dumps({"tuned": None, "removed": path}), flush=True)

    # A mismatching config computed wrong logits — its timing must not
    # be promotable from a green-looking run (mega_ns_sweep contract).
    return 0 if (any_ok and all_match) else 1


if __name__ == "__main__":
    raise SystemExit(main())
