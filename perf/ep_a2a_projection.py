"""EP all-to-all latency projection at the reference's headline config.

The reference's flagship number (BASELINE.md / `README.md:100-101`):
low-latency EP AllToAll dispatch at 128 tokens/rank, topk=8, hidden
7168, fp8 payloads, on 32x H800 — **137 us** (vs DeepEP's 182 us).

Single-chip hardware can't measure a 32-rank exchange, so this script
does what the reference's own `comm_perf_model.py` does: price the
wire. Every rank ships 128*topk routed token copies of 7168 fp8 bytes
(+ one f32 scale per token row — the per-row keepdims codec in
`ops/moe/ep_a2a.py` `_fp8_encode`, ~4/7168 overhead) split across 31
peers; on a TPU mesh the
intra-slice share rides ICI and the cross-slice share rides DCN. The
printed projection is the analytic floor for `ep_dispatch(payload=
"fp8")` at that config, alongside the measured reference baseline.

Usage: python perf/ep_a2a_projection.py [--ranks 32 --local 4]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tokens", type=int, default=128, help="tokens per rank")
    p.add_argument("--topk", type=int, default=8)
    p.add_argument("--hidden", type=int, default=7168)
    p.add_argument("--ranks", type=int, default=32)
    p.add_argument("--local", type=int, default=4,
                   help="ranks per ICI slice (v5e host = 4 chips)")
    # Explicit default: chip_spec(None) asks jax.devices(), which hangs
    # forever when the relay is down.
    p.add_argument("--chip", default="v5e")
    args = p.parse_args(argv)

    from triton_distributed_tpu.tools.perf_model import (
        _ring_bw_gbs,
        chip_spec,
    )

    spec = chip_spec(args.chip)
    # fp8 payload + ONE f32 scale per token row (the codec in
    # ops/moe/ep_a2a.py _fp8_encode: axis=-1 keepdims reduction).
    row_bytes = args.hidden * 1 + 4
    routed = args.tokens * args.topk  # token copies leaving each rank
    # Uniform routing: (ranks-1)/ranks of copies leave the rank; the
    # cross-slice share rides DCN.
    off_rank = routed * (args.ranks - 1) / args.ranks
    local = min(args.local, args.ranks)
    off_slice_frac = (args.ranks - local) / max(args.ranks - 1, 1)
    ici_bytes = off_rank * (1 - off_slice_frac) * row_bytes
    dcn_bytes = off_rank * off_slice_frac * row_bytes
    ici_us = ici_bytes / (_ring_bw_gbs(spec, True) * 1e9) * 1e6
    # dcn_gbs is PER HOST: the slice's `local` ranks share one NIC.
    dcn_us = dcn_bytes * local / (spec.dcn_gbs * 1e9) * 1e6
    total_us = max(ici_us, 1.0) + dcn_us  # DCN serializes after ICI

    print(json.dumps({
        "config": {
            "tokens_per_rank": args.tokens, "topk": args.topk,
            "hidden": args.hidden, "payload": "fp8+scales",
            "ranks": args.ranks, "ranks_per_slice": local,
            "chip": spec.name,
        },
        "wire_bytes_per_rank": int(off_rank * row_bytes),
        "projection_us": {
            "ici": round(ici_us, 1), "dcn": round(dcn_us, 1),
            "total": round(total_us, 1),
        },
        "reference_us": {"triton_distributed_32xH800": 137,
                         "deepep_32xH800": 182},
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
