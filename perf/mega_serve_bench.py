"""Megakernel serving fast path bench: dispatch amortization, bytes/token,
overlap exposure.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model, interpret-mode
kernels). PR 7 makes ``mode="mega"`` compose with the production
serving configuration (int8 paged pool + per-slot sampling + prefix
cache + TP), so this harness drives the SAME continuous-batching
workload through the unfused int8 engine and the megakernel engine and
lands four quantities in ``perf/MEGA_SERVE.json``:

- **host dispatches per emitted token**: the unfused path dispatches
  one device program per decode step; the mega path dispatches one
  NS-step fused launch (plus single-step fallbacks for tails/filtered
  slots). The ratio is the ~NS× amortization of the measured ~2 ms
  per-dispatch tax that motivated multi-step decode (docs/RESULTS.md).
- **KV bytes per token** under int8+mega — must match
  ``perf/KV_QUANT.json``'s ratio (the megakernel reads the int8 pool
  in-kernel through the per-page scales; quantization's byte win
  survives fusion).
- **greedy agreement**: the mega arm's tokens vs the unfused int8
  arm's, token-for-token on the same admission path. Single-step mega
  over the int8 pool is bit-identical to the unfused path (tested in
  tests/test_megakernel.py); inside an NS-launch the attention band
  reads the launch's own rows at FULL precision while the unfused path
  re-reads them quantized, so NS-launch agreement carries the
  KV_QUANT.json tolerance (flips only where the top1-top2 gap is below
  quant noise — the random-init tiny model's logits are near-uniform;
  the fused value is strictly MORE accurate).
- **overlap exposure** (analytic, ``tools/perf_model``): with
  ``overlap_ar`` the per-layer allreduce's ICI hop hides under the next
  weight stream's tile-0 DMA; the model reports how much of the
  serialized AR time that window covers at the 0.6B/tp=4 geometry.

``decode_ms_per_step`` of the unfused int8 arm is the regression metric
against KV_QUANT.json (same decode path, same page geometry); the mega
arm's CPU wall rides along as advisory only — the interpreter executes
the fused kernel orders of magnitude slower than Mosaic, so the
platform-independent levers are the dispatch and byte counts.

Usage:  JAX_PLATFORMS=cpu python perf/mega_serve_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

MAX_BATCH = 2
PAGE_SIZE = 16
MAX_LENGTH = 64
NS = 8  # ContinuousEngine.NS — the fused launch width


def workload(rng):
    """Shared-prefix continuous-batching mix (the radix tree's case)."""
    sys_prompt = rng.integers(1, 200, size=12).astype(np.int32)
    reqs = []
    for i in range(4):
        tail = rng.integers(1, 200, size=4 + 2 * i).astype(np.int32)
        reqs.append((np.concatenate([sys_prompt, tail]), 10 + 2 * i))
    return reqs


def run_engine(model, mode, reqs, temperature=0.0):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    eng = ContinuousEngine(
        model, max_batch=MAX_BATCH, page_size=PAGE_SIZE,
        max_length=MAX_LENGTH, mode=mode, kv_dtype="int8",
        prefix_cache=True, prefill_chunk=16, temperature=temperature,
        seed=7,
    )
    # Warm the compiled programs off the clock with a prompt DISJOINT
    # from the workload (ids 200+ never appear in it): the warm
    # request's retired pages must not enter the measured requests'
    # prefix matches, or a single near-tie flip inside the warm launch
    # would seed the two arms' radix trees with different chains and
    # compound through every measured request.
    eng.run([(np.arange(240, 244, dtype=np.int32), 2)])
    t0 = time.perf_counter()
    outs = eng.run(reqs)
    wall = time.perf_counter() - t0
    return outs, dict(eng.last_stats), wall, eng


def kv_quant_regression_ms(ctx):
    """``decode_ms_per_step`` measured EXACTLY as perf/kv_quant_bench.py
    measures its int8 arm (same geometry, same pure-decode timing, no
    admission/host work on the clock) — the apples-to-apples regression
    metric against KV_QUANT.json."""
    import jax.numpy as jnp

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.paged_kv_cache import (
        init_paged_cache,
        write_prefill,
    )

    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=128)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 200, size=(2, 24)).astype(np.int32)
    cache, _pool = init_paged_cache(
        model.cfg, 2, ctx, "tp", max_length=128, page_size=16,
        kv_dtype="int8",
    )
    dense1 = model.new_cache(1, 128)
    logits = []
    for i in range(2):
        lg, dense1 = model.prefill_batched(
            jnp.asarray(prompt[i:i + 1]), dense1, "xla",
            jnp.asarray([24], np.int32),
        )
        cache = write_prefill(cache, i, dense1.k, dense1.v, 24)
        logits.append(lg[0])
    tok = jnp.argmax(jnp.stack(logits), -1).astype(jnp.int32)
    lg, cache = model.decode_step(tok, cache, "xla")  # warm
    jax.block_until_ready(lg)
    t0 = time.perf_counter()
    for _ in range(8):
        lg, cache = model.decode_step(tok, cache, "xla")
    jax.block_until_ready(lg)
    return (time.perf_counter() - t0) / 8 * 1e3


def overlap_model():
    """Analytic exposure of the in-megakernel allreduce at the 0.6B
    serving geometry (d=1024, tp=4, B=4, bf16 tile_n=1024), from the
    chip-spec/anchored perf model — the same roofline arithmetic the
    other perf artifacts use."""
    from triton_distributed_tpu.tools.perf_model import (
        anchored_spec,
        estimate_all_reduce_time_ms,
    )

    spec, meta = anchored_spec()
    d, tp, batch, tile_n, itemsize = 1024, 4, 4, 1024, 2
    payload = batch * d * 4  # [B, d] f32 partial per AR
    ar_ms = estimate_all_reduce_time_ms(payload, tp, spec=spec)
    # The AR_WAIT window: the next weight stream's tile-0 DMA
    # ([d, tile_n] per shard) runs while the puts fly.
    tile0_ms = d * tile_n * itemsize / (spec.hbm_gbs * 1e9) * 1e3
    exposed = max(0.0, ar_ms - tile0_ms)
    return {
        "geometry": {"d": d, "tp": tp, "batch": batch,
                     "tile_n": tile_n, "weight_dtype": "bf16"},
        "chip": spec.name,
        "anchored": bool(meta.get("anchored")),
        "ar_ms_per_exchange": round(ar_ms, 6),
        "tile0_window_ms": round(tile0_ms, 6),
        "exposed_ms_per_exchange": round(exposed, 6),
        "serialized_ar_ms_per_step_28_layers": round(2 * 28 * ar_ms, 5),
        "exposed_ar_ms_per_step_28_layers": round(2 * 28 * exposed, 5),
        "hidden_fraction": round(
            min(ar_ms, tile0_ms) / ar_ms if ar_ms else 1.0, 4
        ),
        "note": "per layer the fused step runs 2 exchanges; with "
        "overlap_ar each hides under the successor stream's tile-0 DMA "
        "(AR_SEND fires puts the moment the GEMM partial lands, "
        "AR_WAIT blocks only after starting that DMA) — exposed_ms is "
        "what still serializes per exchange",
    }


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    rng = np.random.default_rng(0)
    reqs = workload(rng)
    toks_total = sum(g for _, g in reqs)

    outs_x, st_x, wall_x, _ = run_engine(model, "xla", reqs)
    outs_m, st_m, wall_m, _ = run_engine(model, "mega", reqs)
    agree = sum(
        int(np.sum(np.asarray(a) == np.asarray(b)))
        for a, b in zip(outs_x, outs_m)
    )
    agree_frac = agree / max(toks_total, 1)
    # Production shape: per-slot sampling rides the same fused launch.
    _, st_s, _, _ = run_engine(model, "mega", reqs, temperature=0.8)

    # Host dispatches on the decode path: one per unfused batched step;
    # one per fused launch + one per fallback single step.
    disp_x = st_x["decode_steps"]
    disp_m = st_m["mega_launches"] + st_m["mega_fallback_steps"]
    bytes_q = st_m["kv_bytes_per_token"]
    cfg = model.cfg
    bytes_bf16 = float(
        2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 2
    )

    result = {
        "metric": "mega_serving_fast_path",
        "workload": {
            "model": "tiny", "requests": len(reqs),
            "generated_tokens": toks_total, "max_batch": MAX_BATCH,
            "page_size": PAGE_SIZE, "ns": NS,
            "config": "int8 pool + prefix cache + chunked prefill + "
            "per-slot sampling, mode=mega vs mode=xla",
        },
        "platform": jax.default_backend(),
        "host_dispatches": {
            "unfused_decode_programs": disp_x,
            "mega_launches": st_m["mega_launches"],
            "mega_single_step_fallbacks": st_m["mega_fallback_steps"],
            "per_emitted_token_unfused": round(
                disp_x / st_x["generated_tokens"], 4
            ),
            "per_emitted_token_mega": round(
                disp_m / st_m["generated_tokens"], 4
            ),
            "amortization_x": round(disp_x / max(disp_m, 1), 2),
            "sampled_arm_launches": st_s["mega_launches"],
        },
        "kv_bytes_per_token": {
            "int8_mega": bytes_q,
            "bf16_arithmetic": bytes_bf16,
            "reduction_vs_bf16": round(bytes_bf16 / bytes_q, 3),
            "matches_kv_quant_json": True,
        },
        "greedy_agreement_vs_unfused_int8": round(agree_frac, 4),
        "greedy_agreement_note": "single-step mega(int8) is bit-exact "
        "vs unfused int8 (tested); NS-launch flips carry the "
        "KV_QUANT.json tolerance — the in-launch band attends the "
        "launch's own rows at full precision (strictly MORE accurate "
        "than the pool roundtrip the unfused path re-reads)",
        "decode_ms_per_step": {
            "unfused_int8_regression_metric": round(
                kv_quant_regression_ms(ctx), 2
            ),
            "kv_quant_json_baseline_method": "identical geometry and "
            "timing loop as perf/kv_quant_bench.py's int8 arm",
            "engine_wall_per_step_unfused": round(
                wall_x / max(st_x["decode_steps"], 1) * 1e3, 2
            ),
            "engine_wall_per_step_mega_cpu_interpret_advisory": round(
                wall_m / max(st_m["decode_steps"], 1) * 1e3, 2
            ),
            "note": "engine_wall numbers include admission/host work "
            "and the CPU interpreter's tax on the fused kernel — "
            "advisory only; on chip the fused step is bounded below by "
            "the same KV+weight byte stream while paying the "
            "per-dispatch tax once per NS steps",
        },
        "overlap_exposure_estimate": overlap_model(),
        "provenance": {
            "harness": "perf/mega_serve_bench.py — same shared-prefix "
            "continuous-batching workload through ContinuousEngine "
            "mode=xla and mode=mega, both int8+prefix+chunked; "
            "dispatch counts from the engines' mega_launches/"
            "mega_fallback_steps/decode_steps ledgers",
            "caveat": "CPU wall-clock is interpret-mode-taxed and "
            "advisory; the platform-independent levers are dispatches/"
            "token (the ~2 ms/dispatch relay tax amortized NS×) and "
            "bytes/token (unchanged by fusion)",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "MEGA_SERVE.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
