"""Megakernel serving fast path bench: dispatch amortization, bytes/token,
overlap exposure.

CPU-runnable (``JAX_PLATFORMS=cpu``, tiny model, interpret-mode
kernels). PR 7 makes ``mode="mega"`` compose with the production
serving configuration (int8 paged pool + per-slot sampling + prefix
cache + TP), so this harness drives the SAME continuous-batching
workload through the unfused int8 engine and the megakernel engine and
lands four quantities in ``perf/MEGA_SERVE.json``:

- **host dispatches per emitted token**: the unfused path dispatches
  one device program per decode step; the mega path dispatches one
  NS-step fused launch (plus single-step fallbacks for tails/filtered
  slots). The ratio is the ~NS× amortization of the measured ~2 ms
  per-dispatch tax that motivated multi-step decode (docs/RESULTS.md).
- **KV bytes per token** under int8+mega — must match
  ``perf/KV_QUANT.json``'s ratio (the megakernel reads the int8 pool
  in-kernel through the per-page scales; quantization's byte win
  survives fusion).
- **greedy agreement**: the mega arm's tokens vs the unfused int8
  arm's, token-for-token on the same admission path. Single-step mega
  over the int8 pool is bit-identical to the unfused path (tested in
  tests/test_megakernel.py); inside an NS-launch the attention band
  reads the launch's own rows at FULL precision while the unfused path
  re-reads them quantized, so NS-launch agreement carries the
  KV_QUANT.json tolerance (flips only where the top1-top2 gap is below
  quant noise — the random-init tiny model's logits are near-uniform;
  the fused value is strictly MORE accurate).
- **overlap exposure** (analytic, ``tools/perf_model``): with
  ``overlap_ar`` the per-layer allreduce's ICI hop hides under the next
  weight stream's tile-0 DMA; the model reports how much of the
  serialized AR time that window covers at the 0.6B/tp=4 geometry.

``decode_ms_per_step`` of the unfused int8 arm is the regression metric
against KV_QUANT.json (same decode path, same page geometry); the mega
arm's CPU wall rides along as advisory only — the interpreter executes
the fused kernel orders of magnitude slower than Mosaic, so the
platform-independent levers are the dispatch and byte counts.

Usage:  JAX_PLATFORMS=cpu python perf/mega_serve_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

MAX_BATCH = 2
PAGE_SIZE = 16
MAX_LENGTH = 64
NS = 8  # ContinuousEngine.NS — the fused launch width

# Resident/NS-sweep arms (PR 18): longer generations so every arm runs
# multiple rounds (NS=32 needs headroom: 12-token prompt + 64 generated
# + one projected NS=32 launch stays under 128).
SWEEP_MAX_LENGTH = 128
SWEEP_GEN = 64
SWEEP_NS = (8, 16, 32)


def workload(rng):
    """Shared-prefix continuous-batching mix (the radix tree's case)."""
    sys_prompt = rng.integers(1, 200, size=12).astype(np.int32)
    reqs = []
    for i in range(4):
        tail = rng.integers(1, 200, size=4 + 2 * i).astype(np.int32)
        reqs.append((np.concatenate([sys_prompt, tail]), 10 + 2 * i))
    return reqs


def run_engine(model, mode, reqs, temperature=0.0):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    eng = ContinuousEngine(
        model, max_batch=MAX_BATCH, page_size=PAGE_SIZE,
        max_length=MAX_LENGTH, mode=mode, kv_dtype="int8",
        prefix_cache=True, prefill_chunk=16, temperature=temperature,
        seed=7,
    )
    # Warm the compiled programs off the clock with a prompt DISJOINT
    # from the workload (ids 200+ never appear in it): the warm
    # request's retired pages must not enter the measured requests'
    # prefix matches, or a single near-tie flip inside the warm launch
    # would seed the two arms' radix trees with different chains and
    # compound through every measured request.
    eng.run([(np.arange(240, 244, dtype=np.int32), 2)])
    t0 = time.perf_counter()
    outs = eng.run(reqs)
    wall = time.perf_counter() - t0
    return outs, dict(eng.last_stats), wall, eng


def sweep_workload(rng):
    """Steady-state decode mix for the NS sweep: MAX_BATCH requests
    admitted up front (no mid-stream prefill on the clock), long
    generations so every NS runs several rounds — the host gaps between
    launches then measure pure dispatch/bookkeeping, which is exactly
    what the resident pipeline is supposed to hide."""
    return [
        (rng.integers(1, 200, size=12).astype(np.int32), SWEEP_GEN)
        for _ in range(MAX_BATCH)
    ]


def run_sweep_arm(model, reqs, *, ns, resident, temperature=0.0,
                  top_p=1.0, top_k=0):
    """One NS-sweep arm: bf16 pool (greedy arms must be BIT-identical
    to the unfused engine), device tracer on so the launch ledger
    carries (t0, wall_s) per launch. Returns outputs, stats, and the
    tracer-measured host-dispatch metrics."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.obs.kernel_trace import validate_ring

    eng = ContinuousEngine(
        model, max_batch=MAX_BATCH, page_size=PAGE_SIZE,
        max_length=SWEEP_MAX_LENGTH, mode="mega", prefix_cache=True,
        temperature=temperature, top_p=top_p, top_k=top_k, seed=7,
        kernel_trace=True, ns=ns, resident=resident,
    )
    # Warm the compiled programs off the clock (disjoint prompt ids —
    # same convention as run_engine).
    eng.run([(np.arange(240, 244, dtype=np.int32), 2)])
    n0 = eng._trace_launch_n
    outs = eng.run(reqs)
    st = dict(eng.last_stats)
    launches = [ln for ln in eng.kernel_trace_launches() if ln.launch > n0]
    # Ring-validation gate: every measured launch's device ring must be
    # structurally clean, and on the resident arm the RING_POLL task
    # must have observed exactly the doorbell the host published for
    # that round (a stale snapshot here would mean the kernel scheduled
    # against a ring state the host had already moved past).
    doorbells = 0
    for ln in launches:
        viol = validate_ring(ln.get_records(), doorbell=ln.doorbell)
        assert not viol, f"ns={ns} resident={resident}: {viol}"
        doorbells += ln.doorbell is not None
    if resident:
        assert doorbells > 0, "resident arm recorded no doorbell"
    # Host-dispatch gap: wall time between one launch's drain and the
    # next launch's issue — admission, planning, token routing, trace
    # decode. The resident pipeline issues round i+1 BEFORE draining
    # round i, so its gaps collapse toward zero.
    gap_s, pairs = 0.0, 0
    for a, b in zip(launches, launches[1:]):
        if b.launch == a.launch + 1:
            gap_s += max(0.0, b.t0 - (a.t0 + a.wall_s))
            pairs += 1
    toks = max(st["generated_tokens"], 1)
    return outs, st, {
        "ns": ns,
        "resident": bool(resident),
        "launches": st["mega_launches"],
        "single_step_fallbacks": st["mega_fallback_steps"],
        "resident_rounds": st["mega_resident_rounds"],
        "ring_doorbells": st["mega_ring_doorbells"],
        "traced_launches": len(launches),
        "gap_pairs": pairs,
        "host_dispatch_us_per_token": round(gap_s * 1e6 / toks, 1),
        "launches_per_token": round(
            (st["mega_launches"] + st["mega_fallback_steps"]) / toks, 4
        ),
    }


def sampled_distribution_gate(model):
    """Distribution-preservation proof for the in-kernel top-k/top-p
    filter, asserted BEFORE any bench number is recorded: over an
    NS-step fused launch, the kernel's bisection filter + gumbel argmax
    must emit EXACTLY the host reference — chained single-step decode,
    ``sampling.filter_logits`` keep-set, argmax over the same
    temperature-scaled noise. Bit-exact equality means the fused path
    samples from the identical filtered distribution (same keep-set,
    same perturbation), not an approximation of it."""
    import dataclasses

    import jax.numpy as jnp

    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.models.paged_kv_cache import (
        init_paged_cache,
        write_prefill,
    )
    from triton_distributed_tpu.models.sampling import filter_logits

    B, NS_G, page, s_max = 2, 4, 16, 64
    V = model.cfg.vocab_size
    v_pad = model.params.lm_head.shape[1]
    cache = model.new_cache(B, max_length=s_max)
    step = model.decode_fn("xla")
    for toks in ([3, 5], [7, 11], [13, 17]):
        _, cache = step(model.params, jnp.asarray(toks, jnp.int32), cache)
    pages_per_seq = s_max // page
    paged, pool = init_paged_cache(
        model.cfg, B, model.ctx, max_length=s_max, page_size=page,
        num_pages=B * pages_per_seq + 1, assign_pages=False,
    )
    pool.allocate(1)  # page 0 = reserved trash page (engine convention)
    table = np.asarray(
        [pool.allocate(pages_per_seq) for _ in range(B)], np.int32
    )
    paged = dataclasses.replace(paged, page_table=jnp.asarray(table))
    for b in range(B):
        paged = write_prefill(
            paged, b, cache.k[:, b:b + 1], cache.v[:, b:b + 1],
            int(cache.kv_len[b]),
        )
    mega = MegaQwen3(model)
    tok0 = jnp.asarray([19, 23], jnp.int32)
    temps = np.asarray([0.7, 1.3], np.float32)
    tks = np.asarray([5, 0], np.int32)        # row0 top-k; row1 off
    tps = np.asarray([1.0, 0.8], np.float32)  # row1 top-p
    noise = jnp.asarray(temps)[None, :, None] * jax.random.gumbel(
        jax.random.key(7), (NS_G, B, v_pad), jnp.float32
    )
    sampcfg = np.zeros((B, 4), np.float32)
    for b in range(B):
        t, k, p = float(temps[b]), int(tks[b]), float(tps[b])
        sampcfg[b] = [1.0 / t, k if 0 < k < V else V,
                      max(min(p, 1.0), 1e-6), 1.0]
    # Host reference: chained single-step, keep-set from filter_logits.
    import jax as _jax

    p_ref = _jax.tree.map(jnp.copy, paged)
    t = tok0
    ref = []
    for i in range(NS_G):
        lg, p_ref = mega.decode_step(t, p_ref)
        nxt = []
        for b in range(B):
            filt = filter_logits(
                lg[b], float(temps[b]), float(tps[b]), int(tks[b])
            )
            keep = np.isfinite(np.asarray(filt))
            score = np.where(
                keep, np.asarray(lg[b] + noise[i, b, :V]), -np.inf
            )
            nxt.append(int(np.argmax(score)))
        t = jnp.asarray(nxt, jnp.int32)
        ref.append(np.asarray(t))
    fn = mega.decode_multi_fn(
        B, s_max, NS_G, sampled=True, page=page,
        num_pages=int(paged.k_pages.shape[1]), valid_arg=True,
        filtered=True,
    )
    mtoks, _, _ = fn(
        model.params, tok0, _jax.tree.map(jnp.copy, paged),
        jnp.full((B,), NS_G, jnp.int32), noise, jnp.asarray(sampcfg),
    )
    np.testing.assert_array_equal(np.asarray(mtoks), np.stack(ref))
    return {
        "steps_checked": NS_G, "rows": B,
        "knobs": "row0 T=0.7 top_k=5; row1 T=1.3 top_p=0.8",
        "bit_exact_vs_host_filter_logits": True,
    }


def resident_sweep():
    """The PR 18 section: tp=1 context (in-kernel filtering is
    single-rank), bf16 pool, NS sweep + resident arm. Every gate
    asserts before the caller records a number."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained(
        "tiny", ctx=ctx, max_length=SWEEP_MAX_LENGTH
    )
    rng = np.random.default_rng(1)
    reqs = sweep_workload(rng)

    # Unfused greedy golds: the bit-identity gate's reference.
    gold_eng = ContinuousEngine(
        model, max_batch=MAX_BATCH, page_size=PAGE_SIZE,
        max_length=SWEEP_MAX_LENGTH, mode="xla", prefix_cache=True,
    )
    golds = gold_eng.run([(p.copy(), g) for p, g in reqs])

    arms = []
    base_us = None
    res_us = None
    for ns in SWEEP_NS:
        outs, _st, m = run_sweep_arm(model, reqs, ns=ns, resident=False)
        # Greedy bit-identity gate: bf16 mega tokens == unfused tokens,
        # token for token, at every NS.
        for got, gold in zip(outs, golds):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(gold))
        assert m["single_step_fallbacks"] == 0, m
        if ns == 8:
            base_us = m["host_dispatch_us_per_token"]
        arms.append(m)
    for ns in (8, 32):
        outs, _st, m = run_sweep_arm(model, reqs, ns=ns, resident=True)
        for got, gold in zip(outs, golds):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(gold))
        assert m["single_step_fallbacks"] == 0, m
        assert m["resident_rounds"] > 0, m
        if ns == 32:
            res_us = m["host_dispatch_us_per_token"]
        arms.append(m)

    # Sampled arm: per-slot top-k/top-p rides the SAME fused resident
    # launch through the in-kernel bisection filter — the rounds that
    # used to be single-step fallbacks. The acceptance gate: the
    # fallback counter reads zero on a pure-sampled workload.
    dist_gate = sampled_distribution_gate(model)
    _outs, st_s, m_s = run_sweep_arm(
        model, reqs, ns=8, resident=True,
        temperature=0.8, top_k=5, top_p=0.9,
    )
    assert st_s["mega_fallback_steps"] == 0, st_s
    assert st_s["mega_filtered_rounds"] > 0, st_s

    # The tentpole gate: the resident arm's tracer-measured host
    # dispatch cost per token must drop >= 2x vs the NS=8 baseline.
    assert base_us is not None and res_us is not None
    # A fully-pipelined resident arm measures 0.0 gap (every issue
    # precedes the prior drain); floor at the metric's 0.1 us rounding
    # unit so the recorded ratio reads "at least this much".
    drop = base_us / max(res_us, 0.1)
    assert drop >= 2.0, (
        f"resident host-dispatch drop {drop:.2f}x < 2x "
        f"(baseline {base_us} us/tok, resident {res_us} us/tok)"
    )

    mesh_mod.finalize_distributed()
    return {
        "workload": {
            "requests": MAX_BATCH, "gen_len": SWEEP_GEN,
            "max_length": SWEEP_MAX_LENGTH, "pool": "bf16",
            "note": "all requests admitted up front — gaps between "
            "launches measure pure host dispatch/bookkeeping",
        },
        "arms": arms,
        "host_dispatch_us_per_token_ns8": base_us,
        "host_dispatch_us_per_token_resident": res_us,
        "resident_dispatch_drop_x": round(drop, 2),
        "drop_note": "resident gap floored at the metric's 0.1 us "
        "rounding unit — a 0.0 reading means every launch issued "
        "before the previous one drained, so the true drop is bounded "
        "below by the recorded ratio",
        "sampled_resident_arm": {
            "knobs": "temperature=0.8 top_k=5 top_p=0.9 (engine-wide)",
            "filtered_rounds": st_s["mega_filtered_rounds"],
            "single_step_fallbacks": st_s["mega_fallback_steps"],
            "fallback_metric": "tdt_mega_single_step_fallbacks_total",
            "host_dispatch_us_per_token":
                m_s["host_dispatch_us_per_token"],
        },
        "distribution_gate": dist_gate,
        "gates": "asserted before this file was written: greedy "
        "bit-identity vs the unfused engine on every arm, sampled "
        "bit-exactness vs the host filter_logits reference, "
        "validate_ring gap-free (+doorbell match on resident rings), "
        "zero single-step fallbacks, resident dispatch drop >= 2x",
    }


def kv_quant_regression_ms(ctx):
    """``decode_ms_per_step`` measured EXACTLY as perf/kv_quant_bench.py
    measures its int8 arm (same geometry, same pure-decode timing, no
    admission/host work on the clock) — the apples-to-apples regression
    metric against KV_QUANT.json."""
    import jax.numpy as jnp

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.paged_kv_cache import (
        init_paged_cache,
        write_prefill,
    )

    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=128)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 200, size=(2, 24)).astype(np.int32)
    cache, _pool = init_paged_cache(
        model.cfg, 2, ctx, "tp", max_length=128, page_size=16,
        kv_dtype="int8",
    )
    dense1 = model.new_cache(1, 128)
    logits = []
    for i in range(2):
        lg, dense1 = model.prefill_batched(
            jnp.asarray(prompt[i:i + 1]), dense1, "xla",
            jnp.asarray([24], np.int32),
        )
        cache = write_prefill(cache, i, dense1.k, dense1.v, 24)
        logits.append(lg[0])
    tok = jnp.argmax(jnp.stack(logits), -1).astype(jnp.int32)
    lg, cache = model.decode_step(tok, cache, "xla")  # warm
    jax.block_until_ready(lg)
    t0 = time.perf_counter()
    for _ in range(8):
        lg, cache = model.decode_step(tok, cache, "xla")
    jax.block_until_ready(lg)
    return (time.perf_counter() - t0) / 8 * 1e3


def overlap_model():
    """Analytic exposure of the in-megakernel allreduce at the 0.6B
    serving geometry (d=1024, tp=4, B=4, bf16 tile_n=1024), from the
    chip-spec/anchored perf model — the same roofline arithmetic the
    other perf artifacts use."""
    from triton_distributed_tpu.tools.perf_model import (
        anchored_spec,
        estimate_all_reduce_time_ms,
    )

    spec, meta = anchored_spec()
    d, tp, batch, tile_n, itemsize = 1024, 4, 4, 1024, 2
    payload = batch * d * 4  # [B, d] f32 partial per AR
    ar_ms = estimate_all_reduce_time_ms(payload, tp, spec=spec)
    # The AR_WAIT window: the next weight stream's tile-0 DMA
    # ([d, tile_n] per shard) runs while the puts fly.
    tile0_ms = d * tile_n * itemsize / (spec.hbm_gbs * 1e9) * 1e3
    exposed = max(0.0, ar_ms - tile0_ms)
    return {
        "geometry": {"d": d, "tp": tp, "batch": batch,
                     "tile_n": tile_n, "weight_dtype": "bf16"},
        "chip": spec.name,
        "anchored": bool(meta.get("anchored")),
        "ar_ms_per_exchange": round(ar_ms, 6),
        "tile0_window_ms": round(tile0_ms, 6),
        "exposed_ms_per_exchange": round(exposed, 6),
        "serialized_ar_ms_per_step_28_layers": round(2 * 28 * ar_ms, 5),
        "exposed_ar_ms_per_step_28_layers": round(2 * 28 * exposed, 5),
        "hidden_fraction": round(
            min(ar_ms, tile0_ms) / ar_ms if ar_ms else 1.0, 4
        ),
        "note": "per layer the fused step runs 2 exchanges; with "
        "overlap_ar each hides under the successor stream's tile-0 DMA "
        "(AR_SEND fires puts the moment the GEMM partial lands, "
        "AR_WAIT blocks only after starting that DMA) — exposed_ms is "
        "what still serializes per exchange",
    }


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM

    # Resident/NS sweep first: it needs its own tp=1 context (the
    # in-kernel filter is single-rank) and finalizes it before the
    # tp=4 arms below initialize theirs.
    resident = resident_sweep()

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    rng = np.random.default_rng(0)
    reqs = workload(rng)
    toks_total = sum(g for _, g in reqs)

    outs_x, st_x, wall_x, _ = run_engine(model, "xla", reqs)
    outs_m, st_m, wall_m, _ = run_engine(model, "mega", reqs)
    agree = sum(
        int(np.sum(np.asarray(a) == np.asarray(b)))
        for a, b in zip(outs_x, outs_m)
    )
    agree_frac = agree / max(toks_total, 1)
    # Production shape: per-slot sampling rides the same fused launch.
    _, st_s, _, _ = run_engine(model, "mega", reqs, temperature=0.8)

    # Host dispatches on the decode path: one per unfused batched step;
    # one per fused launch + one per fallback single step.
    disp_x = st_x["decode_steps"]
    disp_m = st_m["mega_launches"] + st_m["mega_fallback_steps"]
    bytes_q = st_m["kv_bytes_per_token"]
    cfg = model.cfg
    bytes_bf16 = float(
        2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 2
    )

    result = {
        "metric": "mega_serving_fast_path",
        "workload": {
            "model": "tiny", "requests": len(reqs),
            "generated_tokens": toks_total, "max_batch": MAX_BATCH,
            "page_size": PAGE_SIZE, "ns": NS,
            "config": "int8 pool + prefix cache + chunked prefill + "
            "per-slot sampling, mode=mega vs mode=xla",
        },
        "platform": jax.default_backend(),
        "host_dispatches": {
            "unfused_decode_programs": disp_x,
            "mega_launches": st_m["mega_launches"],
            "mega_single_step_fallbacks": st_m["mega_fallback_steps"],
            "per_emitted_token_unfused": round(
                disp_x / st_x["generated_tokens"], 4
            ),
            "per_emitted_token_mega": round(
                disp_m / st_m["generated_tokens"], 4
            ),
            "amortization_x": round(disp_x / max(disp_m, 1), 2),
            "sampled_arm_launches": st_s["mega_launches"],
        },
        "kv_bytes_per_token": {
            "int8_mega": bytes_q,
            "bf16_arithmetic": bytes_bf16,
            "reduction_vs_bf16": round(bytes_bf16 / bytes_q, 3),
            "matches_kv_quant_json": True,
        },
        "greedy_agreement_vs_unfused_int8": round(agree_frac, 4),
        "greedy_agreement_note": "single-step mega(int8) is bit-exact "
        "vs unfused int8 (tested); NS-launch flips carry the "
        "KV_QUANT.json tolerance — the in-launch band attends the "
        "launch's own rows at full precision (strictly MORE accurate "
        "than the pool roundtrip the unfused path re-reads)",
        "decode_ms_per_step": {
            "unfused_int8_regression_metric": round(
                kv_quant_regression_ms(ctx), 2
            ),
            "kv_quant_json_baseline_method": "identical geometry and "
            "timing loop as perf/kv_quant_bench.py's int8 arm",
            "engine_wall_per_step_unfused": round(
                wall_x / max(st_x["decode_steps"], 1) * 1e3, 2
            ),
            "engine_wall_per_step_mega_cpu_interpret_advisory": round(
                wall_m / max(st_m["decode_steps"], 1) * 1e3, 2
            ),
            "note": "engine_wall numbers include admission/host work "
            "and the CPU interpreter's tax on the fused kernel — "
            "advisory only; on chip the fused step is bounded below by "
            "the same KV+weight byte stream while paying the "
            "per-dispatch tax once per NS steps",
        },
        "overlap_exposure_estimate": overlap_model(),
        "resident_decode": resident,
        "provenance": {
            "harness": "perf/mega_serve_bench.py — same shared-prefix "
            "continuous-batching workload through ContinuousEngine "
            "mode=xla and mode=mega, both int8+prefix+chunked; "
            "dispatch counts from the engines' mega_launches/"
            "mega_fallback_steps/decode_steps ledgers",
            "caveat": "CPU wall-clock is interpret-mode-taxed and "
            "advisory; the platform-independent levers are dispatches/"
            "token (the ~2 ms/dispatch relay tax amortized NS×) and "
            "bytes/token (unchanged by fusion)",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "MEGA_SERVE.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
