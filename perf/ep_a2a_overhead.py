"""Single-chip kernel overhead of the device-initiated EP exchange.

VERDICT r2 missing #2 asked for a kernel measurement to replace the
pure wire projection (`perf/ep_a2a_projection.py`). A 32-rank exchange
needs 32 chips; at n=1 the measurable kernel-side costs are launch,
barrier entry, the SMEM splits read, and the full local-segment
block-copy loop (32 block DMAs at lossless capacity). The per-PEER
push/arrival/drain loops are `range(1, n)` — EMPTY at n=1 — so the
measured overhead is a LOWER BOUND on the kernel side of a real
multi-rank exchange, and the combined number reports as such:

    total_us_lower_bound ≈ overhead_us (measured, n=1)
                           + wire_us (projection, 8-rank)

Timing follows the relay rules (perf/OVERLAP_RESULTS.md): iterations
chained inside one jit with a non-foldable data dependency, fenced by
host fetch, medians over interleaved reps.

Usage: python perf/ep_a2a_overhead.py [--tokens 128 --topk 8 --hidden 7168]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tokens", type=int, default=128)
    p.add_argument("--topk", type=int, default=8)
    p.add_argument("--hidden", type=int, default=7168)
    p.add_argument("--iters", type=int, default=16)
    p.add_argument("--reps", type=int, default=7)
    p.add_argument("--blocks", default="32,64,128",
                   help="EP_BLOCK_ROWS values to A/B — the descriptor-"
                        "count lever (at the headline config the "
                        "uniform fill is 128 rows/dest, so block=128 "
                        "is ONE DMA per destination)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.ops.moe.ep_exchange import ep_exchange
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    n = 1
    t, k, d = args.tokens, args.topk, args.hidden
    cap = t * k  # lossless capacity
    row = d + 8 + (-(d + 8)) % 128  # fp8 payload + scale + expert id, padded
    rows = jnp.zeros((n, cap, row), jnp.uint8)
    splits = jnp.full((n,), cap, jnp.int32)

    def chained(x, iters, op):
        def body(_, carry):
            # Non-foldable carry: XOR the previous call's first byte in.
            xi = carry.at[0, 0, 0].set(carry[0, 0, 0] ^ jnp.uint8(1))
            return op(xi)

        out = jax.lax.fori_loop(0, iters, body, x)
        return jnp.sum(out.astype(jnp.int32))

    def make_run(iters, op):
        run = ctx.shard_map(
            lambda x: chained(x, iters, op)[None],
            in_specs=jax.sharding.PartitionSpec(None, None, None),
            out_specs=jax.sharding.PartitionSpec(None),
        )
        run = jax.jit(run)
        np.asarray(run(rows))  # compile + warm
        return run

    # Cost-attribution probes, same operand shapes and chaining: a
    # one-DMA whole-buffer copy kernel (per-call floor + one descriptor)
    # vs the real exchange (barrier + 2·ceil(cap/block) descriptors).
    # The difference isolates what the per-BLOCK machinery costs on this
    # platform vs what a pallas call of this shape costs at all.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from triton_distributed_tpu.ops.common import comm_pallas_call

    def _copy_kernel(x_ref, o_ref, sem):
        pltpu.make_async_copy(x_ref, o_ref, sem).start()
        pltpu.make_async_copy(x_ref, o_ref, sem).wait()

    def one_dma_copy(xi):
        return comm_pallas_call(
            _copy_kernel,
            jax.ShapeDtypeStruct(xi.shape, xi.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA],
            ctx=ctx,
        )(xi)

    from triton_distributed_tpu.runtime.utils import median_time

    def timed(run):
        return median_time(lambda: np.asarray(run(rows)), reps=args.reps)

    # Slope timing: T(3x) - T(x) over 2x iterations cancels the fixed
    # per-execution dispatch round-trip that total/iters folds in (the
    # r3 on-chip log's 5-7 ms "per-iter" readings moved with the relay's
    # load, not the kernel's — a fixed-cost signature; the reported
    # dispatch_us makes that fixed cost visible instead of folded).
    def slope(block):
        op = (lambda xi: ep_exchange(xi, splits, splits, axis="tp",
                                     ctx=ctx, block=block))
        s1 = timed(make_run(args.iters, op))
        s3 = timed(make_run(3 * args.iters, op))
        return (max((s3 - s1) / (2 * args.iters) * 1e6, 0.0),
                max(s1 * 1e6, 0.0))

    from triton_distributed_tpu.ops.moe.ep_exchange import EP_BLOCK_ROWS

    blocks = []
    for tok in args.blocks.split(","):
        tok = tok.strip()
        if not tok:
            continue
        b = int(tok)
        if b <= 0:
            raise SystemExit(f"--blocks values must be positive, got {b}")
        # Alignment keeps the A/B byte counts equal (ep_exchange pads
        # internally, so misaligned blocks WORK — they just move more
        # bytes); skip those from the comparison.
        if cap % b == 0:
            blocks.append(b)
    if not blocks:
        blocks = [EP_BLOCK_ROWS]  # old single-measurement behavior

    by_block = {b: slope(b) for b in blocks}
    best_block = min(by_block, key=lambda b: by_block[b][0])
    # Headline stays the library DEFAULT block (comparable across the
    # round's ONCHIP_r3.jsonl entries); the sweep's winner is reported
    # separately — promoting it is an explicit choice, not min-over-
    # noise selection.
    headline = by_block.get(EP_BLOCK_ROWS, by_block[best_block])
    overhead_us, base_us = headline
    dispatch_us = max(base_us - overhead_us * args.iters, 0.0)

    c1 = timed(make_run(args.iters, one_dma_copy))
    c3 = timed(make_run(3 * args.iters, one_dma_copy))
    copy_us = max((c3 - c1) / (2 * args.iters) * 1e6, 0.0)

    # Wire projection at the headline 8-rank intra-slice config.
    from perf.ep_a2a_projection import main as proj_main  # noqa: F401
    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        proj_main([
            "--ranks", "8", "--local", "8",
            "--tokens", str(t), "--topk", str(k), "--hidden", str(d),
        ])
    wire = json.loads(buf.getvalue())

    print(json.dumps({
        "config": {"tokens": t, "topk": k, "hidden": d,
                   "payload": "fp8+scales packed rows",
                   "row_bytes": int(row), "capacity": int(cap)},
        "platform": jax.devices()[0].platform,
        "kernel_overhead_us_n1_lower_bound": round(overhead_us, 1),
        "headline_block_rows": EP_BLOCK_ROWS if EP_BLOCK_ROWS in by_block
        else best_block,
        "best_block_rows": best_block,
        "best_overhead_us": round(by_block[best_block][0], 1),
        "overhead_us_by_block": {
            str(b): round(v[0], 1) for b, v in sorted(by_block.items())
        },
        "fixed_dispatch_us_per_execution": round(dispatch_us, 1),
        # Same shapes/chaining, ONE whole-buffer DMA: the platform's
        # per-pallas-call floor. exchange - copy ≈ the per-block
        # machinery (barrier + 2·ceil(cap/block) descriptors).
        "one_dma_copy_us": round(copy_us, 1),
        "wire_projection_us": wire["projection_us"],
        # Lower bound: the n=1 kernel cannot execute the per-peer
        # push/arrival/drain loops (empty at n=1) — see module docstring.
        "total_us_8rank_ici_lower_bound": round(
            overhead_us + wire["projection_us"]["total"], 1
        ),
        "reference_us": {"triton_dist_32xH800": 137, "deepep": 182},
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
