"""Beyond-HBM decode ladder: big-geometry wq8 decode from synthetic int8.

VERDICT r3 task 4 names Qwen3-8B-under-wq8 as a headline-class target a
16 GB v5e can hold — but the normal wq8 path quantizes FROM loaded bf16
params (~16.4 GB at 8B, over HBM). This harness uses
``MegaQwen3.quantized_init``: Q8Params are synthesized device-side as
int8 + scales directly (~9 GB at 8B incl. the bf16 embed), no bf16 tree
ever exists, and the decode ladder runs the production wq8 megakernel
over a random-content 512-token KV context.

Evidence produced per model geometry:
  * single- vs multi-step token cross-check (greedy chains over the
    same synthetic weights must agree bit-for-bit),
  * chained ms/step for the mega_q8 multi-step kernel,
  * achieved HBM GB/s vs the chip's peak (decode is bandwidth-bound;
    weight bytes here are the int8 stream + bf16 embed row reads).

The logits carry no knowledge (weights are random) — this is geometry/
bandwidth evidence, clearly labeled, complementing the real-checkpoint
1.7B e2e. Reference scale anchor: Qwen3-8B TP8 across 8×H800 = 640 GB
(``docs/mega_triton_kernel.md:27-31``); here the same geometry decodes
on ONE 16 GB chip.

Usage: python perf/ladder_q8_synth.py [--model Qwen/Qwen3-8B]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="Qwen/Qwen3-8B")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--ns", type=int, default=8)
    p.add_argument("--prompt", type=int, default=512)
    p.add_argument("--max-length", type=int, default=1024)
    p.add_argument("--cpu", action="store_true",
                   help="interpret-mode smoke at the tiny preset")
    args = p.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig
    from triton_distributed_tpu.models.config import get_config
    from triton_distributed_tpu.models.kv_cache import KVCache
    from triton_distributed_tpu.models.qwen import Qwen3
    from triton_distributed_tpu.runtime.mesh import initialize_distributed
    from triton_distributed_tpu.runtime.utils import median_time

    t0 = time.time()
    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    model_name = "tiny" if args.cpu else args.model
    cfg = get_config(model_name, max_length=args.max_length)
    model = Qwen3(cfg, ctx=ctx)  # params stay None — never built
    mega = MegaQwen3(model, cfg=MegaConfig(wq8=True))
    qp = mega.quantized_init(jax.random.PRNGKey(0))
    int8_bytes = sum(
        x.size for x in jax.tree.leaves(qp) if x.dtype == jnp.int8
    )
    embed_bytes = qp.embed.size * qp.embed.dtype.itemsize
    print(json.dumps({
        "model": model_name,
        "int8_weight_gb": round(int8_bytes / 1e9, 2),
        "embed_gb": round(embed_bytes / 1e9, 2),
        "synth_init_s": round(time.time() - t0, 1),
    }), flush=True)

    # Random-content context: the decode reads a full 512-token KV span
    # per layer exactly like a post-prefill cache would be read.
    prompt = min(args.prompt, args.max_length - args.steps)
    cache0 = model.new_cache(1)
    fill = jax.jit(lambda k, c: KVCache(
        k=jax.random.normal(k, c.k.shape, c.k.dtype) * 0.3,
        v=jax.random.normal(k, c.v.shape, c.v.dtype) * 0.3,
        kv_len=jnp.full_like(c.kv_len, prompt),
    ))
    cache0 = fill(jax.random.PRNGKey(1), cache0)
    jax.block_until_ready(cache0)
    tok0 = jnp.asarray([1], jnp.int32)
    s_max = int(cache0.k.shape[3])

    from perf._chain import multi_step_chain, single_step_chain

    steps, ns = args.steps, args.ns
    if steps % ns:
        raise SystemExit(f"--ns {ns} must divide --steps {steps}")
    sstep = mega.decode_fn(1, s_max)
    mstep = mega.decode_multi_fn(1, s_max, ns)

    s_seq = single_step_chain(sstep, qp, tok0, cache0, steps)()
    m_once = multi_step_chain(mstep, ns, qp, tok0, cache0, steps)
    m_seq = m_once()
    match = bool((s_seq == m_seq).all())
    print(json.dumps({
        "cross_check": "mega_q8_synth", "ok": match,
        "tokens": m_seq[:8].tolist(),
    }), flush=True)

    sec = median_time(m_once)
    ms = sec / steps * 1e3
    # Bytes touched per decode step: the whole int8 weight stream +
    # scales/norms (fp32/bf16, small) + the KV context read + one
    # embed row (negligible).
    kv_bytes = (
        2 * cfg.num_layers * cfg.num_kv_heads * prompt * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    step_bytes = int8_bytes + kv_bytes
    from bench import chip_peak_gbs

    peak = chip_peak_gbs(jax)
    gbs = step_bytes / (ms * 1e-3) / 1e9
    print(json.dumps({
        "metric": f"{model_name.split('/')[-1].lower()}_q8_synth_decode"
                  "_ms_per_step",
        "value": round(ms, 3),
        "unit": "ms",
        "platform": jax.devices()[0].platform,
        "steps_per_launch": ns,
        "achieved_gbs": round(gbs, 1),
        "vs_baseline": round(gbs / peak, 4),
        "floor_ms_at_peak": round(step_bytes / peak / 1e6, 2),
        "cross_check_ok": match,
        "note": "synthetic int8 weights (geometry/bandwidth evidence; "
                "real-checkpoint serving evidence = real_weights_e2e)",
    }), flush=True)
    return 0 if match else 1


if __name__ == "__main__":
    raise SystemExit(main())
