"""Durable KV tier bench: hit-rate/capacity curves, fault-back vs
re-prefill, and supervisor-restart recovery — every number gated on an
asserted bit-exact continuation.

Three arms (docs/serving.md "Tiered KV", docs/scale-out.md "Durable
snapshots"):

1. **Hit rate vs capacity** under a long-tail shared-prefix population
   (a handful of hot system prompts + a tail of colder templates) on a
   pool far smaller than the population. Without the tier, every
   eviction is re-prefilled (the PREFIX_CACHE.json regime under
   pressure); with it, evicted chains fault back on digest match. The
   sweep reports prefill work done / avoided at several tier
   capacities, with every arrival's output asserted equal to its
   tier-less golden BEFORE the number is recorded.
2. **Fault-back latency vs re-prefill**: wall time to admit a prompt
   whose prefix pages are tier-resident vs the same prompt cold
   (gen_len=1 arrivals, the prefix_cache_bench TTFT method). CPU
   wall-clock is interpret-taxed and advisory; the platform-
   independent lever is prefill tokens computed (fault-back writes
   pages, a device-side copy; re-prefill runs the model).
3. **Supervisor-restart recovery** (the PR 10 chaos suite's missing
   case): a stub process fleet with snapshot pulls persisted under
   ``resume_dir`` is killed mid-batch — children SIGKILLed, supervisor
   abandoned un-drained. A fresh supervisor boots over the same dir,
   the requests are re-submitted, and the arm records tokens restored
   from the durable snapshots vs regenerated — gated on every output
   matching the stub's pure-function golden bit-exactly.

Output follows perf/MEASURED.json conventions: one JSON object with a
``provenance`` block, printed to stdout and written to
``perf/KV_TIER.json``.

Usage:  JAX_PLATFORMS=cpu python perf/kv_tier_bench.py
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from triton_distributed_tpu.runtime import mesh as mesh_mod  # noqa: E402

# Long-tail shared-prefix population: a rotating HOT set whose chains
# (3 × 4 pages) exceed the 8-page pool — every hot revisit finds its
# chain ALREADY LRU-evicted, which is exactly the regime where
# "evicted = gone" re-prefills everything and a tier faults it back —
# plus a cold TAIL (seen once each; classic long-tail).
PAGE_SIZE = 16
MAX_LENGTH = 128
NUM_PAGES = 8           # pool: 2 chains' worth; the hot set needs 3
HOT_PREFIXES = 3
TAIL_PREFIXES = 3
PREFIX_TOKENS = 48      # 3 full pages per prefix
SUFFIX_TOKENS = 4
ARRIVALS = 18
TIER_CAPS = [0, 64 << 10, 32 << 20]  # off / starved / ample


def _population(rng):
    hots = [rng.integers(1, 200, size=PREFIX_TOKENS).astype(np.int32)
            for _ in range(HOT_PREFIXES)]
    tails = [rng.integers(1, 200, size=PREFIX_TOKENS).astype(np.int32)
             for _ in range(TAIL_PREFIXES)]
    arrivals = []
    t = 0
    for i in range(ARRIVALS):
        if i % 6 == 5 and t < len(tails):
            pre = tails[t]  # a cold tail request, seen exactly once
            t += 1
        else:
            pre = hots[i % HOT_PREFIXES]  # deterministic hot rotation
        suf = rng.integers(1, 200, size=SUFFIX_TOKENS).astype(np.int32)
        arrivals.append(np.concatenate([pre, suf]))
    return arrivals


def arm_hit_rate(model):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    rng = np.random.default_rng(0)
    arrivals = _population(rng)
    golden_eng = ContinuousEngine(
        model, max_batch=1, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
        prefix_cache=True,
    )
    golds = [golden_eng.run([(p, 1)])[0] for p in arrivals]

    sweep = []
    for cap in TIER_CAPS:
        eng = ContinuousEngine(
            model, max_batch=1, page_size=PAGE_SIZE,
            max_length=MAX_LENGTH, prefix_cache=True,
            num_pages=NUM_PAGES, tier_bytes=cap,
        )
        prefilled = hits = faults = spilled = tier_hits = 0
        for i, p in enumerate(arrivals):
            out = eng.run([(p, 1)])[0]
            np.testing.assert_array_equal(out, golds[i])  # the gate
            st = eng.last_stats
            prefilled += st["prefill_tokens"]
            hits += st["prefix_hit_tokens"]
            faults += st["tier_faults"]
            tier_hits += st["tier_hits"]
            spilled += st["tier_spilled_pages"]
        total_prompt = sum(len(p) for p in arrivals)
        entry = {
            "tier_bytes": cap,
            "prefill_tokens": int(prefilled),
            "prompt_tokens": int(total_prompt),
            "prefill_work_avoided_frac": round(
                1.0 - prefilled / total_prompt, 4
            ),
            "prefix_hit_tokens": int(hits),
            "tier_hits": int(tier_hits),
            "tier_faulted_pages": int(faults),
            "tier_spilled_pages": int(spilled),
        }
        if cap:
            entry["tier_hit_rate"] = round(
                eng.tier.stats["hits"]
                / max(eng.tier.stats["hits"] + eng.tier.stats["misses"],
                      1),
                4,
            )
            entry["store"] = {
                k: eng.tier.snapshot()[k]
                for k in ("puts", "hits", "misses", "evictions", "drops")
            }
        assert eng.audit() == []
        sweep.append(entry)
    return sweep, arrivals, golds


def arm_fault_back_latency(model):
    """Admission wall: tier fault-back vs cold re-prefill of the SAME
    prompt (both bit-exact-gated)."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    def build(tier_bytes):
        return ContinuousEngine(
            model, max_batch=1, page_size=PAGE_SIZE,
            max_length=MAX_LENGTH, prefix_cache=True,
            num_pages=NUM_PAGES, tier_bytes=tier_bytes,
        )

    # Dedicated prompts: one probe + two distinct evictors whose
    # chains (3 × ~4 pages on a 10-page pool) force the probe's chain
    # out of the tree between its two admissions.
    rng = np.random.default_rng(42)
    prompts = [
        np.concatenate([
            rng.integers(1, 200, size=PREFIX_TOKENS),
            rng.integers(1, 200, size=SUFFIX_TOKENS),
        ]).astype(np.int32)
        for _ in range(3)
    ]
    golden_eng = ContinuousEngine(
        model, max_batch=1, page_size=PAGE_SIZE, max_length=MAX_LENGTH,
        prefix_cache=True,
    )
    p_golds = [golden_eng.run([(p, 1)])[0] for p in prompts]
    probe, gold_probe = prompts[0], p_golds[0]

    def cycle(eng):
        """probe → 2 evictors (push probe's chain out) → probe again;
        times the FINAL admission (warm tier / cold cache)."""
        np.testing.assert_array_equal(eng.run([(probe, 1)])[0], gold_probe)
        for p, g in zip(prompts[1:], p_golds[1:]):
            np.testing.assert_array_equal(eng.run([(p, 1)])[0], g)
        t0 = time.perf_counter()
        out = eng.run([(probe, 1)])[0]
        wall = time.perf_counter() - t0
        np.testing.assert_array_equal(out, gold_probe)  # the gate
        return wall, eng.last_stats

    # Warm both program shapes outside the timings.
    cycle(build(0))
    cycle(build(32 << 20))
    reps = 3
    cold_walls, warm_walls = [], []
    warm_faults = warm_prefill = cold_prefill = 0
    for _ in range(reps):
        w, st = cycle(build(0))
        cold_walls.append(w)
        cold_prefill += st["prefill_tokens"]
        w, st = cycle(build(32 << 20))
        warm_walls.append(w)
        warm_faults += st["tier_faults"]
        warm_prefill += st["prefill_tokens"]
    assert warm_faults > 0, "tier never faulted — the arm measured nothing"
    cold_w, warm_w = float(np.mean(cold_walls)), float(np.mean(warm_walls))
    return {
        "reprefill_wall_s_mean": round(cold_w, 4),
        "faultback_wall_s_mean": round(warm_w, 4),
        # >1 means fault-back was slower in WALL time on this host —
        # expected on CPU, where interpret-mode prefill of a tiny
        # model is cheap while the fault path pays per-page host→
        # device writes; on hardware the prefill side scales with
        # model FLOPs and the fault side stays a memcpy. The
        # platform-independent lever is the prefill-token delta.
        "wall_ratio_faultback_over_reprefill_cpu_advisory": round(
            warm_w / max(cold_w, 1e-9), 3
        ),
        "reprefill_tokens_per_cycle": int(cold_prefill / reps),
        "faultback_prefill_tokens_per_cycle": int(warm_prefill / reps),
        "prefill_tokens_avoided_per_cycle": int(
            (cold_prefill - warm_prefill) / reps
        ),
        "faultback_pages_per_cycle": int(warm_faults / reps),
    }


def arm_supervisor_restart():
    """Kill a fleet mid-batch; reboot over the same resume_dir; gate
    on bit-exact outputs; record restored vs regenerated tokens."""
    from triton_distributed_tpu.models.kv_tier import SNAP_KIND, PageStore
    from triton_distributed_tpu.models.stub import stub_generate
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    resume = tempfile.mkdtemp(prefix="tdt-tier-resume-")
    prompts = [np.arange(1, 9, dtype=np.int32),
               np.arange(20, 30, dtype=np.int32)]
    gens = [8, 8]
    golds = [stub_generate(p, g) for p, g in zip(prompts, gens)]

    def mk_sup():
        return FleetSupervisor(
            [stub_spec("r0", delay_s=2.5, page_size=4, num_pages=64)],
            heartbeat_s=0.05, snapshot_s=0.05, resume_dir=resume,
            spawn_timeout_s=120.0,
        )

    sup = mk_sup()
    router = sup.start()
    results: dict = {}
    th = threading.Thread(
        target=lambda: results.update(
            res=router.run(list(zip(prompts, gens)), results=True)
        ),
        daemon=True,
    )
    th.start()
    store = PageStore(dir=resume)

    def progressed():
        # Kill only once real work is at stake: a persisted snapshot
        # with ≥4 of its tokens generated but unfinished.
        for k in store.keys(SNAP_KIND):
            snap = store.peek(SNAP_KIND, k) or {}
            out = snap.get("out") or []
            if 4 <= len(out) < int(snap.get("gen_len", 0)):
                return True
        return False

    assert sup.wait_for(progressed, timeout_s=60)
    t_kill = time.monotonic()
    sup._stop.set()
    if sup._thread is not None:
        sup._thread.join(timeout=10)
    proc = router.replicas[0].proc
    os.kill(router.replicas[0].pid, signal.SIGKILL)
    proc.wait(timeout=10)
    th.join(timeout=60)

    sup2 = mk_sup()
    try:
        router2 = sup2.start()
        t_up = time.monotonic()
        res2 = router2.run(list(zip(prompts, gens)), results=True)
        t_done = time.monotonic()
        for r, gold in zip(res2, golds):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == gold  # the gate
        st = router2.last_stats
        restored = int(st["migrated_in_tokens"])
        assert restored >= 1, "nothing resumed — the arm measured nothing"
        out = {
            "requests": len(prompts),
            "tokens_total": int(sum(gens)),
            "tokens_restored_from_snapshots": restored,
            "work_preserved_frac": round(restored / sum(gens), 3),
            "reboot_to_serving_s": round(t_up - t_kill, 2),
            "resubmit_wall_s": round(t_done - t_up, 2),
            "bit_exact": True,  # asserted above, per request
        }
    finally:
        sup2.shutdown()
        shutil.rmtree(resume, ignore_errors=True)
    return out


def main() -> int:
    from triton_distributed_tpu.models import AutoLLM

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=MAX_LENGTH)
    sweep, _arrivals, _golds = arm_hit_rate(model)
    latency = arm_fault_back_latency(model)
    restart = arm_supervisor_restart()

    off = next(e for e in sweep if e["tier_bytes"] == 0)
    ample = sweep[-1]
    result = {
        "metric": "kv_tier_hit_rate_faultback_and_restart_recovery",
        "workload": {
            "page_size": PAGE_SIZE,
            "num_pages": NUM_PAGES,
            "hot_prefixes": HOT_PREFIXES,
            "tail_prefixes": TAIL_PREFIXES,
            "prefix_tokens": PREFIX_TOKENS,
            "arrivals": ARRIVALS,
        },
        "platform": jax.default_backend(),
        "capacity_sweep": sweep,
        "tier_prefill_tokens_saved_vs_off": int(
            off["prefill_tokens"] - ample["prefill_tokens"]
        ),
        "faultback_vs_reprefill": latency,
        "supervisor_restart": restart,
        "provenance": {
            "harness": "perf/kv_tier_bench.py — per-arrival "
            "ContinuousEngine.run(gen_len=1) over a long-tail "
            "shared-prefix population on a 10-page pool (tiny model); "
            "restart arm kills a stub fleet mid-batch and reboots a "
            "FleetSupervisor over the same resume_dir",
            "gates": "EVERY recorded arrival asserted bit-exact "
            "against a tier-less golden before counting; the restart "
            "arm asserts per-request bit-exactness vs the stub's pure "
            "generator and restored tokens >= 1",
            "caveat": "CPU wall-clock is interpret-mode-taxed and "
            "advisory; prefill tokens computed / avoided and the "
            "restored-token fractions are the platform-independent "
            "levers",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "KV_TIER.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
