"""Production-shaped load generator for the serving wire.

Every perf/ artifact before this one measured a single scenario; the
SLO goodput yardstick (ROADMAP item 5, docs/observability.md "SLO
goodput") needs traffic shaped like production:

- **Arrival processes** — seeded Poisson (exponential gaps at ``rate``
  req/s) or bursty (exponential gaps between bursts of
  ``burst_size`` near-simultaneous arrivals at the same mean rate) —
  the two shapes that bracket real front-end traffic.
- **Zipf-weighted shared-prefix population** — prompts draw one of
  ``prefix_pool`` system-prompt-like prefixes with probability
  ∝ 1/rank^``zipf_a`` plus a unique suffix, so the radix tree (and
  the KV tier behind it) sees the hot-head/long-tail reuse pattern
  production sees.
- **Long-tail output lengths** — lognormal ``gen_len`` clipped to
  [``gen_min``, ``gen_max``]: most answers short, a heavy tail of
  long ones (what makes per-token SLOs interesting).
- **Mid-stream cancellations** — a ``cancel_frac`` of requests carry
  ``cancel_after`` (tokens): the driver cancels them once that many
  frames arrived, exercising the teardown path under load.

A trace is a PURE function of its :class:`LoadSpec` (same seed → same
trace, tested), serializable to JSONL (``save_trace``/``load_trace``)
so runs are comparable ACROSS PRs: record once, replay against every
scheduler change. :func:`replay` walks the arrival stamps against the
wall clock and drives one streaming request per arrival through
``serving.server.request_stream``, collecting each request's summary
(wire-side TTFT/TPOT/outcome — stamped by the server at the frame
writes, docs/serving.md "Streaming & cancellation").
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

import numpy as np


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One workload's shape. ``rate`` is mean arrivals/second;
    ``process`` is ``"poisson"`` or ``"bursty"``."""

    rate: float = 4.0
    n_requests: int = 32
    process: str = "poisson"
    burst_size: int = 4
    # Shared-prefix population.
    prefix_pool: int = 8
    zipf_a: float = 1.2
    prefix_len: int = 24
    suffix_min: int = 2
    suffix_max: int = 8
    vocab: int = 211
    # Long-tail output lengths (lognormal, clipped).
    gen_mean_ln: float = 2.2
    gen_sigma_ln: float = 0.6
    gen_min: int = 4
    gen_max: int = 48
    # Mid-stream cancellations.
    cancel_frac: float = 0.0
    cancel_after: int = 2
    slo_class: str = "default"
    # Per-request class mix (docs/observability.md "SLO goodput"):
    # ``((name, weight), ...)`` pairs — each request draws its
    # ``slo_class`` with probability ∝ weight (seeded, replay-
    # identical). Empty keeps the scalar ``slo_class`` for every
    # request — traces from pre-mix specs are bit-identical.
    #
    # The class name ``"agentic"`` is special: those requests are
    # reshaped into repetitive re-ask continuations — the prompt
    # becomes its shared prefix plus one per-prefix motif repeated
    # ``agentic_repeats`` times, the multi-turn agent-loop shape
    # (same tool-call scaffolding re-sent each turn) that speculative
    # decoding — n-gram AND radix-tree drafting — feeds on
    # (docs/serving.md "Speculative decoding"). Motifs are drawn from
    # the seeded rng AFTER every pre-existing draw, so specs without
    # an agentic class (and all pre-mix specs) keep bit-identical
    # traces.
    class_mix: tuple = ()
    agentic_motif: int = 6
    agentic_repeats: int = 3
    # The class name ``"document"`` is special too: those requests
    # become long-context document jobs — the shared prefix plus a
    # unique body of ``doc_min``..``doc_max`` tokens (10k+ by default;
    # tests scale the knobs down), the workload the long-context
    # serving path (cp prefill, sharded slots — docs/serving.md
    # "Long-context serving") exists for. Body draws land AFTER the
    # agentic motif draws, so mixes without "document" (and all
    # pre-mix specs) keep bit-identical traces.
    doc_min: int = 10240
    doc_max: int = 16384
    seed: int = 0


def _prefixes(spec: LoadSpec, rng) -> list[list[int]]:
    """The shared-prefix population: ``prefix_pool`` deterministic
    token chains (drawn once from the seeded rng, so the POPULATION is
    part of the trace's identity too)."""
    return [
        rng.integers(1, spec.vocab, size=spec.prefix_len).tolist()
        for _ in range(spec.prefix_pool)
    ]


def _zipf_weights(n: int, a: float) -> np.ndarray:
    w = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** a
    return w / w.sum()


def generate_trace(spec: LoadSpec) -> list[dict]:
    """The trace: one dict per request, sorted by arrival time ``t``
    (seconds from trace start). Pure in ``spec`` — the determinism the
    cross-PR comparability contract rests on (tested)."""
    rng = np.random.default_rng(spec.seed)
    prefixes = _prefixes(spec, rng)
    weights = _zipf_weights(spec.prefix_pool, spec.zipf_a)
    # Arrival stamps.
    ts: list[float] = []
    t = 0.0
    if spec.process == "poisson":
        for _ in range(spec.n_requests):
            t += float(rng.exponential(1.0 / spec.rate))
            ts.append(t)
    elif spec.process == "bursty":
        # Bursts of `burst_size` back-to-back arrivals; gaps sized so
        # the MEAN rate still equals `rate`.
        while len(ts) < spec.n_requests:
            t += float(rng.exponential(spec.burst_size / spec.rate))
            for _ in range(min(spec.burst_size,
                               spec.n_requests - len(ts))):
                ts.append(t)
    else:
        raise ValueError(
            f"process must be 'poisson' or 'bursty', got {spec.process!r}"
        )
    trace: list[dict] = []
    for i, t_arr in enumerate(ts):
        pi = int(rng.choice(spec.prefix_pool, p=weights))
        suffix_len = int(rng.integers(spec.suffix_min,
                                      spec.suffix_max + 1))
        suffix = rng.integers(1, spec.vocab, size=suffix_len).tolist()
        gen_len = int(np.clip(
            round(float(rng.lognormal(spec.gen_mean_ln,
                                      spec.gen_sigma_ln))),
            spec.gen_min, spec.gen_max,
        ))
        cancel_after = None
        if spec.cancel_frac > 0 and rng.random() < spec.cancel_frac:
            cancel_after = min(spec.cancel_after, max(gen_len - 1, 1))
        trace.append({
            "i": i,
            "t": round(t_arr, 6),
            "prompt": prefixes[pi] + suffix,
            "prefix_id": pi,
            "gen_len": gen_len,
            "cancel_after": cancel_after,
            "slo_class": spec.slo_class,
        })
    if spec.class_mix:
        # Class draws come AFTER every pre-existing draw so a spec
        # without a mix consumes the rng stream exactly as before —
        # the cross-PR trace-identity contract stays intact.
        names = [str(n) for n, _w in spec.class_mix]
        w = np.asarray([float(wt) for _n, wt in spec.class_mix],
                       np.float64)
        if len(names) == 0 or (w <= 0).all():
            raise ValueError(f"bad class_mix: {spec.class_mix!r}")
        w = w / w.sum()
        for row in trace:
            row["slo_class"] = names[int(rng.choice(len(names), p=w))]
        if "agentic" in names:
            # Repetitive re-ask continuation class: one motif PER
            # PREFIX (drawn lazily, in row order — deterministic), so
            # agentic requests sharing a prefix repeat the SAME
            # continuation and the radix tree sees cross-request
            # reuse, not just within-prompt n-gram repetition. Draws
            # land after every other rng use: mixes without "agentic"
            # consume the stream exactly as before.
            motifs: dict[int, list[int]] = {}
            for row in trace:
                if row["slo_class"] != "agentic":
                    continue
                pi = row["prefix_id"]
                if pi not in motifs:
                    motifs[pi] = rng.integers(
                        1, spec.vocab, size=spec.agentic_motif
                    ).tolist()
                row["prompt"] = (
                    prefixes[pi] + motifs[pi] * spec.agentic_repeats
                )
        if "document" in names:
            # Long-context document class: shared prefix + a unique
            # 10k+-token body (row order, after the agentic draws —
            # the same stream-compatibility contract as above).
            for row in trace:
                if row["slo_class"] != "document":
                    continue
                body_len = int(rng.integers(spec.doc_min,
                                            spec.doc_max + 1))
                body = rng.integers(1, spec.vocab,
                                    size=body_len).tolist()
                row["prompt"] = prefixes[row["prefix_id"]] + body
    return trace


def save_trace(path: str, trace: list[dict],
               spec: LoadSpec | None = None) -> None:
    """JSONL: an optional spec header line, then one request per line
    (the replayable-artifact half of cross-PR comparability)."""
    with open(path, "w") as f:
        if spec is not None:
            f.write(json.dumps(
                {"_spec": dataclasses.asdict(spec)}
            ) + "\n")
        for row in trace:
            f.write(json.dumps(row) + "\n")


def load_trace(path: str) -> tuple[list[dict], dict | None]:
    """Inverse of :func:`save_trace`: ``(trace, spec_dict_or_None)``."""
    trace: list[dict] = []
    spec = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if "_spec" in row:
                spec = row["_spec"]
                continue
            trace.append(row)
    return trace, spec


def replay(trace: list[dict], host: str, port: int, *,
           speed: float = 1.0, timeout: float = 300.0) -> list[dict]:
    """Replay a trace against a live server through the STREAMING
    wire: one thread per arrival (launched at its trace stamp /
    ``speed``), each driving ``request_stream`` and — when the row
    carries ``cancel_after`` — sending ``{"cmd": "cancel"}`` on a
    second connection once that many frames arrived (the mid-stream
    cancellation arm). Returns one record per request, trace order::

        {"i", "t", "gen_len", "cancel_after", "tokens": [...],
         "wire": {ttft_s, tpot_s, e2e_s, outcome, status, ...},
         "error": str | None}

    Every latency number in ``wire`` is the SERVER's wire-side stamp
    (docs/serving.md "Streaming & cancellation") — the replay adds no
    client-side clock of its own.
    """
    from triton_distributed_tpu.serving.server import (
        request,
        request_stream,
    )

    records: list[dict | None] = [None] * len(trace)
    t0 = time.monotonic()

    def drive(idx: int, row: dict) -> None:
        tid = f"lg{idx}"
        rec = {
            "i": row.get("i", idx),
            "t": row["t"],
            "gen_len": row["gen_len"],
            "cancel_after": row.get("cancel_after"),
            "tokens": [],
            "wire": None,
            "error": None,
        }
        payload = {
            "requests": [row["prompt"]],
            "gen_lens": [row["gen_len"]],
            "ticket_ids": [tid],
        }
        if row.get("slo_class"):
            payload["slo_class"] = row["slo_class"]
        cancel_after = row.get("cancel_after")
        cancelled = False
        try:
            for fr in request_stream(host, port, payload,
                                     timeout=timeout):
                if fr.get("frame") == "token":
                    rec["tokens"].append(fr["token"])
                    if (cancel_after is not None and not cancelled
                            and len(rec["tokens"]) >= cancel_after):
                        cancelled = True
                        request(host, port, {
                            "cmd": "cancel", "ticket_ids": [tid],
                        }, timeout=timeout)
                else:
                    rec["wire"] = (fr.get("wire") or [None])[0]
        except Exception as e:  # noqa: BLE001 — per-request record
            rec["error"] = f"{type(e).__name__}: {e}"
        records[idx] = rec

    threads: list[threading.Thread] = []
    for idx, row in enumerate(trace):
        due = t0 + row["t"] / max(speed, 1e-9)
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=drive, args=(idx, row), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout)
    return [r if r is not None else {"error": "driver timed out"}
            for r in records]


def parse_classes(text: str) -> tuple:
    """``"interactive:4,document:1"`` → ``((name, weight), ...)`` —
    the ``class_mix`` wire format of the CLI (a bare name means
    weight 1)."""
    mix: list[tuple[str, float]] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, w = part.rsplit(":", 1)
            mix.append((name.strip(), float(w)))
        else:
            mix.append((part, 1.0))
    return tuple(mix)


def main(argv=None) -> int:
    """Generate a trace to JSONL (round-trip-verified) and print a
    per-class summary — the record-once half of cross-PR replay."""
    import argparse

    p = argparse.ArgumentParser(
        description="Seeded production-shaped trace generator "
        "(perf/loadgen.py). Writes JSONL replayable with replay().",
    )
    p.add_argument("--out", required=True, help="JSONL trace path")
    p.add_argument("--rate", type=float, default=4.0)
    p.add_argument("--n", type=int, default=32,
                   help="number of requests")
    p.add_argument("--process", choices=("poisson", "bursty"),
                   default="poisson")
    p.add_argument("--burst-size", type=int, default=4)
    p.add_argument(
        "--classes", default="",
        help="SLO class mix, e.g. 'interactive:4,document:1' "
        "(weights optional). 'agentic' requests become repetitive "
        "re-ask continuations; 'document' requests become "
        "long-context jobs (--doc-min/--doc-max body tokens).",
    )
    p.add_argument("--doc-min", type=int, default=10240)
    p.add_argument("--doc-max", type=int, default=16384)
    p.add_argument("--cancel-frac", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    spec = LoadSpec(
        rate=args.rate, n_requests=args.n, process=args.process,
        burst_size=args.burst_size, cancel_frac=args.cancel_frac,
        class_mix=parse_classes(args.classes),
        doc_min=args.doc_min, doc_max=args.doc_max, seed=args.seed,
    )
    trace = generate_trace(spec)
    save_trace(args.out, trace, spec)
    back, _spec_d = load_trace(args.out)
    if back != trace:
        raise SystemExit(
            f"JSONL round-trip mismatch for {args.out} — trace is not "
            "replay-safe"
        )
    by_class: dict[str, list[int]] = {}
    for row in trace:
        by_class.setdefault(row["slo_class"], []).append(
            len(row["prompt"])
        )
    print(f"{len(trace)} requests -> {args.out}")
    for name in sorted(by_class):
        lens = by_class[name]
        print(f"  {name}: {len(lens)} reqs, prompt tokens "
              f"{min(lens)}..{max(lens)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
