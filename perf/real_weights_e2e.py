"""End-to-end serve of a REAL HF-format Qwen3 checkpoint on the chip.

VERDICT r2 missing #4: nothing had ever run the HF-checkpoint path end
to end. Zero-egress means no true pretrained weights exist on this
machine, so this script builds the most faithful substitute: an actual
``transformers.Qwen3ForCausalLM`` (random init, REAL architecture code)
saved with ``save_pretrained`` — byte-identical format to a downloaded
checkpoint — then drives the full serving path against it:

    AutoLLM.from_pretrained(<hf dir>) → Engine.serve(mode=...)

and emits a generation transcript + tok/s. The loader/math parity with
upstream transformers is separately pinned by
``tests/test_model.py::test_hf_transformers_parity`` (greedy tokens
bit-identical at fp32), so a coherent run here certifies the
checkpoint path, not the weights' knowledge.

Relay safety (skill notes: heavy first contact can wedge the relay):
defaults to a reduced depth; pass --full for true Qwen3-0.6B dims.

Usage: python perf/real_weights_e2e.py [--full] [--mode mega_multi]
"""

import argparse
import json
import os
import sys
import tempfile
import time

_T0 = time.perf_counter()  # process start — anchors the first phase marker

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# Checkpoint geometries. ``1.7b`` is the REAL Qwen3-1.7B architecture
# (headline-class per VERDICT r3 task 4: a 16 GB v5e holds 1.7B/4B
# bf16); its checkpoint is saved bf16 — the dtype real Qwen3 releases
# ship in — which also halves the host->device load.
_GEOMS = {
    "small": dict(vocab_size=32768, hidden_size=1024,
                  intermediate_size=3072, num_hidden_layers=8,
                  num_attention_heads=16, num_key_value_heads=8),
    "0.6b": dict(vocab_size=151936, hidden_size=1024,
                 intermediate_size=3072, num_hidden_layers=28,
                 num_attention_heads=16, num_key_value_heads=8),
    "1.7b": dict(vocab_size=151936, hidden_size=2048,
                 intermediate_size=6144, num_hidden_layers=28,
                 num_attention_heads=16, num_key_value_heads=8),
}


def build_checkpoint(geom: str) -> str:
    # Reuse an already-built checkpoint: save_pretrained costs minutes
    # on this 1-core host, and every watcher retry pays it again. The
    # build is deterministic (manual_seed(0)), so an existing dir with
    # weights is byte-equivalent to a rebuild.
    path = os.path.join(
        tempfile.gettempdir(),
        {"small": "qwen3_hf_small", "0.6b": "qwen3_hf_full"}.get(
            geom, f"qwen3_hf_{geom}"
        ),
    )
    if os.path.exists(os.path.join(path, "config.json")) and any(
        f.endswith(".safetensors") for f in os.listdir(path)
    ):
        return path

    import torch
    import transformers

    cfg = transformers.Qwen3Config(
        head_dim=128,
        rope_theta=1e6,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        max_position_embeddings=2048,
        **_GEOMS[geom],
    )
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(cfg).eval()
    if geom == "1.7b":
        model = model.to(torch.bfloat16)
    # Build into a scratch dir and rename into place: save_pretrained
    # is non-atomic and takes minutes here — a watcher kill mid-save
    # would otherwise leave a partial dir that passes the reuse check
    # forever.
    tmp = path + ".building"
    if os.path.exists(tmp):
        import shutil

        shutil.rmtree(tmp)
    model.save_pretrained(tmp, safe_serialization=True)
    os.rename(tmp, path)
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--full", action="store_true",
                   help="alias for --geom 0.6b")
    p.add_argument("--geom", default=None, choices=sorted(_GEOMS),
                   help="checkpoint geometry: small (depth-8 smoke), "
                        "0.6b, 1.7b (headline-class, bf16 checkpoint)")
    p.add_argument("--mode", default="mega_multi",
                   choices=["xla", "pallas", "mega", "mega_multi"])
    p.add_argument("--q8", action="store_true",
                   help="weight-only int8 megakernel decode "
                        "(MegaConfig(wq8=True); mega modes only)")
    p.add_argument("--gen-len", type=int, default=64)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.models import AutoLLM, Engine
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    # Phase progress on stderr (flushed): a step-timeout kill then still
    # shows WHERE the time went — the 03:19 on-chip session burned its
    # whole 1500 s budget with zero output.
    def phase(name, t0=[_T0]):
        now = time.perf_counter()
        print(f"[e2e +{now - t0[0]:.0f}s] {name}", file=sys.stderr, flush=True)
        t0[0] = now

    geom = args.geom or ("0.6b" if args.full else "small")
    phase(f"imports done; building HF checkpoint (torch, 1 core, {geom})")
    ckpt = build_checkpoint(geom)
    phase("checkpoint saved; initializing device context")
    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    phase("ctx up; AutoLLM.from_pretrained (safetensors -> device)")
    t0 = time.perf_counter()
    model = AutoLLM.from_pretrained(ckpt, ctx=ctx, max_length=1024)
    load_s = time.perf_counter() - t0
    phase("params loaded; building Engine")

    mode = args.mode
    if mode == "mega_multi":
        mode = "mega"  # Engine auto-selects multi-step in mega mode
    mega_cfg = None
    if args.q8:
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )

        mega_cfg = MegaConfig(wq8=True)
    eng = Engine(model, temperature=0.0, mode=mode, mega_cfg=mega_cfg)
    prompt = np.arange(1, 33, dtype=np.int32)[None]

    # First serve is the WARM-UP (prefill + decode compiles, tens of
    # seconds through the relay); the timed number comes from the
    # second, already-compiled call. The pair doubles as the greedy
    # determinism check.
    t0 = time.perf_counter()
    out = eng.serve(prompt, gen_len=args.gen_len)
    cold_wall = time.perf_counter() - t0
    phase(f"cold serve done ({cold_wall:.0f}s incl. compiles); timed serve")
    t0 = time.perf_counter()
    out2 = eng.serve(prompt, gen_len=args.gen_len)
    wall = time.perf_counter() - t0
    gen = out[0, prompt.shape[1]:]
    deterministic = bool((out == out2).all())

    print(json.dumps({
        "checkpoint": ckpt,
        "config": {"small": "qwen3-0.6B-depth8", "0.6b": "qwen3-0.6B",
                   "1.7b": "qwen3-1.7B"}[geom],
        "platform": jax.devices()[0].platform,
        "mode": args.mode + ("+q8" if args.q8 else ""),
        "load_s": round(load_s, 1),
        "gen_len": int(args.gen_len),
        "cold_wall_s": round(cold_wall, 2),
        "wall_s": round(wall, 2),
        "tok_s": round(args.gen_len / wall, 2),
        "deterministic": deterministic,
        "transcript_tokens": gen.tolist(),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
