"""Overlap-efficiency benchmark: how much comm does AG+GEMM / GEMM+RS hide?

The north-star metric (BASELINE.md / reference ``README.md:190-205``
charts): fused-overlap time vs (pure GEMM, GEMM + blocking collective).
Comm-hidden fraction = (T_blocking − T_overlap) / T_comm — 1.0 means the
collective costs nothing extra.

Two modes:

1. **Measured (single chip)**: at tp=1 there is no comm, but the rung
   that CAN regress is the orchestrated Pallas kernel's compute path vs
   the plain XLA GEMM — kernel overhead, staging pipeline stalls. We
   measure that ratio (``kernel_efficiency``). Timing follows the axon
   relay rules (data-dependent chaining inside one jit, host fetch as
   fence — see bench.py).
2. **Analytic (tp=8 projection)**: the perf model
   (``tools/perf_model.py``, parity with the reference's
   ``comm_perf_model.py``) prices GEMM and ring collectives at the
   survey north-star shapes and projects the hidden fraction the fused
   kernels target: T_overlap ≈ max(T_gemm, T_comm) + per-step latency.

Usage:
    python perf/overlap_efficiency.py [--cpu] [--m 8192 --k 4096 --n 12288]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measured_kernel_efficiency(args, jax, jnp, np):
    """tp=1: fused-kernel GEMM path vs plain XLA dot (no comm rung)."""
    from triton_distributed_tpu.ops.overlap import AGGemmConfig, ag_gemm_op
    from triton_distributed_tpu.ops.overlap.ag_gemm import create_ag_gemm_context
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    m, k, n = args.m, args.k, args.n
    dt = jnp.bfloat16 if not args.cpu else jnp.float32
    key = jax.random.key(0)
    a = jax.random.normal(key, (m, k), jnp.float32).astype(dt)
    b = jax.random.normal(key, (k, n), jnp.float32).astype(dt)

    iters = 8

    def make(f):
        # Chain iterations with a data dependency; fence by host fetch.
        # Two traps make naive chaining flatter the pure-XLA rung:
        # (1) `a + acc * 0` folds, letting XLA hoist the GEMM out of the
        #     loop — the perturbation below is sub-ulp but not foldable;
        # (2) carrying `out[0, 0]` leaves all but one dot product DEAD —
        #     XLA can slice through the GEMM and compute a vector dot.
        #     `jnp.sum(out)` keeps every element live (the side-effecting
        #     Pallas rung never had this hazard, which silently skews the
        #     comparison).
        def chained(a, b):
            def body(_, acc):
                out = f(a + (acc * 1e-30).astype(a.dtype), b)
                return jnp.sum(out.astype(jnp.float32))

            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

        run = jax.jit(chained)
        np.asarray(run(a, b))  # compile + warm
        return run

    # Interleave the rungs and take medians: the relay occasionally lets
    # one call's work leak into the next measurement window (an inflated
    # rep immediately followed by an impossibly fast one), so min() over
    # sequential reps is untrustworthy.
    cfg = create_ag_gemm_context(m, n, k, dt)
    runs = {
        # Cast back to the input dtype so both rungs pay the same
        # epilogue (the fused kernel's output is bf16).
        "xla": make(
            lambda a, b: jnp.dot(
                a, b, preferred_element_type=jnp.float32
            ).astype(a.dtype)
        ),
        "fused": make(lambda a, b: ag_gemm_op(a, b, "tp", cfg, ctx)),
    }
    samples = {name: [] for name in runs}
    for _ in range(7):
        for name, run in runs.items():
            t0 = time.perf_counter()
            np.asarray(run(a, b))
            samples[name].append((time.perf_counter() - t0) / iters * 1e3)

    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    t_xla = median(samples["xla"])
    t_fused = median(samples["fused"])
    return {
        "xla_gemm_ms": round(t_xla, 3),
        "fused_kernel_ms": round(t_fused, 3),
        "kernel_efficiency": round(t_xla / max(t_fused, 1e-9), 4),
    }


def analytic_projection(args, jnp):
    """tp=8 projection from the perf model, ANCHORED to measured
    hardware by default (VERDICT r2 weak #2): the spec's HBM/MXU/ICI
    rates come from ``perf/MEASURED.json`` via ``anchored_spec`` rather
    than datasheet peaks, and every projected number carries the
    recorded cross-process error bars."""
    from triton_distributed_tpu.tools.perf_model import (
        anchored_spec,
        chip_spec,
        estimate_all_gather_time_ms,
        estimate_gemm_time_ms,
        estimate_reduce_scatter_time_ms,
    )

    tp = args.tp
    m, k, n = args.m, args.k, args.n
    dt = jnp.bfloat16
    if args.datasheet:
        spec, meta = chip_spec(args.chip), {"anchored": False}
    else:
        spec, meta = anchored_spec()
    ebar = meta.get("error_bars_frac", 0.0)
    out = {"anchoring": meta}

    def entry(t_gemm, t_comm):
        # Fused: compute starts on the local chunk immediately;
        # per-chunk arrival latency exposes ~1/tp of the shorter leg.
        def frac_at(tc):
            t_o = max(t_gemm, tc) + min(t_gemm, tc) / tp
            return (t_gemm + tc - t_o) / max(tc, 1e-9)

        t_overlap = max(t_gemm, t_comm) + min(t_gemm, t_comm) / tp
        t_blocking = t_gemm + t_comm
        # Error bars perturb the COMM leg only — MEASURED.json's ±30%
        # belongs to the unmeasurable-ICI proxy; the gemm anchor is a
        # stable within-process median. Both endpoints recompute the
        # whole expression consistently (in the compute-bound regime the
        # fraction is flat at 1 - 1/tp, so the range collapses there).
        endpoints = sorted(
            (frac_at(t_comm * (1 + ebar)), frac_at(t_comm * (1 - ebar)))
        )
        return {
            "gemm_ms": round(t_gemm, 3),
            "comm_ms": round(t_comm, 3),
            "blocking_ms": round(t_blocking, 3),
            "overlap_ms": round(t_overlap, 3),
            "comm_hidden_frac": round(frac_at(t_comm), 4),
            "comm_hidden_frac_range": [
                round(endpoints[0], 4), round(endpoints[1], 4)
            ],
        }

    # AG+GEMM: gather A rows [m, k], each device computes [m, k]@[k, n/tp].
    out["ag_gemm"] = entry(
        estimate_gemm_time_ms(m, n // tp, k, dt, spec),
        estimate_all_gather_time_ms(m * k * 2, tp, spec=spec),
    )

    # GEMM+RS: [m, k/tp]@[k/tp, n] partials reduced+scattered over rows.
    # Three kernel variants (ops/overlap/gemm_rs.py GemmRSConfig):
    # single ring (one ICI direction), counter-rotating dual rings
    # (both directions = the model's bidir rate), and dual rings with
    # the fp8 wire hop (half the bytes again).
    t_gemm = estimate_gemm_time_ms(m, n, k // tp, dt, spec)
    rs_bytes = m * n * 2
    out["gemm_rs_unidir"] = entry(
        t_gemm,
        estimate_reduce_scatter_time_ms(rs_bytes, tp, spec=spec, bidir=False),
    )
    t_rs_bidir = estimate_reduce_scatter_time_ms(rs_bytes, tp, spec=spec)
    out["gemm_rs"] = entry(t_gemm, t_rs_bidir)
    out["gemm_rs_fp8_wire"] = entry(t_gemm, t_rs_bidir / 2)
    out["chip"] = spec.name
    return out


def model_validation(args, jnp):
    """Model-vs-measured at tp=1 (the verdict's ≤15% gate): predict the
    north-star GEMM and fused-kernel times from the anchored spec and
    compare against the RECORDED on-chip medians in MEASURED.json."""
    from triton_distributed_tpu.tools.perf_model import (
        anchored_spec,
        estimate_gemm_time_ms,
        measured_anchors,
    )

    anchors = measured_anchors()
    g = (anchors or {}).get("gemm_anchor")
    if not g:
        return {"available": False}
    spec, _ = anchored_spec(anchors)
    pred = estimate_gemm_time_ms(g["m"], g["n"], g["k"], jnp.bfloat16, spec)
    rows = {
        "xla_gemm": {"measured_ms": g["ms"], "model_ms": round(pred, 3)},
    }
    if "fused_ms" in g:
        # The fused kernel runs the same GEMM through the manual staging
        # pipeline; the model charges the same roofline (measured
        # kernel_efficiency 0.95-0.97 — within the model's resolution).
        rows["fused_kernel"] = {
            "measured_ms": g["fused_ms"], "model_ms": round(pred, 3),
        }
    for r in rows.values():
        r["rel_err"] = round(abs(r["model_ms"] - r["measured_ms"])
                             / r["measured_ms"], 4)
    return {
        "available": True, "tp1": rows,
        "max_rel_err": max(r["rel_err"] for r in rows.values()),
        "note": (
            "xla_gemm IS the anchor (rel_err 0 by construction); the "
            "fused_kernel row is the independent check. Add non-anchor "
            "shapes on the next on-chip session for a stronger gate."
        ),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--m", type=int, default=8192)
    p.add_argument("--k", type=int, default=4096)
    p.add_argument("--n", type=int, default=12288)
    p.add_argument("--tp", type=int, default=8)
    p.add_argument("--chip", default=None, help="chip kind for the model")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--skip-measure", action="store_true")
    p.add_argument("--datasheet", action="store_true",
                   help="use datasheet peaks instead of measured anchors")
    args = p.parse_args(argv)

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    result = {
        "shapes": {"m": args.m, "k": args.k, "n": args.n, "tp": args.tp},
        "projection_tp8": analytic_projection(args, jnp),
        "model_validation": model_validation(args, jnp),
    }
    if not args.skip_measure:
        result["measured_tp1"] = measured_kernel_efficiency(args, jax, jnp, np)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
