"""Live socket-server demo on the chip: transcript + tok/s THROUGH the wire.

VERDICT r3 missing #4: the reference demos a live socket server + chat
on real hardware (``test/models/model_server.py:112-198``); this repo's
``ModelServer`` was only ever exercised by the CPU test suite. This
harness stands the server up on the real chip (megakernel engine),
drives it over the SOCKET protocol — ping, two generate requests (the
repeat doubles as the greedy-determinism check), shutdown — and emits
the wire-measured latency + tok/s.

Defaults are relay-gentle (depth-8 0.6B geometry); --full for the true
0.6B and --model for the headline presets.

Usage: python perf/serve_demo.py [--mode mega] [--gen-len 32]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stream_once(host, port, payload, timeout=1200):
    """Drive one payload through the streaming wire (docs/serving.md
    "Streaming & cancellation"), printing tokens AS THEY ARRIVE.
    Returns (tokens, summary, wall_s); the summary's ``wire`` entries
    carry the server's wire-side TTFT/TPOT — the numbers a user saw,
    not an engine latch."""
    from triton_distributed_tpu.serving.server import request_stream

    t0 = time.time()
    toks = []
    summary = None
    for fr in request_stream(host, port, payload, timeout=timeout):
        if fr.get("frame") == "token":
            toks.append(fr["token"])
            print(fr["token"], end=" ", flush=True)
        else:
            summary = fr
    print(flush=True)
    return toks, summary, time.time() - t0


def _drive_pair(host, port, payload, stream):
    """The demo's cold + warm request pair (the repeat doubles as the
    determinism check), ONE implementation for the fleet and
    single-server paths: streaming prints tokens as they arrive and
    reports the wire-side numbers. Returns (r1, r2, cold_s, warm_s)
    with r1/r2 response-shaped (the stream summary carries the same
    keys)."""
    from triton_distributed_tpu.serving.server import request

    if stream:
        toks1, r1, cold_s = _stream_once(host, port, payload)
        toks2, r2, warm_s = _stream_once(host, port, payload)
        _print_stream_report(
            r2, cold_s, warm_s, deterministic=toks1 == toks2
        )
        return r1, r2, cold_s, warm_s
    t1 = time.time()
    r1 = request(host, port, payload, timeout=1200)
    cold_s = time.time() - t1
    t2 = time.time()
    r2 = request(host, port, payload, timeout=1200)
    warm_s = time.time() - t2
    return r1, r2, cold_s, warm_s


def _print_stream_report(summary, cold_s, warm_s, deterministic):
    wire = (summary or {}).get("wire") or [{}]
    w = wire[0]
    print(json.dumps({
        "stream": True,
        "deterministic": deterministic,
        "cold_wall_s": round(cold_s, 2),
        "warm_wall_s": round(warm_s, 2),
        "wire_ttft_s": w.get("ttft_s"),
        "wire_tpot_s": w.get("tpot_s"),
        "wire_e2e_s": w.get("e2e_s"),
        "slo_outcome": w.get("outcome"),
        "statuses": [x["status"] for x in (summary or {}).get(
            "results", [])],
    }), flush=True)


def _fleet_demo(args) -> int:
    """--fleet N: a supervised process fleet (docs/scale-out.md
    "Process fleet") driven through the wire like --replicas — the
    parent loads NO model; children are run_server processes under the
    FleetSupervisor (heartbeats, respawn, snapshot recovery)."""
    from triton_distributed_tpu.serving.server import ModelServer, request
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        ReplicaSpec,
        stub_spec,
    )

    t0 = time.time()
    mode = args.mode if not (args.cpu and args.mode == "mega") else "xla"
    pool_fleet = args.prefill_replicas > 0 or args.decode_replicas > 0
    if pool_fleet:
        members = (
            [(f"p{i}", "prefill") for i in range(args.prefill_replicas)]
            + [(f"d{i}", "decode") for i in range(args.decode_replicas)]
        )
    else:
        members = [(f"r{i}", "mixed") for i in range(args.fleet)]
    if args.model == "stub":
        def make_spec(name, role="mixed"):
            return stub_spec(name, delay_s=0.05, role=role)
    else:
        child = [
            sys.executable, "-m",
            "triton_distributed_tpu.serving.run_server",
            "--model", args.model, "--port", "0", "--continuous",
            "--mode", mode,
        ]
        if args.kv_dtype:
            child += ["--kv-dtype", args.kv_dtype]
        if args.speculative:
            child += ["--speculative", str(args.speculative)]
        if args.tier_bytes:
            child += ["--tier-bytes", str(args.tier_bytes)]
        if args.cp > 1:
            child += ["--cp", str(args.cp)]
        if args.rank_page_budget:
            child += ["--rank-page-budget", str(args.rank_page_budget)]
        if args.tier_dir:
            # Restart-safe from one flag: children must export
            # snapshots for the supervisor's resume store (which
            # derives its pull cadence from resume_dir) to hold any.
            child += ["--snapshot-every", "8"]
        env = {"JAX_PLATFORMS": "cpu"} if args.cpu else None

        def make_spec(name, role="mixed"):
            argv_i = list(child)
            if args.tier_dir:
                # --tier-shared: every child mounts the SAME fabric
                # dir (docs/scale-out.md "KV fabric") so a fresh
                # replica boots warm from the pool's spills; default
                # stays per-child DIR/r<i>.
                argv_i += ["--tier-dir",
                           (args.tier_dir if args.tier_shared
                            else os.path.join(args.tier_dir, name))]
            return ReplicaSpec(name, argv_i, env=env, role=role)

    specs = [make_spec(name, role) for name, role in members]
    launcher = None
    if args.fake_hosts:
        # Local host failure domains (docs/scale-out.md "Multi-host
        # fleet"): round-robin the children across N named process
        # groups so killing a "host" is one correlated loss.
        from triton_distributed_tpu.serving.launcher import (
            FakeHostLauncher,
        )

        host_names = [f"h{i}" for i in range(args.fake_hosts)]
        launcher = FakeHostLauncher(host_names)
        for i, spec in enumerate(specs):
            spec.host = host_names[i % len(host_names)]
    sup = FleetSupervisor(
        specs,
        launcher=launcher,
        policy="pools" if pool_fleet else "affinity",
        resume_dir=(os.path.join(args.tier_dir, "resume")
                    if args.tier_dir else None),
        tier_fabric=(args.model != "stub"
                     and bool(args.tier_bytes or args.tier_dir)),
        router_kw={
            "request_timeout_s": args.request_timeout or None,
        },
    )
    router = sup.start()
    scaler = None
    if args.autoscale:
        from triton_distributed_tpu.serving.autoscaler import Autoscaler

        scaler = Autoscaler(
            sup, lambda role, name: make_spec(name, role),
            pool_bounds={
                "prefill": (args.prefill_replicas,
                            args.prefill_replicas + 2),
                "decode": (args.decode_replicas,
                           args.decode_replicas + 2),
            },
        ).start()
    server = ModelServer(router).start()
    print(json.dumps({
        "serving": args.model, "mode": mode,
        "fleet": len(members), "pools": router.pool_shape()
        if pool_fleet else None,
        "hosts": args.fake_hosts or None,
        "autoscale": bool(scaler), "port": server.port,
        "logs": sup.log_dir,
        "startup_s": round(time.time() - t0, 1),
    }), flush=True)
    try:
        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
        prompt = list(range(1, 33))
        payload = {"requests": [prompt], "gen_lens": [args.gen_len]}
        r1, r2, cold_s, warm_s = _drive_pair(
            server.host, server.port, payload, args.stream
        )
        gen1 = np.asarray(r1["outputs"][0])
        gen2 = np.asarray(r2["outputs"][0])
        router_stats = r2["stats"].get("router", {})
        print(json.dumps({
            "transcript_tokens": gen1.tolist(),
            "deterministic": bool(
                gen1.shape == gen2.shape and (gen1 == gen2).all()
            ),
            "cold_wall_s": round(cold_s, 2),
            "warm_wall_s": round(warm_s, 2),
            "wire_tok_s": round(args.gen_len / warm_s, 2),
            "statuses": [x["status"] for x in r2["results"]],
            "affinity_hits": router_stats.get("affinity_hits"),
            "routed": router_stats.get("routed"),
            "supervisor": sup.stats()["slots"],
        }, default=str), flush=True)
        if args.stats:
            stats = request(server.host, server.port, {"cmd": "stats"})
            print("== stats ==", flush=True)
            print(json.dumps(stats["stats"], indent=2, default=str),
                  flush=True)
    finally:
        import contextlib

        with contextlib.suppress(Exception):
            request(server.host, server.port, {"cmd": "shutdown"},
                    timeout=10.0)
        server.shutdown()
        if scaler is not None:
            scaler.stop()
        sup.shutdown()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="Qwen/Qwen3-0.6B",
                   help="model preset, checkpoint dir, 'stub', or "
                   "'moe' (tiny-moe Qwen3MoE preset; size with "
                   "--num-experts/--top-k — docs/serving.md "
                   "'MoE serving')")
    p.add_argument("--num-experts", type=int, default=0,
                   help="override the MoE preset's routed expert count")
    p.add_argument("--top-k", type=int, default=0,
                   help="override the MoE preset's experts-per-token")
    p.add_argument("--moe-intermediate", type=int, default=0,
                   help="override the MoE preset's per-expert FFN width")
    p.add_argument("--full", action="store_true",
                   help="full depth (default: num_layers=8, vocab 32768)")
    p.add_argument("--mode", default="mega",
                   choices=["xla", "pallas", "mega"])
    p.add_argument("--gen-len", type=int, default=32)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--kv-dtype", default=None, choices=["int8"],
                   help="int8-quantized paged KV pool (composes with "
                   "every --mode including mega — in-kernel dequant; "
                   "stats payload then carries kv_bytes_per_token/"
                   "kv_dtype through the wire)")
    p.add_argument("--speculative", type=int, default=0, metavar="K",
                   help="self-drafting speculative decoding, up to K "
                   "draft tokens per row (docs/serving.md); excluded "
                   "with --mode mega")
    p.add_argument("--tier-bytes", type=int, default=0,
                   help="host-RAM durable KV tier per engine, bytes "
                   "(0 = off): evicted radix pages spill and fault "
                   "back on digest match (docs/serving.md 'Tiered "
                   "KV'); with --fleet children inherit it")
    p.add_argument("--tier-dir", default=None, metavar="DIR",
                   help="disk tier directory (atomic, checksummed); "
                   "with --fleet each child gets DIR/r<i> (or the "
                   "shared DIR with --tier-shared) and the supervisor "
                   "persists pulled snapshots under DIR/resume — a "
                   "restart-safe fleet from one flag "
                   "(docs/scale-out.md 'Durable snapshots')")
    p.add_argument("--tier-shared", action="store_true",
                   help="share ONE KV tier across the replicas "
                   "(docs/scale-out.md 'KV fabric'): with --fleet "
                   "every child mounts the same --tier-dir; with "
                   "--replicas the engines share one in-process "
                   "PageStore")
    p.add_argument("--stats", action="store_true",
                   help="after generating, fetch {'cmd':'stats'} and "
                   "{'cmd':'metrics'} through the wire and pretty-print "
                   "the payloads (docs/observability.md)")
    p.add_argument("--replicas", type=int, default=0,
                   help="serve N ContinuousEngine replicas behind the "
                   "prefix-affinity router (docs/scale-out.md); the "
                   "demo then drives 'requests' payloads and the "
                   "repeat doubles as the affinity-hit check")
    p.add_argument("--fleet", type=int, default=0,
                   help="boot a SUPERVISED PROCESS fleet of N "
                   "run_server children (FleetSupervisor — "
                   "docs/scale-out.md 'Process fleet'; no model loads "
                   "in this process) and drive the router through the "
                   "wire exactly like --replicas; children inherit "
                   "--model/--mode/--kv-dtype/--speculative (note: "
                   "children load the NAMED preset — the demo's "
                   "depth-8 trim applies only in-process)")
    p.add_argument("--prefill-replicas", type=int, default=0,
                   help="role-typed PROCESS fleet: N children tagged "
                   "prefill, routed with --policy pools "
                   "(docs/scale-out.md 'Disaggregated pools & "
                   "autoscaling'); goes with --decode-replicas and "
                   "sizes the fleet itself — drop --fleet N")
    p.add_argument("--decode-replicas", type=int, default=0,
                   help="role-typed fleet: N children tagged decode "
                   "(post-prefill slots decode here)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the pool autoscaler over the role-typed "
                   "fleet (needs --prefill-replicas/--decode-replicas)")
    p.add_argument("--fake-hosts", type=int, default=0, metavar="N",
                   help="with --fleet/--prefill-replicas: partition "
                   "the children into N named fake hosts (process "
                   "groups h0..h{N-1}, docs/scale-out.md 'Multi-host "
                   "fleet') so host failure domains run locally — the "
                   "supervisor classifies whole-host loss as ONE "
                   "host_down and re-places survivors")
    p.add_argument("--stream", action="store_true",
                   help="drive the generation through the streaming "
                   "wire ('stream': true): tokens print as they "
                   "arrive and the report carries WIRE-side TTFT/TPOT "
                   "from the summary frame (docs/serving.md "
                   "'Streaming & cancellation'). Without --replicas/"
                   "--fleet the demo serves a ContinuousEngine (the "
                   "fixed-batch Engine has no per-token emission).")
    p.add_argument("--cp", type=int, default=1, metavar="N",
                   help="context-parallel chunked prefill width for the "
                   "continuous engines (docs/serving.md 'Long-context "
                   "serving'): one request's prompt is split across N "
                   "virtual ranks and the per-block KV exchange hides "
                   "under the next block's attention; excluded with "
                   "--mode mega and --speculative")
    p.add_argument("--rank-page-budget", type=int, default=0,
                   metavar="TOKENS",
                   help="per-rank resident KV budget in tokens for "
                   "sharded long-context slots (docs/serving.md "
                   "'Long-context serving'): a slot whose KV would "
                   "exceed it keeps a resident paged window and "
                   "demotes cold pages to the KV tier — needs "
                   "--tier-bytes/--tier-dir and --replicas/--fleet")
    p.add_argument("--request-timeout", type=float, default=0.0,
                   help="with --replicas: router-observed replica "
                   "timeout (seconds; 0 = off — a cold compile must "
                   "not read as a hang)")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="wrap the generation in group_profile(DIR) and "
                   "print the merged one-file timeline path — host "
                   "trace_spans plus (with --mode mega) the device "
                   "task tracer's per-task rows and their measured "
                   "overlap (docs/profiling.md 'Device task tracer')")
    args = p.parse_args(argv)
    # kv_dtype×mega and replicas×mega compose since PR 7 (the megakernel
    # is the general serving fast path — docs/megakernel.md); the ONE
    # remaining conflict is speculative×mega, refused loudly BY FLAG
    # NAME before any model loads, instead of silently downgrading the
    # mode. (--cpu coerces mega→xla below, which does compose.)
    if args.speculative and args.mode == "mega" and not args.cpu:
        p.error(
            "--speculative and --mode mega do not compose (the NS-step "
            "fused launch advances all slots in lockstep and already "
            "amortizes per-step dispatch, and the resident work ring "
            "splices whole slots between rounds — never a mid-launch "
            "verify/rollback; docs/megakernel.md 'Resident decode'); "
            "drop --speculative or use --mode xla/pallas"
        )
    longctx = args.cp > 1 or args.rank_page_budget
    if args.cp < 1:
        p.error("--cp takes a width >= 1")
    if longctx:
        # The run_server refusal set, mirrored (fail fast BY FLAG NAME
        # before any model loads — docs/serving.md 'Long-context
        # serving'). --cpu coerces mega→xla, which does compose.
        if args.mode == "mega" and not args.cpu:
            p.error(
                "--cp/--rank-page-budget and --mode mega do not "
                "compose (the megakernel's fused NS-step launch owns "
                "the whole batch; context-parallel prefill and "
                "sharded-slot decode ride the chunked paged path); "
                "use --mode xla/pallas"
            )
        if args.speculative:
            p.error(
                "--cp/--rank-page-budget and --speculative do not "
                "compose (draft/verify rollback assumes whole-slot "
                "resident KV); drop one"
            )
        if args.model == "stub":
            p.error(
                "--cp/--rank-page-budget need a real engine (stub "
                "children generate without a KV cache); use a real "
                "--model"
            )
        if not (args.replicas or args.stream or args.fleet
                or args.prefill_replicas or args.decode_replicas):
            p.error(
                "--cp/--rank-page-budget ride the continuous serving "
                "stack only (the fixed-batch Engine prefills in one "
                "shot); add --replicas N, --fleet N or --stream"
            )
    if args.rank_page_budget and not (args.tier_bytes or args.tier_dir):
        p.error(
            "--rank-page-budget demotes cold pages to the KV tier; "
            "add --tier-bytes N and/or --tier-dir DIR"
        )
    if (args.tier_bytes or args.tier_dir) and not (
            args.fleet or args.replicas):
        # Fail fast by flag name (the speculative×mega convention): the
        # single fixed-batch Engine has no tier — silently ignoring the
        # flags would fake restart-safety.
        p.error(
            "--tier-bytes/--tier-dir ride the continuous serving stack "
            "only (docs/serving.md 'Tiered KV'): add --replicas N or "
            "--fleet N"
        )
    if args.tier_bytes and args.fleet and args.model == "stub":
        p.error(
            "--tier-bytes does nothing on a stub fleet (stub children "
            "have no KV tier); --tier-dir still arms the supervisor's "
            "durable resume store, or use a real --model"
        )
    if args.tier_shared and args.fake_hosts:
        # Mirror run_server's refusal: a shared tier dir is one
        # filesystem and host failure domains model separate machines
        # — per-child tiers reach each other over the wire fabric.
        p.error(
            "--tier-shared cannot cross --fake-hosts failure domains "
            "(a shared dir is ONE host's disk); drop --tier-shared"
        )
    if args.tier_shared:
        # Refuse by flag name (the run_server convention): sharing a
        # tier needs multiple engines and a tier to share.
        if not (args.fleet or args.replicas > 1
                or args.prefill_replicas or args.decode_replicas):
            p.error(
                "--tier-shared shares ONE KV tier ACROSS replicas "
                "(docs/scale-out.md 'KV fabric'); add --fleet N or "
                "--replicas N (N >= 2)"
            )
        if args.model == "stub" and not args.replicas:
            p.error(
                "--tier-shared does nothing on a stub fleet (stub "
                "children have no KV tier); use a real --model"
            )
        if (args.fleet or args.prefill_replicas
                or args.decode_replicas) and not args.tier_dir:
            p.error(
                "--tier-shared on a PROCESS fleet shares through disk; "
                "give the common directory with --tier-dir DIR"
            )
        if args.replicas > 1 and not (args.tier_bytes or args.tier_dir):
            p.error(
                "--tier-shared needs a tier to share: add --tier-bytes "
                "N and/or --tier-dir DIR"
            )
    # Role-typed pools ride the PROCESS fleet only — refuse by flag
    # name everywhere else instead of silently serving an untyped
    # fleet (docs/scale-out.md 'Disaggregated pools & autoscaling').
    pool_fleet = args.prefill_replicas > 0 or args.decode_replicas > 0
    if pool_fleet:
        if args.prefill_replicas <= 0 or args.decode_replicas <= 0:
            p.error(
                "--prefill-replicas and --decode-replicas go together "
                "(a one-role fleet has nowhere to hand prefilled "
                "slots); give both, each >= 1"
            )
        if args.fleet:
            p.error(
                "--prefill-replicas/--decode-replicas size the fleet "
                "themselves (prefill+decode children); drop --fleet N"
            )
        if args.replicas:
            p.error(
                "--prefill-replicas/--decode-replicas are PROCESS-"
                "fleet pool shapes; in-process --replicas would "
                "silently ignore the role tags — drop --replicas"
            )
    if args.autoscale and not pool_fleet:
        p.error(
            "--autoscale resizes role pools: add --prefill-replicas N "
            "and --decode-replicas M"
        )
    if args.fake_hosts and not (args.fleet or pool_fleet):
        p.error(
            "--fake-hosts places PROCESS-fleet children on failure "
            "domains; add --fleet N or --prefill-replicas/"
            "--decode-replicas (docs/scale-out.md 'Multi-host fleet')"
        )

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from triton_distributed_tpu.models import AutoLLM, Engine
    from triton_distributed_tpu.runtime.mesh import initialize_distributed
    from triton_distributed_tpu.serving.server import ModelServer, request

    if args.fleet > 0 or pool_fleet:
        return _fleet_demo(args)

    t0 = time.time()
    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    # --model moe: the Qwen3MoE alias — resolved by the ONE helper
    # run_server's main uses (the tiny-moe preset is already tiny, so
    # the --full shrink overrides don't apply to it).
    from triton_distributed_tpu.serving.run_server import (
        resolve_model_args,
    )

    model_name, overrides = resolve_model_args(
        args.model, args.num_experts, args.top_k, args.moe_intermediate
    )
    if args.model != "moe" and not args.full:
        overrides.update({"num_layers": 8, "vocab_size": 32768})
    model = AutoLLM.from_pretrained(
        model_name, ctx=ctx, max_length=1024, **overrides
    )
    jax.block_until_ready(model.params)
    mode = args.mode if not (args.cpu and args.mode == "mega") else "xla"
    kernel_trace = bool(args.trace) and mode == "mega"
    if args.replicas > 0:
        from triton_distributed_tpu.models.continuous import ContinuousEngine
        from triton_distributed_tpu.serving.router import Router

        shared_tier = None
        if args.tier_shared and (args.tier_bytes or args.tier_dir):
            # One PageStore behind every replica (docs/scale-out.md
            # "KV fabric"): spills land where siblings fault back.
            from triton_distributed_tpu.models.kv_tier import PageStore

            shared_tier = PageStore(
                capacity_bytes=args.tier_bytes or (64 << 20),
                dir=args.tier_dir, fsync=False,
            )
        eng = Router([
            ContinuousEngine(
                model, max_batch=2, max_length=1024, mode=mode,
                temperature=0.0, prefix_cache=True,
                kv_dtype=args.kv_dtype, speculative=args.speculative,
                kernel_trace=kernel_trace,
                cp=args.cp,
                rank_page_budget=args.rank_page_budget,
                tier=shared_tier,
                tier_bytes=args.tier_bytes,
                tier_dir=(os.path.join(args.tier_dir, f"r{i}")
                          if args.tier_dir and shared_tier is None
                          else None),
            )
            for i in range(args.replicas)
        ], request_timeout_s=args.request_timeout or None)
    elif args.stream:
        # Streaming needs the continuous 'requests' path (per-token
        # emission); the fixed-batch Engine has none.
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        eng = ContinuousEngine(
            model, max_batch=2, max_length=1024, mode=mode,
            temperature=0.0, prefix_cache=True, kv_dtype=args.kv_dtype,
            speculative=args.speculative, kernel_trace=kernel_trace,
            cp=args.cp,
        )
    else:
        eng = Engine(model, temperature=0.0, mode=mode,
                     paged=bool(args.kv_dtype or args.speculative),
                     kv_dtype=args.kv_dtype,
                     speculative=args.speculative,
                     kernel_trace=kernel_trace)
    server = ModelServer(eng, trace_dir=args.trace).start()
    print(json.dumps({"serving": args.model, "mode": mode,
                      "replicas": args.replicas, "port": server.port,
                      "startup_s": round(time.time() - t0, 1)}), flush=True)
    try:
        import contextlib as _ctxlib

        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
        prompt = list(range(1, 33))
        if args.replicas > 0 or args.stream:
            payload = {"requests": [prompt], "gen_lens": [args.gen_len]}
        else:
            payload = {"input_ids": [prompt], "gen_len": args.gen_len}
        with _ctxlib.ExitStack() as stack:
            if args.trace:
                from triton_distributed_tpu.runtime.profiling import (
                    group_profile,
                )

                stack.enter_context(group_profile(
                    "serve_demo", out_dir=args.trace, merge=False
                ))
            r1, r2, cold_s, warm_s = _drive_pair(
                server.host, server.port, payload, args.stream
            )
        if args.trace:
            # ONE merged timeline: host trace_spans + (mega) the device
            # task tracer's per-task rows, tagged with request trace
            # ids (docs/profiling.md "Device task tracer").
            from triton_distributed_tpu.obs import kernel_trace as kt

            launches = getattr(
                eng, "kernel_trace_launches", lambda: []
            )()
            merged = kt.merge_with_host_profile(
                "serve_demo", args.trace, launches
            )
            print(json.dumps({
                "merged_trace": merged,
                "traced_mega_launches": len(launches),
            }), flush=True)
        if args.replicas > 0 or args.stream:
            gen1 = np.asarray(r1["outputs"][0])
            gen2 = np.asarray(r2["outputs"][0])
            router = r2["stats"].get("router", {})
            extra = {
                "statuses": [x["status"] for x in r2["results"]],
                # The repeat shares the full prompt: a working mirror
                # routes it back to the seeded replica as a hit.
                "affinity_hits": router.get("affinity_hits"),
                "routed": router.get("routed"),
            }
        else:
            gen1 = np.asarray(r1["output_ids"])[0, len(prompt):]
            gen2 = np.asarray(r2["output_ids"])[0, len(prompt):]
            extra = {}
        print(json.dumps({
            "platform": jax.devices()[0].platform,
            "transcript_tokens": gen1.tolist(),
            "deterministic": bool(
                gen1.shape == gen2.shape and (gen1 == gen2).all()
            ),
            "cold_wall_s": round(cold_s, 2),
            "warm_wall_s": round(warm_s, 2),
            "wire_tok_s": round(args.gen_len / warm_s, 2),
            "engine_stats": r2.get("stats"),
            **extra,
        }), flush=True)
        if args.stats:
            stats = request(server.host, server.port, {"cmd": "stats"})
            print("== stats ==", flush=True)
            print(json.dumps(stats["stats"], indent=2, default=str),
                  flush=True)
            m = request(server.host, server.port, {"cmd": "metrics"})
            print("== metrics (json snapshot) ==", flush=True)
            print(json.dumps(m["metrics"], indent=2, default=str),
                  flush=True)
            print("== metrics (prometheus) ==", flush=True)
            print(m["prometheus"], flush=True)
    finally:
        # A wedged generate (chip hang) leaves the accept loop busy: the
        # shutdown request would then time out too — never let it mask
        # the real failure or skip the local socket teardown.
        import contextlib

        with contextlib.suppress(Exception):
            request(server.host, server.port, {"cmd": "shutdown"},
                    timeout=10.0)
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
