"""On-chip randomized stress subset — Mosaic races interpret can't see.

VERDICT r3 task 8: the interpret-mode stress suite (tests/test_stress.py)
proves semantics but runs a simulator; only the real chip exercises
Mosaic's actual DMA/semaphore interleavings. This script loops the
tp=1-runnable hot paths with fresh random data each iteration (the
reference's stress pattern: ``stress_test_ag_gemm.py:54-81`` —
randomized loop, fixed shapes so nothing recompiles, golden check every
iteration):

  * megakernel multi-step decode — NS-step chain tokens must be
    BIT-IDENTICAL to the single-step chain (same kernel math, different
    launch structure: any staging/feedback race diverges them);
  * wq8 int8 decode — same single-vs-multi identity on the quantized
    kernels;
  * flash-decode — Pallas split-KV kernel vs the pure-XLA golden at
    randomized kv_len (tolerance; exercises the chunked softmax DMAs).

Exit 0 only on zero failures across all iterations.

Usage: python perf/onchip_stress.py [--iters 20]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--ns", type=int, default=8)
    p.add_argument("--layers", type=int, default=4,
                   help="reduced depth (relay-gentle; geometry stays "
                        "true 0.6B so tiles/DMAs are production-shaped)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    t0 = time.time()
    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained(
        "Qwen/Qwen3-0.6B", ctx=ctx, max_length=1024,
        num_layers=args.layers, vocab_size=32768,
    )
    jax.block_until_ready(model.params)

    from perf._chain import (
        multi_step_chain,
        prepare_decode_state,
        single_step_chain,
    )
    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    steps, ns = args.steps, args.ns
    failures = []

    def log(rec):
        print(json.dumps(rec), flush=True)

    # --- megakernel single-vs-multi (bf16 and wq8) --------------------
    for label, cfg in (("mega_bf16", None),
                       ("mega_q8", MegaConfig(wq8=True))):
        mega = MegaQwen3(model, cfg=cfg)
        params = (mega.quantized_params() if label == "mega_q8"
                  else mega._step_params())
        tok0, cache0, s_max = prepare_decode_state(model)
        sstep = mega.decode_fn(1, s_max)
        mstep = mega.decode_multi_fn(1, s_max, ns)
        n_fail = 0
        for it in range(args.iters):
            # Fresh random greedy start: perturb the starting token
            # (cache contents follow from the model's own prefill; the
            # race surface is the decode chain itself). Shapes are
            # fixed, so nothing recompiles across iterations.
            tok = jnp.asarray([(7 * it + 3) % 32768], jnp.int32)
            s_seq = single_step_chain(sstep, params, tok, cache0, steps)()
            m_seq = multi_step_chain(mstep, ns, params, tok, cache0, steps)()
            if (s_seq != m_seq).any():
                n_fail += 1
                failures.append({"path": label, "iter": it,
                                 "single": s_seq.tolist(),
                                 "multi": m_seq.tolist()})
        log({"path": label, "iters": args.iters, "failures": n_fail,
             "elapsed_s": round(time.time() - t0, 1)})

    # --- flash-decode vs golden at randomized kv_len ------------------
    from triton_distributed_tpu.ops.attention.flash_decode import (
        flash_decode,
        gqa_decode_reference,
    )

    B, HQ, HKV, HD, S = 2, 16, 8, 128, 512
    key = jax.random.PRNGKey(0)

    @jax.jit
    def fresh(key):
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, HQ, HD), jnp.bfloat16)
        kc = jax.random.normal(kk, (B, HKV, S, HD), jnp.bfloat16)
        vc = jax.random.normal(kv, (B, HKV, S, HD), jnp.bfloat16)
        return q, kc, vc

    fd = jax.jit(lambda q, kc, vc, kl: flash_decode(q, kc, vc, kl))
    gold_f = jax.jit(
        lambda q, kc, vc, kl: gqa_decode_reference(q, kc, vc, kl))
    n_fail = 0
    rng = np.random.default_rng(0)
    for it in range(args.iters):
        key = jax.random.fold_in(key, it)
        q, kc, vc = fresh(key)
        kl = jnp.asarray(rng.integers(1, S + 1, size=(B,)), jnp.int32)
        got = np.asarray(fd(q, kc, vc, kl), np.float32)
        want = np.asarray(gold_f(q, kc, vc, kl), np.float32)
        if np.isnan(got).any() or np.abs(got - want).max() > 2e-2:
            n_fail += 1
            failures.append({
                "path": "flash_decode", "iter": it,
                "kv_len": kl.tolist(),
                "max_err": float(np.abs(got - want).max()),
            })
    log({"path": "flash_decode", "iters": args.iters, "failures": n_fail,
         "elapsed_s": round(time.time() - t0, 1)})

    log({"summary": {"total_failures": len(failures),
                     "failures": failures[:5],
                     "platform": jax.devices()[0].platform,
                     "wall_s": round(time.time() - t0, 1)}})
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
