"""Staged on-chip work queue — run EVERYTHING pending when the relay answers.

The axon relay's outages (5+ hours observed; down for this entire
round-3 session so far) make chip time precious and first-contact load
risky (heavy pushes have twice correlated with wedging the relay —
skill notes). This runner executes the round's pending on-chip items in
ESCALATING order of load, each in its own subprocess with a timeout, so
one wedge costs one step, and appends every result to a JSONL log:

1. probe        — tiny: jax.devices() + 1 add (seconds)
2. kernel_smoke — one small Pallas ring kernel through Mosaic
3. sweep_small  — ag_gemm tile sweep at a reduced shape
4. ep_overhead  — perf/ep_a2a_overhead.py (device-initiated EP kernel)
5. adaptive_ag  — AG+GEMM adaptive-schedule order observation (n=1
                  degenerate: validates compile + order output on chip)
6. ladder       — bench.py full decode ladder (jit/pallas/mega/
                  mega_multi + token cross-check) — THE deliverable
7. e2e          — perf/real_weights_e2e.py (HF-format checkpoint,
                  mega_multi serve, transcript + tok/s)
8. sweep_full   — overlap tile sweeps at north-star shapes (bonus)

Usage: python perf/onchip_session.py [--log perf/ONCHIP_r3.jsonl]
       [--only ladder,e2e] [--skip sweep_full]
"""

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = """
import jax, numpy as np
d = jax.devices()
assert d[0].platform != "cpu", d
import jax.numpy as jnp
x = jnp.ones((8, 128)) + 1
print("probe ok:", d[0].device_kind, float(np.asarray(x).sum()))
"""

_KERNEL_SMOKE = """
import jax, numpy as np
import jax.numpy as jnp
from triton_distributed_tpu.runtime.mesh import initialize_distributed
from triton_distributed_tpu.ops.collectives.all_gather import (
    all_gather_op, AllGatherMethod)
ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
x = jnp.ones((16, 128), jnp.float32)
out = all_gather_op(x, "tp", AllGatherMethod.PALLAS_RING, ctx)
assert np.asarray(out).shape == (16, 128)
print("kernel smoke ok (Mosaic compile + run)")
"""

_ADAPTIVE_AG = """
import jax, numpy as np
import jax.numpy as jnp
from triton_distributed_tpu.runtime.mesh import initialize_distributed
from triton_distributed_tpu.ops.overlap.ag_gemm import AGGemmConfig, ag_gemm_op
ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
rng = np.random.default_rng(0)
a = jnp.asarray(rng.standard_normal((256, 512)), jnp.bfloat16)
b = jnp.asarray(rng.standard_normal((512, 512)), jnp.bfloat16)
cfg = AGGemmConfig(tile_n=128, adaptive=True)
out = ag_gemm_op(a, b, "tp", cfg, ctx)
gold = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
err = np.abs(np.asarray(out, np.float32) - gold)
assert err.max() < 2.0, err.max()
print("adaptive ag_gemm compiled+ran on chip (semaphore_read + SMEM order)")
"""

STEPS = [
    # A recovering relay's first contact can spend 20-40 s compiling
    # plus connection wobble — don't write off a live chip at 120 s.
    ("probe", [sys.executable, "-c", _PROBE], 240),
    ("kernel_smoke", [sys.executable, "-c", _KERNEL_SMOKE], 300),
    # Weight-stream sweep FIRST among the heavy steps: the winner lands
    # in MEGA_TUNED.json for the (next) ladder/bench — in a short relay
    # window these two are what move BENCH_r03.
    ("mega_tiles", [sys.executable, "perf/mega_tile_sweep.py"], 2400),
    # bench.py's own worst case: ~860 s probe retries + 2700 s global
    # worker deadline + CPU fallback ladder + teardown — the step
    # timeout must sit ABOVE it or the always-emit JSON contract breaks.
    ("ladder", [sys.executable, "bench.py"], 4800),
    ("sweep_small", [sys.executable, "perf/sweep_overlap_tiles.py",
                     "--m", "2048", "--k", "1024", "--n", "2048",
                     "--iters", "4"], 600),
    ("ep_overhead", [sys.executable, "perf/ep_a2a_overhead.py"], 600),
    # Slope-timed per-component decode profile: splits the measured
    # ladder's ms/step into per-matvec floors + fixed dispatch cost
    # (the number that decides where megakernel tuning goes next).
    ("decode_profile", [sys.executable, "perf/decode_profile.py"], 900),
    # int8 weight-stream variant of the tile sweep (informational; the
    # bf16 tuned file is never written by this step).
    ("mega_tiles_q8", [sys.executable, "perf/mega_tile_sweep.py",
                       "--q8", "--configs",
                       "1024:1024:2,1024:1024:4:1,2048:1024:4:1:1"], 1800),
    # Launch-width sweep: fits per-launch vs per-step megakernel cost
    # (decides whether wider NS or kernel-body tuning moves the ladder).
    ("mega_ns", [sys.executable, "perf/mega_ns_sweep.py"], 2400),
    ("adaptive_ag", [sys.executable, "-c", _ADAPTIVE_AG], 400),
    # e2e burned a full 1500 s budget twice with the relay HEALTHY for
    # part of it (03:19 run) — the torch-side checkpoint build plus the
    # host->device weight transfer need more headroom on this 1-core
    # host; phase markers on stderr now show where the time goes.
    ("e2e", [sys.executable, "perf/real_weights_e2e.py",
             "--mode", "mega_multi", "--gen-len", "64"], 2700),
    ("sweep_full", [sys.executable, "perf/sweep_overlap_tiles.py",
                    "--op", "gemm_rs"], 2400),
]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--log", default="perf/ONCHIP_r3.jsonl")
    p.add_argument("--only", default=None)
    p.add_argument("--skip", default="")
    args = p.parse_args(argv)

    only = set(args.only.split(",")) if args.only else None
    skip = set(args.skip.split(",")) if args.skip else set()
    failures = 0
    sys.path.insert(0, os.path.join(ROOT, "perf"))
    from _tpulock import HELD_ENV, acquire, release

    with open(os.path.join(ROOT, args.log), "a") as log:
        for name, argvs, timeout in STEPS:
            if (only and name not in only) or name in skip:
                continue
            # Serialize against a concurrently-launched bench.py (the
            # driver's end-of-round run): one chip, one measurer. Give
            # up after 15 min and run anyway (a wedged holder must not
            # stall the whole queue window). Children run with the
            # held-marker set so a step that itself runs bench.py (the
            # ladder) doesn't poll against its own parent's hold.
            lock = acquire(timeout_s=900)
            env = dict(os.environ)
            if lock is not None:
                env[HELD_ENV] = "1"
            t0 = time.time()
            rec = {"step": name, "t_start": round(t0, 1)}
            if lock is None:
                rec["lock"] = "contended (proceeded without)"
            try:
                try:
                    r = subprocess.run(
                        argvs, cwd=ROOT, timeout=timeout,
                        capture_output=True, text=True, env=env,
                    )
                    rec["rc"] = r.returncode
                    rec["stdout_tail"] = r.stdout[-2000:]
                    if r.returncode != 0:
                        rec["stderr_tail"] = r.stderr[-1000:]
                        failures += 1
                except subprocess.TimeoutExpired as e:
                    rec["rc"] = "timeout"

                    # Keep the partial output — it names the rung/step
                    # that wedged, which is the whole point of the log.
                    # (On timeout the attached output can be bytes even
                    # under text=True.)
                    def _tail(raw, k):
                        if isinstance(raw, bytes):
                            raw = raw.decode(errors="replace")
                        return (raw or "")[-k:]

                    rec["stdout_tail"] = _tail(e.stdout, 2000)
                    rec["stderr_tail"] = _tail(e.stderr, 1000)
                    failures += 1
                except Exception as e:  # spawn failure etc.
                    rec["rc"] = f"spawn-error: {type(e).__name__}: {e}"[:200]
                    failures += 1
            finally:
                release(lock)
                rec["wall_s"] = round(time.time() - t0, 1)
                log.write(json.dumps(rec) + "\n")
                log.flush()
            print(json.dumps({k: rec[k] for k in ("step", "rc", "wall_s")}),
                  flush=True)
            if name == "probe" and rec["rc"] != 0:
                print("[onchip] relay not answering; aborting session")
                return 1
    return 0 if failures == 0 else 2


if __name__ == "__main__":
    raise SystemExit(main())
