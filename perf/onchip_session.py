"""Staged on-chip work queue — run EVERYTHING pending when the relay answers.

The axon relay's outages (5+ hours observed, ~30-min windows every few
hours) make chip time precious and first-contact load risky (heavy
pushes have twice correlated with wedging the relay — skill notes).
This runner executes the round's pending on-chip items in ESCALATING
order of load, each in its own subprocess with a timeout, so one wedge
costs one step, and appends every result to a JSONL log.

Round-4 queue (VERDICT r3 "Next round" items in priority order):

1. probe          — tiny: jax.devices() + 1 add (seconds)
2. kernel_smoke   — one small Pallas ring kernel through Mosaic
3. ladder_first   — bank an UNTUNED ladder before anything heavy
                    (r5: four rounds of zero TPU numbers; also warms
                    the persistent compile cache for 4 and the driver)
4. mega_tiles     — weight-stream sweep → perf/MEGA_TUNED.json (task 2)
5. ladder         — bench.py 0.6B decode ladder, inherits the tuning
                    (task 1: the driver-artifact evidence class)
6. decode_profile — slope-timed per-matvec floors (task 3 split)
7. gemm_mfu       — plain-GEMM MFU at ≥3 shapes × variants (tasks 3+6)
8. ep_overhead    — EP dispatch-tax slope + block sweep (task 5)
9. adaptive_order — straggler-reaction order observation (task 7)
10. ladder_17     — bench.py at Qwen3-1.7B geometry (task 4:
                    headline-class decode on the chip)
11. e2e_17        — 1.7B HF-checkpoint serve, transcript + tok/s (task 4)
12. stress        — randomized on-chip stress subset (task 8)
13. mega_ns / mega_tiles_q8 / ladder_4b / ladder_8b_q8 / e2e /
    sweep_full — depth, int8 sweep, 4B/8B-geometry ladders, 0.6B e2e,
    north-star tile sweeps

Usage: python perf/onchip_session.py [--log perf/ONCHIP_r4.jsonl]
       [--only ladder,e2e_17] [--skip sweep_full]
"""

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = """
import jax, numpy as np
d = jax.devices()
assert d[0].platform != "cpu", d
import jax.numpy as jnp
x = jnp.ones((8, 128)) + 1
print("probe ok:", d[0].device_kind, float(np.asarray(x).sum()))
"""

_KERNEL_SMOKE = """
import jax, numpy as np
import jax.numpy as jnp
from triton_distributed_tpu.runtime.mesh import initialize_distributed
from triton_distributed_tpu.ops.collectives.all_gather import (
    all_gather_op, AllGatherMethod)
ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
x = jnp.ones((16, 128), jnp.float32)
out = all_gather_op(x, "tp", AllGatherMethod.PALLAS_RING, ctx)
assert np.asarray(out).shape == (16, 128)
print("kernel smoke ok (Mosaic compile + run)")
"""

# Entries: (name, argv, timeout_s[, extra_env]). Ordered by priority
# within escalating load — a ~30-min window drains the head; later
# windows resume from pending().
STEPS = [
    # A recovering relay's first contact can spend 20-40 s compiling
    # plus connection wobble — don't write off a live chip at 120 s.
    ("probe", [sys.executable, "-c", _PROBE], 240),
    ("kernel_smoke", [sys.executable, "-c", _KERNEL_SMOKE], 300),
    # BANK A LADDER FIRST (round 5): four rounds produced zero TPU
    # numbers — before spending ~20 min of a possibly-short window on
    # the tile sweep, land the untuned ladder (~2-5 min warm). It also
    # warms the compile cache for the SHARED compiles (model init,
    # jit/pallas rungs; the mega rungs recompile if the sweep picks a
    # non-default config — budget the post-sweep ladder accordingly).
    # Timeout: D + 2100 headroom (see the ladder note below).
    ("ladder_first", [sys.executable, "bench.py"], 3100,
     {"TDT_BENCH_DEADLINE_S": "900"}),
    # Weight-stream sweep FIRST among the heavy steps: the winner lands
    # in MEGA_TUNED.json for the (next) ladder/bench — these two are
    # what move BENCH_r04 (VERDICT task 2). Internal deadline sized so
    # sweep + tuned ladder BOTH fit one ~30-min window; the step
    # timeout leaves a full worst-case config (~400 s fresh compile)
    # PLUS finalize headroom past the deadline, so the tuned-file
    # write always runs before the SIGKILL.
    ("mega_tiles", [sys.executable, "perf/mega_tile_sweep.py",
                    "--deadline-s", "1200"], 1800),
    # Relay is UP here (probe gated), so bench's probe succeeds at
    # once; the reduced deadline stops a mid-ladder outage from
    # burning the session's window on probe retries. Step timeout must
    # sit ABOVE bench's worst case or the always-emit JSON contract
    # breaks: a worker launched just before the probe deadline
    # (D - 480 s) can stall through its longest per-rung watchdog
    # (mega_multi, 1800 s) before the kill + one re-probe (180 s) +
    # CPU stub (~480 s) — worst ~= D + 2100 s.
    ("ladder", [sys.executable, "bench.py"], 4000,
     {"TDT_BENCH_DEADLINE_S": "1800"}),
    # Slope-timed per-component decode profile: splits the measured
    # ladder's ms/step into per-matvec floors + fixed dispatch cost
    # (the number that decides where megakernel tuning goes next).
    ("decode_profile", [sys.executable, "perf/decode_profile.py"], 900),
    # Plain-GEMM MFU at >=3 shapes x accumulation/precision/layout
    # variants — explain-or-fix the 33% (VERDICT task 3), and the
    # perf model's non-anchor validation points (task 6).
    ("gemm_mfu", [sys.executable, "perf/gemm_mfu.py"], 1800),
    # MoE serving fast path: Qwen3MoE through the continuous stack,
    # mega vs unfused, tracer-measured A2A overlap (replaces the old
    # ep_a2a_overhead n=1 floor probe — the tracer stamps the real
    # windows inside the serving megakernel).
    ("moe_serve", [sys.executable, "perf/moe_serve_bench.py"], 1200),
    # Straggler-reaction proof: realized adaptive order vs ring order
    # under virtualized arrival skew (VERDICT task 7).
    ("adaptive_order", [sys.executable,
                        "perf/adaptive_order_probe.py"], 500),
    # Headline-class decode ladder: Qwen3-1.7B geometry, device-side
    # synthetic weights (no host transfer), all rungs incl. wq8
    # (VERDICT task 4).
    # Timeout: D + 2100 headroom (see the ladder note).
    ("ladder_17", [sys.executable, "bench.py"], 4600,
     {"TDT_BENCH_MODEL": "Qwen/Qwen3-1.7B",
      "TDT_BENCH_DEADLINE_S": "2400"}),
    # 1.7B HF-format checkpoint through Engine mega_multi: transcript
    # + tok/s (VERDICT task 4's serving half).
    ("e2e_17", [sys.executable, "perf/real_weights_e2e.py",
                "--geom", "1.7b", "--mode", "mega_multi",
                "--gen-len", "64"], 2700),
    # Live socket-server demo: transcript + tok/s measured THROUGH the
    # wire protocol (reference model_server.py:112-198 parity).
    ("serve_demo", [sys.executable, "perf/serve_demo.py",
                    "--mode", "mega", "--gen-len", "32"], 1200),
    # Randomized on-chip stress subset (VERDICT task 8).
    ("stress", [sys.executable, "perf/onchip_stress.py",
                "--iters", "12"], 1500),
    # Launch-width sweep: fits per-launch vs per-step megakernel cost
    # (decides whether wider NS or kernel-body tuning moves the ladder).
    ("mega_ns", [sys.executable, "perf/mega_ns_sweep.py"], 2400),
    # int8 weight-stream variant of the tile sweep (informational; the
    # bf16 tuned file is never written by this step).
    ("mega_tiles_q8", [sys.executable, "perf/mega_tile_sweep.py",
                       "--q8", "--configs",
                       "1024:1024:2,1024:1024:4:1,2048:1024:4:1:1"], 1800),
    # 4B-geometry ladder (8 GB bf16 params — the biggest bf16 model a
    # 16 GB v5e holds with headroom).
    # Timeout: D + 2100 headroom (see the ladder note).
    ("ladder_4b", [sys.executable, "bench.py"], 5200,
     {"TDT_BENCH_MODEL": "Qwen/Qwen3-4B",
      "TDT_BENCH_DEADLINE_S": "3000"}),
    # Beyond-HBM: 8B-geometry wq8 decode from synthetic int8 (the bf16
    # tree would exceed the chip; labeled synthetic in its output).
    ("ladder_8b_q8", [sys.executable, "perf/ladder_q8_synth.py"], 2400),
    ("e2e", [sys.executable, "perf/real_weights_e2e.py", "--full",
             "--mode", "mega_multi", "--gen-len", "64"], 2700),
    ("sweep_full", [sys.executable, "perf/sweep_overlap_tiles.py",
                    "--op", "gemm_rs"], 2400),
]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--log", default="perf/ONCHIP_r4.jsonl")
    p.add_argument("--only", default=None)
    p.add_argument("--skip", default="")
    args = p.parse_args(argv)

    only = set(args.only.split(",")) if args.only else None
    skip = set(args.skip.split(",")) if args.skip else set()
    failures = 0
    sys.path.insert(0, os.path.join(ROOT, "perf"))
    sys.path.insert(0, ROOT)
    from _tpulock import HELD_ENV, acquire, release

    from bench import _compile_cache_env

    def run_one(log, name, argvs, timeout, extra_env):
        """One bounded step under the chip lock + shared cache env,
        fully recorded (rc, output tails, wall) in the JSONL log.

        Serializes against a concurrently-launched bench.py (the
        driver's end-of-round run): one chip, one measurer. Gives up
        on the lock after 15 min and runs anyway (a wedged holder must
        not stall the whole queue window). Children run with the
        held-marker set so a step that itself runs bench.py (the
        ladder) doesn't poll against its own parent's hold."""
        lock = acquire(timeout_s=900)
        # Persistent compilation cache for EVERY step (one policy,
        # defined once in bench.py — VERDICT r4 next #8).
        env = _compile_cache_env(dict(os.environ))
        env.update(extra_env)
        if lock is not None:
            env[HELD_ENV] = "1"
        t0 = time.time()
        rec = {"step": name, "t_start": round(t0, 1)}
        if lock is None:
            rec["lock"] = "contended (proceeded without)"
        try:
            try:
                r = subprocess.run(
                    argvs, cwd=ROOT, timeout=timeout,
                    capture_output=True, text=True, env=env,
                )
                rec["rc"] = r.returncode
                rec["stdout_tail"] = r.stdout[-2000:]
                if r.returncode != 0:
                    rec["stderr_tail"] = r.stderr[-1000:]
            except subprocess.TimeoutExpired as e:
                rec["rc"] = "timeout"

                # Keep the partial output — it names the rung/step
                # that wedged, which is the whole point of the log.
                # (On timeout the attached output can be bytes even
                # under text=True.)
                def _tail(raw, k):
                    if isinstance(raw, bytes):
                        raw = raw.decode(errors="replace")
                    return (raw or "")[-k:]

                rec["stdout_tail"] = _tail(e.stdout, 2000)
                rec["stderr_tail"] = _tail(e.stderr, 1000)
            except Exception as e:  # spawn failure etc.
                rec["rc"] = f"spawn-error: {type(e).__name__}: {e}"[:200]
        finally:
            release(lock)
            rec["wall_s"] = round(time.time() - t0, 1)
            log.write(json.dumps(rec) + "\n")
            log.flush()
        print(json.dumps({k: rec[k] for k in ("step", "rc", "wall_s")}),
              flush=True)
        return rec

    prev_failed = False
    with open(os.path.join(ROOT, args.log), "a") as log:
        for entry in STEPS:
            name, argvs, timeout = entry[:3]
            extra_env = entry[3] if len(entry) > 3 else {}
            if (only and name not in only) or name in skip:
                continue
            if prev_failed and name != "probe":
                # The previous step failed — before burning this step's
                # timeout, distinguish "relay died mid-window" from a
                # step-local failure. Without this gate a mid-window
                # outage grinds serially through EVERY remaining step's
                # timeout (~10 h for a full queue) while the watcher —
                # blocked on this very process — cannot see the next
                # window open. Abort on a dead relay; the watcher
                # resumes probing and relaunches the pending steps.
                rep = run_one(
                    log, "reprobe", [sys.executable, "-c", _PROBE],
                    240, {},
                )
                if rep["rc"] != 0:
                    print("[onchip] relay died mid-session; aborting "
                          "(watcher will resume pending steps)")
                    return 1
                prev_failed = False
            rec = run_one(log, name, argvs, timeout, extra_env)
            if rec["rc"] != 0:
                failures += 1
            prev_failed = rec["rc"] != 0
            if name == "probe" and rec["rc"] != 0:
                print("[onchip] relay not answering; aborting session")
                return 1
    return 0 if failures == 0 else 2


if __name__ == "__main__":
    raise SystemExit(main())
