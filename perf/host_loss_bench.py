"""Host-loss goodput bench → perf/HOST_LOSS.json.

The ISSUE-18 capstone (docs/scale-out.md "Multi-host fleet"): a
2-host fleet (``FakeHostLauncher`` — real child processes grouped
into named "hosts") loses an ENTIRE host mid-stream under the PR-13
load generator, and every number reported survives a bit-exact gate.

Three arms:

1. **Host loss under load**: 4 stub replicas spread over hosts
   h0/h1 behind one front ``ModelServer``; ``perf/loadgen.py``
   replays Poisson/Zipf traffic through the STREAMING wire in three
   waves — pre-loss, loss (the ``host.down`` seam SIGKILLs every
   process group on h1 while batches are in flight), post-recovery.
   Reported: detection time (kill → the ONE ``host_down``
   classification), recovery time (kill → all slots healthy again on
   the survivor), snapshot-RESUMED vs REPLAYED recoveries (the
   supervisor's 0.05 s snapshot pulls seed mid-generation resumes),
   and goodput over time. Gates: every completed request in every
   wave is bit-exact vs the pure reference generator, zero client
   errors, exactly one ``host_down`` event, and post-recovery
   goodput ≥ 0.9 × pre-loss goodput.
2. **Zombie fence**: SIGSTOP a whole host mid-batch (one
   ``host_down``), finish everything bit-exact on the survivor, then
   THAW the zombie — its late completions must hit the epoch fence
   (``fenced_result_dropped``) and latch ZERO results.
3. **Fabric across the loss**: the perf/kv_fabric_bench.py topology
   (two real-model engines, disjoint hot-prefix shards, REAL wire
   peers) re-measured across a peer death: the cross-replica round
   must hold the KV_FABRIC.json fleet baseline, and after the peer's
   server dies the SURVIVOR serves BOTH shards from its local tier
   (adopted entries) — prefill-avoided ≥ the single-engine baseline
   with zero wire pulls.

Usage:  JAX_PLATFORMS=cpu python perf/host_loss_bench.py
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from perf.loadgen import LoadSpec, generate_trace, replay  # noqa: E402

# Arm 1/2 shape: enough replicas that losing a host halves capacity
# without zeroing it, a stub delay big enough that generations span
# several 0.05 s snapshot pulls (so mid-stream kills recover via
# RESUME, not only replay), and a client-side e2e SLO generous enough
# that steady-state waves meet it on this shared CPU host while
# outage-stalled requests can miss.
REPLICAS = 4
HOSTS = ("h0", "h1")
# The stub spreads delay_s over ONE batch's tokens: a batch is in
# flight for ~1 s, and the kill lands 0.5 s into it (the
# test_migration.py snapshot-resume regime) — the seam's own batch is
# mid-generation when the host dies, with several 0.05 s snapshot
# pulls already banked. Guaranteed in-flight loss, not a lucky lull.
STUB_DELAY_S = 1.0
RATE_RPS = 6.0
PRE_N = 16
LOSS_N = 36
POST_N = 16
KILL_AFTER_S = 0.5
E2E_SLO_S = 4.0
GOODPUT_RETENTION = 0.9   # post-recovery goodput ≥ 0.9 × pre-loss


def _reset_telemetry():
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics

    obs.set_enabled(True)
    obs_metrics.default_registry().clear()
    obs_events.default_ring().clear()


def _events():
    from triton_distributed_tpu.obs import events as obs_events

    return [e.as_dict() for e in obs_events.default_ring().tail(0)[0]]


def _load_spec(seed: int, n: int) -> LoadSpec:
    return LoadSpec(rate=RATE_RPS, n_requests=n, prefix_pool=6,
                    gen_min=8, gen_max=16, seed=seed)


def _judge_wave(trace, records) -> dict:
    """Gate a wave: zero errors, every token stream bit-exact vs the
    pure reference generator; goodput = met(e2e ≤ SLO) / n."""
    from triton_distributed_tpu.models.stub import stub_generate

    met = 0
    e2es = []
    for row, rec in zip(trace, records):
        assert not rec.get("error"), (
            f"request {row['i']} errored: {rec.get('error')}"
        )
        gold = stub_generate(row["prompt"], row["gen_len"])
        assert rec["tokens"] == gold, (
            f"request {row['i']} tokens diverged from reference"
        )
        e2e = (rec.get("wire") or {}).get("e2e_s")
        if e2e is not None:
            e2es.append(float(e2e))
            if e2e <= E2E_SLO_S:
                met += 1
    n = len(trace)
    return {
        "n": n,
        "met": met,
        "goodput": round(met / n, 4),
        "e2e_p50_s": round(float(np.percentile(e2es, 50)), 4),
        "e2e_p99_s": round(float(np.percentile(e2es, 99)), 4),
        "bit_exact": True,
        "errors": 0,
    }


def _timeline(trace, records) -> list[dict]:
    """Goodput over time: 1 s arrival buckets of the loss wave."""
    buckets: dict[int, list[int]] = {}
    for row, rec in zip(trace, records):
        b = buckets.setdefault(int(row["t"]), [0, 0])
        b[1] += 1
        e2e = (rec.get("wire") or {}).get("e2e_s")
        if e2e is not None and e2e <= E2E_SLO_S:
            b[0] += 1
    return [
        {"t_s": sec, "n": n, "goodput": round(m / n, 4)}
        for sec, (m, n) in sorted(buckets.items())
    ]


def arm_host_loss() -> dict:
    from triton_distributed_tpu.runtime.faults import FaultPlan
    from triton_distributed_tpu.serving.launcher import FakeHostLauncher
    from triton_distributed_tpu.serving.server import ModelServer
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    _reset_telemetry()
    laun = FakeHostLauncher(HOSTS)
    specs = [
        stub_spec(f"r{i}", delay_s=STUB_DELAY_S, page_size=4,
                  num_pages=256)
        for i in range(REPLICAS)
    ]
    for i, s in enumerate(specs):
        s.host = HOSTS[i % len(HOSTS)]
    sup = FleetSupervisor(
        specs, launcher=laun, heartbeat_s=0.1, heartbeat_timeout_s=1.0,
        heartbeat_misses=2, respawn_backoff_s=0.2, spawn_timeout_s=180.0,
        snapshot_s=0.05,
    )
    t_up0 = time.monotonic()
    router = sup.start()
    spawn_s = time.monotonic() - t_up0
    server = ModelServer(router, max_pending=64).start()
    stamps: dict[str, float] = {}
    try:
        pre = _judge_wave(
            t := generate_trace(_load_spec(5, PRE_N)),
            replay(t, server.host, server.port, timeout=120),
        )

        # The loss wave: the host.down seam fires on the first batch
        # dispatched to an h1 replica, sleeps KILL_AFTER_S on that
        # worker thread (the batch stays in flight, the host keeps
        # making real progress), then SIGKILLs every process group on
        # h1 — a machine dying with work on the wire. A watcher
        # thread stamps detection/recovery on the shared monotonic
        # clock the event ring uses.
        def watch():
            if sup.wait_for(
                lambda: sup.host_stats()["h1"]["down"], timeout_s=60
            ):
                stamps["down"] = time.monotonic()
            if sup.wait_healthy(REPLICAS, timeout_s=120):
                stamps["healthy"] = time.monotonic()

        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()
        loss_trace = generate_trace(_load_spec(6, LOSS_N))
        plan = FaultPlan(seed=7).kill_host(
            laun, host="h1", after_s=KILL_AFTER_S
        )
        with plan:
            loss_records = replay(loss_trace, server.host, server.port,
                                  timeout=120)
        assert plan.fired, "host.down seam never fired"
        watcher.join(timeout=120)
        assert "down" in stamps and "healthy" in stamps, sup.stats()
        loss = _judge_wave(loss_trace, loss_records)

        post = _judge_wave(
            t := generate_trace(_load_spec(8, POST_N)),
            replay(t, server.host, server.port, timeout=120),
        )

        evts = _events()
        t_seam = next(
            e["t"] for e in evts
            if e["kind"] == "fault"
            and e["fields"].get("seam") == "host.down"
        )
        t_kill = t_seam + KILL_AFTER_S  # the seam sleeps, then kills
        downs = [e for e in evts if e["kind"] == "host_down"]
        assert len(downs) == 1, downs  # ONE event for the whole host
        assert downs[0]["fields"]["host"] == "h1"
        lost_slots = sorted(downs[0]["fields"]["slots"])
        assert lost_slots == ["r1", "r3"]
        fo = [e["fields"] for e in evts if e["kind"] == "spawn_failover"]
        assert sorted(f["slot"] for f in fo) == ["r1", "r3"]
        assert all(f["from_host"] == "h1" and f["to_host"] == "h0"
                   for f in fo)
        hosts = sup.host_stats()
        assert sorted(hosts["h0"]["slots"]) == ["r0", "r1", "r2", "r3"]
        assert hosts["h1"]["slots"] == [] and hosts["h1"]["epoch"] == 1

        # Recovery ledger: reroute events count interrupted tickets;
        # snapshot_resume events say which of them kept their partial
        # generations (tokens NOT re-decoded) instead of replaying.
        rerouted = sum(
            1 for e in evts
            if e["kind"] == "reroute" and e["fields"].get("attempt") == 1
        )
        resumes = [e["fields"] for e in evts
                   if e["kind"] == "snapshot_resume"]
        resumed_tickets = {r["ticket"] for r in resumes}
        resumed_tokens = sum(int(r.get("tokens") or 0) for r in resumes)
        assert rerouted >= 1, "host died with nothing in flight"
        assert resumed_tickets and resumed_tokens >= 1, (
            "no mid-generation snapshot resume — the kill landed "
            "between generations"
        )
        assert pre["goodput"] >= GOODPUT_RETENTION
        assert post["goodput"] >= GOODPUT_RETENTION * pre["goodput"]

        return {
            "replicas": REPLICAS,
            "hosts": list(HOSTS),
            "stub_delay_s": STUB_DELAY_S,
            "rate_rps": RATE_RPS,
            "e2e_slo_s": E2E_SLO_S,
            "fleet_spawn_s": round(spawn_s, 3),
            "lost_host": "h1",
            "lost_slots": lost_slots,
            "host_down_events": 1,
            "detection_s": round(stamps["down"] - t_kill, 4),
            "recovery_s": round(stamps["healthy"] - t_kill, 4),
            "rerouted_requests": int(rerouted),
            "snapshot_resumed_requests": len(resumed_tickets),
            "snapshot_resumed_tokens": int(resumed_tokens),
            "replayed_requests": max(
                int(rerouted) - len(resumed_tickets), 0
            ),
            "goodput_pre_loss": pre,
            "goodput_during_loss": loss,
            "goodput_post_recovery": post,
            "goodput_retention": round(
                post["goodput"] / pre["goodput"], 4
            ),
            "loss_wave_timeline": _timeline(loss_trace, loss_records),
            "spawn_failovers": fo,
        }
    finally:
        server.shutdown()
        sup.shutdown()


def arm_zombie_fence() -> dict:
    from triton_distributed_tpu.models.stub import stub_generate
    from triton_distributed_tpu.runtime.faults import FaultPlan
    from triton_distributed_tpu.serving.launcher import FakeHostLauncher
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    _reset_telemetry()
    laun = FakeHostLauncher(HOSTS)
    specs = [
        stub_spec(f"r{i}", delay_s=0.4, page_size=4, num_pages=64)
        for i in range(3)
    ]
    for s, h in zip(specs, ("h0", "h1", "h1")):
        s.host = h
    sup = FleetSupervisor(
        specs, launcher=laun, heartbeat_s=0.1, heartbeat_timeout_s=1.0,
        heartbeat_misses=2, respawn_backoff_s=0.2, spawn_timeout_s=180.0,
        router_kw={"request_timeout_s": 1.5},
    )
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 200, size=16).astype(np.int32)
               for _ in range(6)]
    gens = [6] * len(prompts)
    golds = [stub_generate(p, g) for p, g in zip(prompts, gens)]
    try:
        router = sup.start()
        zombies = [router.replica("r1"), router.replica("r2")]
        t0 = time.monotonic()
        with FaultPlan(seed=4).hang_host(laun, host="h1") as plan:
            res = router.run(list(zip(prompts, gens)), results=True)
            assert plan.fired, "hang seam never fired"
            for r, gold in zip(res, golds):
                assert r.status == "ok", (r.status, r.reason)
                assert r.tokens.tolist() == gold
            assert sup.wait_for(
                lambda: sup.host_stats()["h1"]["down"], timeout_s=60
            ), sup.stats()
            assert sup.wait_healthy(3, timeout_s=120), sup.stats()
            recovered_s = time.monotonic() - t0
            assert all(z.fenced for z in zombies)
            epoch = sup.host_stats()["h1"]["epoch"]
            assert {z.fence_epoch for z in zombies} == {epoch}
            laun.thaw_host("h1")
            assert sup.wait_for(
                lambda: any(e["kind"] == "fenced_result_dropped"
                            for e in _events()),
                timeout_s=60,
            ), "thawed zombie never hit the fence"
        # The dead generation latched NOTHING into the fleet.
        for z in zombies:
            assert z.served == 0 and z.runs == 0
        evts = _events()
        downs = [e for e in evts if e["kind"] == "host_down"]
        assert len(downs) == 1 and downs[0]["fields"]["host"] == "h1"
        dropped = [e["fields"] for e in evts
                   if e["kind"] == "fenced_result_dropped"]
        assert sup.host_stats()["h1"]["down"]  # rejoin stays refused
        return {
            "replicas": 3,
            "hang_host": "h1",
            "host_down_events": 1,
            "fence_epoch": int(epoch),
            "requests_bit_exact": len(prompts),
            "recovered_s": round(recovered_s, 3),
            "fenced_drops": len(dropped),
            "fenced_tickets_dropped": int(
                sum(int(d.get("tickets") or 0) for d in dropped)
            ),
            "zombie_results_latched": 0,
        }
    finally:
        sup.shutdown()


def arm_fabric_across_loss() -> dict:
    """The KV_FABRIC.json topology re-measured across a peer death:
    real tiny model, two tiered engines with REAL wire peers; after
    the cross round (which adopts the peer's shard locally) the peer's
    server dies — the survivor must serve BOTH shards from its local
    tier with zero wire pulls."""
    from perf.kv_fabric_bench import (
        BASELINE_AVOIDED,
        HOTS_PER_REPLICA,
        MAX_LENGTH,
        PREFIX_TOKENS,
        Gold,
        _arrival,
        _mk_engine,
        _seed_shard,
    )
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.kv_tier import FabricClient
    from triton_distributed_tpu.runtime import mesh as mesh_mod
    from triton_distributed_tpu.serving.server import ModelServer, request

    # The fleet-cross bar KV_FABRIC.json certifies; re-read from the
    # artifact when present so the gate tracks the measured baseline.
    cross_baseline = 0.9231
    kvf_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "KV_FABRIC.json")
    if os.path.exists(kvf_path):
        with open(kvf_path) as f:
            cross_baseline = json.load(f)["sharded_fleet_fabric"][
                "cross_replica_round"]["prefill_work_avoided_frac"]

    ctx = mesh_mod.initialize_distributed(
        tp=min(4, len(jax.devices())), devices=jax.devices()[:4]
    )
    try:
        model = AutoLLM.from_pretrained("tiny", ctx=ctx,
                                        max_length=MAX_LENGTH)
        gold = Gold(model)
        rng = np.random.default_rng(7)
        shards = [
            [rng.integers(1, 200, size=PREFIX_TOKENS).astype(np.int32)
             for _ in range(HOTS_PER_REPLICA)]
            for _ in range(2)
        ]
        clients = [FabricClient(pull_timeout_s=5.0) for _ in range(2)]
        engines = [_mk_engine(model, tier=True, fabric=clients[i])
                   for i in range(2)]
        for eng, hots in zip(engines, shards):
            _seed_shard(eng, gold, hots, rng)
        servers = [ModelServer(e).start() for e in engines]
        try:
            for i, fc in enumerate(clients):
                peer = servers[1 - i]
                fc.set_wire_peers([
                    {"name": f"r{1 - i}", "host": peer.host,
                     "port": peer.port},
                ])

            def round_over(targets) -> dict:
                prefill = prompt = remote = hits = 0
                for owner, hots in enumerate(shards):
                    eng = targets(owner)
                    for h in hots:
                        req = _arrival(h, rng)
                        st = gold.check(eng, req)
                        prefill += st["prefill_tokens"]
                        prompt += len(req[0])
                        remote += st["tier_remote_pages"]
                        hits += st["tier_hits"]
                return {
                    "prefill_tokens": int(prefill),
                    "prompt_tokens": int(prompt),
                    "prefill_work_avoided_frac": round(
                        1.0 - prefill / prompt, 4
                    ),
                    "tier_remote_pages": int(remote),
                    "tier_hits": int(hits),
                }

            # Cross round: every prefix to the NON-owner (the fleet
            # shape KV_FABRIC.json measured) — pulls cross the wire
            # and the puller ADOPTS the entries locally.
            cross = round_over(lambda owner: engines[1 - owner])
            assert cross["tier_remote_pages"] > 0, "fabric never pulled"
            assert (cross["prefill_work_avoided_frac"]
                    >= cross_baseline), cross

            # The peer's host dies: its server goes away for good.
            try:
                request(servers[1].host, servers[1].port,
                        {"cmd": "shutdown"}, timeout=10.0)
            except Exception:  # noqa: BLE001 — death is the point
                pass
            servers[1].shutdown()
            servers = servers[:1]

            # The survivor serves BOTH shards: its own from its seeded
            # tier, the dead peer's from the entries the cross round
            # adopted — no wire left to pull from, and none needed.
            post = round_over(lambda owner: engines[0])
            assert post["tier_remote_pages"] == 0, (
                "survivor still pulling from a dead peer"
            )
            assert (post["prefill_work_avoided_frac"]
                    >= BASELINE_AVOIDED), post
            assert engines[0].audit() == []
            return {
                "replicas": 2,
                "hot_prefixes_per_replica": HOTS_PER_REPLICA,
                "fleet_cross_baseline_avoided_frac": cross_baseline,
                "single_engine_baseline_avoided_frac": BASELINE_AVOIDED,
                "cross_replica_round": cross,
                "post_loss_survivor_round": post,
                "bit_exact": True,  # per arrival, in Gold.check
            }
        finally:
            for srv in servers:
                try:
                    request(srv.host, srv.port, {"cmd": "shutdown"},
                            timeout=10.0)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                srv.shutdown()
    finally:
        mesh_mod.finalize_distributed()


def main() -> int:
    t0 = time.time()
    host_loss = arm_host_loss()
    fence = arm_zombie_fence()
    fabric = arm_fabric_across_loss()
    result = {
        "metric": "host_loss_detection_recovery_and_goodput_retention",
        "platform": jax.default_backend(),
        "host_loss": host_loss,
        "zombie_fence": fence,
        "fabric_across_loss": fabric,
        "wall_s": round(time.time() - t0, 2),
        "provenance": {
            "harness": "perf/host_loss_bench.py — FakeHostLauncher "
            "fleet (process groups as named hosts) under the PR-13 "
            "load generator through the STREAMING wire; the host.down "
            "seam SIGKILLs/SIGSTOPs every process group on one host "
            "mid-batch; the fabric arm reuses the kv_fabric_bench "
            "topology (real tiny model, real wire peers) across a "
            "peer death",
            "gates": "every completed request in every wave asserted "
            "bit-exact vs the pure reference generator (zero client "
            "errors); exactly ONE host_down classification per loss; "
            "post-recovery goodput >= 0.9 x pre-loss; >=1 "
            "mid-generation snapshot resume; thawed zombie latches "
            "ZERO results; survivor serves both shards with zero "
            "wire pulls at >= the single-engine avoided baseline",
            "caveat": "wall-clock detection/recovery and goodput are "
            "host-advisory on this shared CPU container; the "
            "classification counts, token identity, fence behavior, "
            "and prefill-avoided fractions are the certified levers",
        },
    }
    print(json.dumps(result), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "HOST_LOSS.json")
    with open(out, "w") as f:
        f.write(json.dumps(result, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
