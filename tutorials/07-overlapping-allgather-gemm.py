"""Tutorial 07: AllGather + GEMM overlap — the TP prefill archetype.

Parity: reference ``tutorials/07-overlapping-allgather-gemm.py`` —
producer all-gather on a comm stream overlapped with a consumer GEMM
spinning on per-chunk barriers (``allgather_gemm.py``).

TPU redesign (no user streams): ONE Pallas kernel drives both. The ICI
DMA engines carry the gather in the background while the MXU computes;
per-chunk DMA semaphores sequence arrival → compute. Step s computes
chunk (me+s) mod n, so compute starts on the local chunk with zero comm
latency and each later chunk's wait overlaps the previous chunk's GEMM.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.ops import ag_gemm_op
from triton_distributed_tpu.ops.overlap.ag_gemm import AGGemmConfig
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(tp=min(4, len(jax.devices())))
    n = ctx.axis_size("tp")
    rng = np.random.default_rng(0)
    m_per, k, n_cols = 16, 64, 256
    a = jnp.asarray(rng.standard_normal((n * m_per, k)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n_cols)), jnp.float32)

    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128), ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=2e-4, atol=2e-4
    )
    print(f"overlapped AG+GEMM over {n} ranks: OK")

    # Race-provocation fixture (parity: for_correctness + straggler):
    # lag rank 1's pushes; the per-chunk waits must still serialize.
    cfg = AGGemmConfig(tile_n=128, straggler_rank=1, straggler_nanos=200_000)
    out = ag_gemm_op(a, b, "tp", cfg, ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=2e-4, atol=2e-4
    )
    print("overlapped AG+GEMM with straggler rank 1: OK")


if __name__ == "__main__":
    main()
