"""Tutorial 11: paged KV cache — pool, page table, paged decode paths.

Parity: reference ``mega_triton_kernel/models/paged_kv_cache.py`` — a
page-pool cache with free-list allocation, consumed by its megakernel's
attention task through a page table.

TPU design: the pool is one array ``[L, P, Hkv, page, hd]``; the page
table rides as a scalar-prefetch operand and ``paged_flash_decode``'s
K/V BlockSpec index maps dereference it — block ``ci`` of sequence ``b``
fetches pool page ``table[b, ci]``, so attention reads the pool
directly and NO dense gather ever materializes. Three consumers share
the design: the model decode step (``decode_step`` dispatches on cache
type), ``Engine(paged=True)`` serving, and the megakernel (per-row page
DMAs in its attention block loop).
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.paged_kv_cache import (
    init_paged_cache,
    write_prefill,
)
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(tp=min(4, len(jax.devices())))
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)

    # 1. The pool: pages are S-axis tiles; sequences own page LISTS
    #    (allocation is host-side control-plane work, per sequence).
    paged, pool = init_paged_cache(
        model.cfg, batch_size=2, ctx=ctx, max_length=64, page_size=16
    )
    print("pool pages:", paged.k_pages.shape[1],
          "table:", np.asarray(paged.page_table).tolist())

    # 2. Dense prefill per sequence, scattered into pages (one slice
    #    copy per page — jitted + donated, so the pool updates in place).
    dense1 = model.new_cache(1, 64)
    toks = jnp.asarray([5, 9, 2, 4, 8, 6, 7, 3], jnp.int32)
    logits, filled = model.prefill(toks, dense1, "xla")
    paged = write_prefill(paged, 0, filled.k, filled.v, len(toks))
    paged = write_prefill(paged, 1, filled.k, filled.v, len(toks))

    # 3. Paged decode: same decode_step entry point — the cache type
    #    selects the paged path (append through the table +
    #    paged_flash_decode over the pool).
    tok = jnp.argmax(logits)[None].repeat(2).astype(jnp.int32)
    logits_p, paged = model.decode_step(tok, paged, "xla")
    print("paged decode logits:", logits_p.shape)

    # 4. End-to-end: Engine(paged=True) serves identically to dense.
    prompt = np.asarray([[5, 9, 2, 4, 8, 6, 7, 3]] * 2, np.int32)
    out_d = Engine(model, temperature=0.0).serve(prompt, gen_len=4)
    out_p = Engine(model, temperature=0.0, paged=True, page_size=16).serve(
        prompt, gen_len=4
    )
    np.testing.assert_array_equal(out_d, out_p)
    print("paged serving matches dense token-for-token: OK")

    # 5. Quantized pool: kv_dtype="int8" stores int8 codes plus
    #    per-page-per-head scales, dequantized INSIDE the attention
    #    kernels — ~half the bf16 pool's bytes per cached token
    #    (docs/serving.md "Quantized KV cache").
    eng_q = Engine(model, temperature=0.0, paged=True, page_size=16,
                   kv_dtype="int8")
    eng_q.serve(prompt, gen_len=4)
    print("int8 pool:", eng_q.last_stats["kv_dtype"],
          "bytes/token:", eng_q.last_stats["kv_bytes_per_token"])


if __name__ == "__main__":
    main()
