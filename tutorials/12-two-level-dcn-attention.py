"""Tutorial 12: two-level (DCN x ICI) sequence parallelism.

Parity: reference ``sp_ag_attention_inter_node.py`` (NVSHMEM intra-node
+ IB inter-node KV gather) and its multi-node flash-decode scaling
(``README.md:202-209``, 32 GPUs = 4 nodes x 8).

TPU design: sequence shards lay out over ``(dcn, ici)`` in rank order.
For prefill attention, the intra-slice half runs the fused one-kernel
Pallas gather+attention (emitting per-row log-sum-exp) while earlier
slices' KV — fully visible under causality — streams through an XLA
online softmax; the halves merge by LSE. For decode, partial (O, LSE)
merge first over the fast ICI fabric, then once over DCN. The split
point is exactly where the reference splits intra/inter-node: ICI is
device-initiable (Pallas remote DMA), DCN is XLA's domain.

On the simulated mesh, a dp axis stands in for DCN.
"""

import functools

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.attention import (
    distributed_flash_decode_2level,
    gqa_decode_reference,
    mha_reference,
    sp_ag_attention_2level,
)
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    n = len(jax.devices())
    dcn = 2 if n >= 2 else 1  # single-chip: degenerate to one slice
    ctx = initialize_distributed({"dcn": dcn, "tp": max(n // dcn, 1)})
    rng = np.random.default_rng(0)

    # Long-context prefill: causal SP attention across 2 slices.
    hq, hkv, S, hd = 4, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((hq, S, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, S, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, S, hd)), jnp.float32)
    f = ctx.shard_map(
        functools.partial(
            sp_ag_attention_2level, inner_axis="tp", outer_axis="dcn",
            block_q=16, ctx=ctx,
        ),
        in_specs=(P(None, ("dcn", "tp"), None),) * 3,
        out_specs=P(None, ("dcn", "tp"), None),
    )
    out = f(q, k, v)
    gold = mha_reference(q[None], k[None], v[None], causal=True)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(gold),
                               atol=2e-5, rtol=2e-5)
    print("2-level SP attention matches dense causal golden: OK")

    # Distributed decode: KV sharded over both levels, two LSE merges.
    B = 2
    qd = jnp.asarray(rng.standard_normal((B, hq, hd)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, hkv, S, hd)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, hkv, S, hd)), jnp.float32)
    lens = jnp.asarray([S, S // 2], jnp.int32)
    f = ctx.shard_map(
        functools.partial(
            distributed_flash_decode_2level, inner_axis="tp",
            outer_axis="dcn", chunk_k=16, ctx=ctx,
        ),
        in_specs=(P(), P(None, None, ("dcn", "tp"), None),
                  P(None, None, ("dcn", "tp"), None), P()),
        out_specs=P(),
    )
    outd = f(qd, kc, vc, lens)
    goldd = gqa_decode_reference(qd, kc, vc, lens)
    np.testing.assert_allclose(np.asarray(outd), np.asarray(goldd),
                               atol=2e-5, rtol=2e-5)
    print("2-level distributed decode matches dense golden: OK")


if __name__ == "__main__":
    main()
