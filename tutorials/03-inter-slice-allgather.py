"""Tutorial 03: hierarchical all-gather across slices (ICI + DCN).

Parity: reference ``tutorials/03-inter-node-allgather.py`` (2D push:
NVLink stage intra-node, RDMA ring across nodes). TPU translation
(SURVEY.md §2.4): intra-slice stage = Pallas kernel over ICI; the
inter-slice stage rides XLA's DCN collectives (DCN transfers cannot be
device-initiated, so the 2-level split is structural, exactly like the
reference's intra/inter-node split).

The simulated mesh uses axes (dcn=2, tp=4) — on real hardware the outer
axis maps across slices/hosts.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.ops.collectives.hierarchical import all_gather_2d_op
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    nd = len(jax.devices())
    if nd >= 8:
        dcn, tp = 2, 4
    elif nd >= 2:
        dcn, tp = 2, nd // 2
    else:
        dcn, tp = 1, 1
    ctx = initialize_distributed({"dcn": dcn, "tp": tp})
    n = dcn * tp
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)

    out = all_gather_2d_op(x, inner_axis="tp", outer_axis="dcn", ctx=ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    print(f"hierarchical all-gather over {dcn}x{tp} (dcn x ici): OK")


if __name__ == "__main__":
    main()
