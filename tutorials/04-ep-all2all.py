"""Tutorial 04: expert-parallel all-to-all dispatch/combine.

Parity: reference ``tutorials/04-deepseek-infer-all2all.py`` — the
DeepEP-style EP pipeline: exchange per-rank token splits, dispatch each
token's hidden state to the ranks owning its top-k experts, run expert
FFNs, combine weighted results back (``ep_a2a.py:37-335``,
``low_latency_all_to_all.py``).

TPU design: splits-exchange and payload movement ride
``jax.lax.all_to_all`` / the Pallas single-hop a2a; static capacity
padding replaces the reference's dynamic recv offsets (XLA wants static
shapes — the reference also caps tokens per rank). The full per-shard
layer is ``ops.moe.ep_a2a.ep_moe_ffn``; this tutorial shards tokens and
experts over a 4-rank EP axis and checks against a dense golden MoE.
"""

from _common import setup

jax = setup()

import functools

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.moe import ep_moe_ffn
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(ep=min(4, len(jax.devices())))
    n = ctx.axis_size("ep")
    E, k, t_loc, d, f = 2 * n, 2, 8, 64, 32
    rng = np.random.default_rng(0)

    x = jnp.asarray(rng.standard_normal((n * t_loc, d)) * 0.1, jnp.float32)
    w_router = jnp.asarray(rng.standard_normal((d, E)) * 0.1, jnp.float32)
    gate = jnp.asarray(rng.standard_normal((E, d, f)) * d**-0.5, jnp.float32)
    up = jnp.asarray(rng.standard_normal((E, d, f)) * d**-0.5, jnp.float32)
    down = jnp.asarray(rng.standard_normal((E, f, d)) * f**-0.5, jnp.float32)
    w1 = jnp.concatenate([gate, up], axis=2)  # fused [E, d, 2f]

    fn = ctx.shard_map(
        functools.partial(
            ep_moe_ffn, k=k, axis="ep", ctx=ctx  # lossless splits-exchange path
        ),
        in_specs=(P("ep", None), P(), P("ep", None, None), P("ep", None, None)),
        out_specs=P("ep", None),
    )
    out = np.asarray(fn(x, w_router, w1, down))

    # Dense golden: route every token, run its experts, weighted-sum.
    logits = np.asarray(x) @ np.asarray(w_router)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    gold = np.zeros_like(out)
    for t in range(out.shape[0]):
        ids = np.argsort(-probs[t])[:k]
        w = probs[t][ids] / probs[t][ids].sum()
        for wj, e in zip(w, ids):
            h = np.asarray(x[t]) @ np.asarray(gate[e])
            u = np.asarray(x[t]) @ np.asarray(up[e])
            act = h / (1 + np.exp(-h)) * u
            gold[t] += wj * (act @ np.asarray(down[e]))

    np.testing.assert_allclose(out, gold, rtol=5e-4, atol=5e-4)
    print(f"EP all-to-all MoE over {n} ranks ({E} experts, top-{k}): OK")


if __name__ == "__main__":
    main()
