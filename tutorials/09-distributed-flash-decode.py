"""Tutorial 09: distributed flash-decode (SP over the KV cache).

Parity: reference distributed GQA flash-decode — each rank attends the
query over its sequence-shard of the KV cache (split-KV kernel,
``flash_decode.py:130/587``), then partial outputs + log-sum-exp are
exchanged across ranks and merged (inter-rank combine,
``flash_decode.py:482``; README "Scaling of Distributed Flash-Decode",
1→32 GPUs). This is how decode escapes the single-chip HBM ceiling for
long contexts.

TPU translation: the per-rank split-KV kernel is a Pallas
scalar-prefetch kernel; the cross-rank (O, LSE) exchange rides the ICI
all-gather (``method='pallas'``) or the XLA collective; the LSE merge is
the standard softmax re-normalization, associative so rank order doesn't
matter.
"""

from _common import setup

jax = setup()

import functools

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.attention.flash_decode import (
    distributed_flash_decode,
    gqa_decode_reference,
)
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(sp=min(4, len(jax.devices())))
    n = ctx.axis_size("sp")
    rng = np.random.default_rng(0)
    B, hq, hkv, hd = 2, 8, 2, 64
    s_loc = 64
    S = n * s_loc

    q = jnp.asarray(rng.standard_normal((B, hq, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, hkv, S, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, hkv, S, hd)), jnp.float32)
    kv_len = jnp.asarray([S, S // 2 + 3], jnp.int32)  # ragged contexts

    f = ctx.shard_map(
        functools.partial(
            distributed_flash_decode, axis="sp", chunk_k=32, ctx=ctx
        ),
        in_specs=(P(), P(None, None, "sp", None), P(None, None, "sp", None), P()),
        out_specs=P(),
    )
    out = np.asarray(f(q, k, v, kv_len))
    gold = np.asarray(gqa_decode_reference(q, k, v, kv_len))
    np.testing.assert_allclose(out, gold, rtol=2e-4, atol=2e-4)
    print(f"distributed flash-decode over {n} KV shards: OK")


if __name__ == "__main__":
    main()
