"""Shared tutorial setup: simulated 8-device CPU mesh by default.

Run any tutorial with ``--tpu`` to use the real TPU devices instead
(single-chip environments degenerate the comm patterns to n=1).
The environment may pin jax_platforms at interpreter startup, so the
override must go through jax.config before first device use.
"""

import os
import sys

# Tutorials live one level below the repo root; make the package
# importable without an install.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup(n_devices: int = 8):
    if "--tpu" not in sys.argv:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    return jax
