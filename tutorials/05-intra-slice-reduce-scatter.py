"""Tutorial 05: intra-slice reduce-scatter over ICI.

Parity: reference ``tutorials/05-intra-node-reduce-scatter.py`` (ring
copy-engine / SM push reduce over NVLink, ``reduce_scatter.py:285-744``).
TPU: one Pallas kernel runs the ring — each step sends the accumulating
chunk to the right neighbor while reducing the chunk that just arrived;
after n-1 hops every rank holds the fully-reduced chunk it owns.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.ops import ReduceScatterMethod, reduce_scatter_op
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(tp=min(8, len(jax.devices())))
    n = ctx.axis_size("tp")
    rng = np.random.default_rng(0)
    # One addend per rank; result chunk r = sum over ranks of rows r.
    x = jnp.asarray(rng.standard_normal((n, n * 8, 128)), jnp.float32)

    for method in (ReduceScatterMethod.XLA, ReduceScatterMethod.PALLAS_RING):
        out = reduce_scatter_op(x, "tp", method, ctx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
        )
        print(f"reduce_scatter[{method.name:11s}] n={n}: OK")


if __name__ == "__main__":
    main()
