"""Tutorial 02: intra-slice all-gather over ICI.

Parity: reference ``tutorials/02-intra-node-allgather.py`` (copy-engine
and NVSHMEM producers over NVLink). On TPU the "node" is an ICI slice
and the producers are Pallas kernels driving the per-chip DMA engines:

- PALLAS_FULL_MESH — every rank puts its shard to every peer in one
  round (lowest latency for small messages; the reference's full-mesh
  copy-engine producer).
- PALLAS_RING / PALLAS_BIDIR_RING — neighbor pushes around the ring,
  bidirectional splits the payload both ways (bandwidth-optimal; the
  reference's ring_push_1d / NUMA-aware variants).
- XLA — ``jax.lax.all_gather``, the compiler-scheduled baseline AUTO
  falls back to for large payloads or off-TPU.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.ops import AllGatherMethod, all_gather_op
from triton_distributed_tpu.runtime.mesh import initialize_distributed
from triton_distributed_tpu.runtime.utils import perf_func


def main():
    ctx = initialize_distributed(tp=min(8, len(jax.devices())))
    n = ctx.axis_size("tp")
    x = jnp.arange(n * 16 * 128, dtype=jnp.float32).reshape(n * 16, 128)

    for method in (
        AllGatherMethod.XLA,
        AllGatherMethod.PALLAS_FULL_MESH,
        AllGatherMethod.PALLAS_RING,
        AllGatherMethod.PALLAS_BIDIR_RING,
    ):
        out = all_gather_op(x, "tp", method, ctx)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
        _, ms = perf_func(
            lambda: all_gather_op(x, "tp", method, ctx), iters=5, warmup_iters=2
        )
        print(f"all_gather[{method.name:17s}] n={n}: OK   {ms:7.3f} ms")


if __name__ == "__main__":
    main()
