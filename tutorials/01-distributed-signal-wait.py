"""Tutorial 01: device-side signal / wait / remote DMA.

Parity: reference ``tutorials/01-distributed-notify-wait.py`` — the
producer rank notifies a consumer's barrier and the consumer spin-waits
before loading. The TPU translation of notify/wait (SURVEY.md §2.4):

- ``notify(rank, sem)``      → ``dl.signal(sem, dst=rank, axis=...)`` or,
  fused with data, ``dl.put_signal`` (the DMA's recv semaphore IS the
  arrival signal — data visibility before signal is hardware-guaranteed).
- ``wait(sem, n)`` + token   → ``dl.wait(sem, n)`` / ``dl.wait_recv``
  (no consume_token: Mosaic orders subsequent loads after the wait).

Here every rank passes a value around a ring: put to the right neighbor,
wait on the left arrival, repeat n-1 times — after n-1 hops each rank
holds its left neighbor's ... neighbor's value, i.e. the value from
rank+1 (mod n).
"""

from _common import setup

jax = setup()

import functools

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import comm_pallas_call, next_collective_id
from triton_distributed_tpu.runtime.mesh import initialize_distributed

AXIS = "tp"


def ring_pass_kernel(x_ref, o_ref, buf, send_sem, recv_sem, *, hops: int):
    me = dl.rank(AXIS)
    n = dl.num_ranks(AXIS)
    right = jax.lax.rem(me + 1, n)

    dl.barrier_all(AXIS)  # peers' buffers must exist before any put
    o_ref[...] = x_ref[...]

    def hop(i, _):
        # put my current value into the right neighbor's landing buffer;
        # the neighbor's recv_sem fires when the bytes are visible.
        dma = dl.put_signal(o_ref, buf, right, send_sem, recv_sem, axis=AXIS)
        dl.wait_recv(recv_sem, buf)  # left neighbor's put has landed
        dma.wait_send()              # my source is reusable
        o_ref[...] = buf[...]
        # Round fence: without it, a fast left neighbor's NEXT put could
        # overwrite buf before this rank consumed it (the classic missing
        # credit/ack race the reference provokes with for_correctness
        # sleeps). Production kernels use double buffers instead — see
        # the slot-per-source scheme in ops/overlap/ag_gemm.py.
        dl.barrier_all(AXIS)
        return _

    jax.lax.fori_loop(0, hops, hop, None)


def main():
    ctx = initialize_distributed(tp=min(8, len(jax.devices())))
    n = ctx.axis_size(AXIS)
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)

    kernel = functools.partial(ring_pass_kernel, hops=n - 1)

    def shard_fn(xi):
        return comm_pallas_call(
            kernel,
            jax.ShapeDtypeStruct(xi.shape, xi.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM(xi.shape, xi.dtype),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
            ],
            collective_id=next_collective_id(),
            ctx=ctx,
        )(xi)

    f = ctx.shard_map(shard_fn, in_specs=P(AXIS, None), out_specs=P(AXIS, None))
    out = np.asarray(f(x))
    # After n-1 hops, rank r holds rank (r+1) mod n's shard.
    gold = np.asarray(x).reshape(n, 8, 128)[(np.arange(n) + 1) % n]
    np.testing.assert_allclose(out.reshape(n, 8, 128), gold)
    print(f"ring signal/wait over {n} devices: OK")


if __name__ == "__main__":
    main()
