"""Tutorial 14: the device-initiated EP all-to-all transport.

Parity: reference ``kernels/nvidia/low_latency_all_to_all.py`` — its
flagship EP dispatch pushes each destination's token rows with
``putmem_signal`` and double-buffers by call count. Tutorial 04 showed
the EP *pipeline* (splits-exchange → dispatch → grouped FFN → combine);
this one zooms into the wire and contrasts the two transports:

- ``method="xla"``: the whole max-padded per-destination segments ride
  ``jax.lax.all_to_all`` — simple, but a lossless capacity of
  ``t*k`` rows means the wire carries worst-case padding even when a
  destination gets 3 tokens.
- ``method="pallas"`` (``ops/moe/ep_exchange.py``): ONE Pallas kernel
  pushes only ``ceil(splits[p]/32)`` 32-row blocks per destination —
  wire bytes scale with the REAL splits — grouped into power-of-two
  runs (popcount ≤ log2 DMA descriptors per peer, round 4: the
  descriptor-count lever that cut the single-chip dispatch floor). The [n]-int splits stay on the
  XLA control plane (they compile into the same program); payload,
  fp8 scales, and expert ids pack into one lane-padded uint8 row (the
  reference's flag-in-data LL codec shape, with the byte-counting DMA
  semaphore standing in for the flag word).

The two transports are BIT-IDENTICAL on tokens — the tutorial routes a
skewed batch (most tokens to rank 0's experts, so segments are very
unevenly filled), runs both, and prints the wire-byte accounting that
makes the pallas transport the default on real TPU.
"""

from _common import setup

jax = setup()

import functools

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.moe import ep_moe_ffn
from triton_distributed_tpu.ops.moe.ep_exchange import EP_BLOCK_ROWS
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(ep=min(4, len(jax.devices())))
    n = ctx.axis_size("ep")
    E, k, t_loc, d = 2 * n, 2, 16, 64
    f = 32
    rng = np.random.default_rng(0)

    # Skewed routing: bias two experts (rank 0's) so splits are uneven —
    # the regime where wire-trimming matters.
    x = jnp.asarray(np.abs(rng.standard_normal((n * t_loc, d))) * 0.1,
                    jnp.float32)
    w_router = jnp.asarray(
        rng.standard_normal((d, E)) * 0.1, jnp.float32
    ).at[:, :2].add(5.0)
    gate = jnp.asarray(rng.standard_normal((E, d, f)) * d**-0.5, jnp.float32)
    up = jnp.asarray(rng.standard_normal((E, d, f)) * d**-0.5, jnp.float32)
    down = jnp.asarray(rng.standard_normal((E, f, d)) * f**-0.5, jnp.float32)
    w1 = jnp.concatenate([gate, up], axis=2)

    outs = {}
    for method in ("xla", "pallas"):
        fn = ctx.shard_map(
            functools.partial(
                ep_moe_ffn, k=k, axis="ep", method=method, ctx=ctx,
            ),
            in_specs=(P("ep", None), P(), P("ep", None, None),
                      P("ep", None, None)),
            out_specs=P("ep", None),
        )
        outs[method] = np.asarray(fn(x, w_router, w1, down))

    assert (outs["xla"] == outs["pallas"]).all(), "transports must agree"

    # Wire accounting at this batch's real splits (host-side replay of
    # the routing, for the printout only).
    logits = np.asarray(x) @ np.asarray(w_router)
    top = np.argsort(-logits, axis=-1)[:, :k]
    epr = E // n
    dest = (top // epr).reshape(n, t_loc * k)  # per source rank
    capacity = t_loc * k
    # XLA path: UNPADDED f32 payload + a separate int32 expert-id a2a
    # (ep_a2a.py non-fp8 branch); only the pallas path packs + pads.
    row_xla = d * 4 + 4
    row_pallas = d * 4 + 4 + (-((d * 4) + 4)) % 128
    xla_bytes = n * (n - 1) * capacity * row_xla  # full segments, all pairs
    pallas_bytes = 0
    for src in range(n):
        splits = np.bincount(dest[src], minlength=n)
        for dst in range(n):
            if dst != src:
                blocks = -(-splits[dst] // EP_BLOCK_ROWS)
                pallas_bytes += blocks * EP_BLOCK_ROWS * row_pallas

    print(f"[tut14] transports bit-identical over {n} ranks (skewed splits)")
    print(f"[tut14] wire bytes: xla(max-padded)={xla_bytes:,} "
          f"pallas(block-trimmed)={pallas_bytes:,} "
          f"({xla_bytes / max(pallas_bytes, 1):.1f}x fewer)")
    print("OK")


if __name__ == "__main__":
    main()
