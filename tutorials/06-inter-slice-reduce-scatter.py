"""Tutorial 06: hierarchical reduce-scatter across slices.

Parity: reference ``tutorials/06-inter-node-reduce-scatter.py`` and the
2-level multinode path ``reduce_scatter_multi_node``
(``reduce_scatter.py:828``): intra-node ring first, then the surviving
chunk crosses nodes. TPU: ICI Pallas ring → DCN ``psum_scatter``; chunk
ids come back inner-major (tp-major), see
``ops/collectives/hierarchical.py``.
"""

from _common import setup

jax = setup()

import functools

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.collectives.hierarchical import reduce_scatter_2d
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    nd = len(jax.devices())
    dcn, tp = (2, 4) if nd >= 8 else ((2, nd // 2) if nd >= 2 else (1, 1))
    ctx = initialize_distributed({"dcn": dcn, "tp": tp})
    n = dcn * tp
    rng = np.random.default_rng(0)
    M = n * 8
    x = jnp.asarray(rng.standard_normal((n, M, 128)), jnp.float32)

    def body(xi):
        return reduce_scatter_2d(
            xi[0], inner_axis="tp", outer_axis="dcn", ctx=ctx
        )

    f = ctx.shard_map(
        body,
        in_specs=P(("dcn", "tp"), None, None),
        out_specs=P(("tp", "dcn"), None),  # chunks land inner-major
    )
    out = np.asarray(f(x))
    np.testing.assert_allclose(
        out, np.asarray(x).sum(0), rtol=1e-4, atol=1e-4
    )
    print(f"hierarchical reduce-scatter over {dcn}x{tp}: OK")


if __name__ == "__main__":
    main()
