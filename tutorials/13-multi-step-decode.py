"""Tutorial 13: multi-step decode — N greedy steps in ONE kernel launch.

Why this exists (measured, not guessed): on the v5e a decode step pays
~2 ms of per-launch/per-op dispatch tax no matter how small the model
is — a 1-layer, 2k-vocab model still runs 2.2 ms/step while 28 real
layers add only 1.3 ms of marginal work. Single-step designs (the
reference's CUDA-graph replay, our jitted ``fori_loop``) replay that
tax every token. The multi-step megakernel replays it every N tokens:

- grid = (nsteps, tasks): one task table serves every step, the kernel
  reads the step index from ``program_id(0)``;
- the LM head keeps a running argmax while streaming its vocab tiles
  and feeds the winning token to the next step's EMBED through SMEM
  (scalar DMA indices must live in SMEM — a VMEM→SMEM DMA moves it);
- under TP each rank argmaxes its vocab shard and one-shot-exchanges
  (best value, best global index) pairs over ICI, every rank reducing
  the candidates identically;
- this launch's earlier K/V rows never touch the cache — attention
  reads them straight from the kernel's own knew/vnew outputs (the
  in-launch "band") with masked online-softmax merges;
- the caller appends all N rows with one contiguous
  ``dynamic_update_slice`` per batch row.

Temperature sampling composes via the Gumbel-max trick
(``decode_multi_fn(..., sampled=True)`` + host-drawn noise), and paged
pools via ``page=page_size`` (all N rows landed by one scatter); only
top-p truncation stays on the single-step path.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.megakernel import MegaQwen3
from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(tp=min(4, len(jax.devices())))
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    B, NS = 2, 4
    cache = model.new_cache(B, max_length=64)

    # Populate a few cache rows through the plain jit path.
    step_gold = model.decode_fn("xla")
    for toks in ([3, 5], [7, 11], [13, 17]):
        _, cache = step_gold(model.params, jnp.asarray(toks, jnp.int32), cache)

    mega = MegaQwen3(model)
    s_max = int(cache.k.shape[3])
    tok0 = jnp.asarray([19, 23], jnp.int32)

    # Reference: chain NS single steps, argmax on the host each step.
    step = mega.decode_fn(B, s_max)
    t, c = tok0, jax.tree.map(jnp.copy, cache)
    ref = []
    for _ in range(NS):
        logits, c = step(model.params, t, c)
        t = jnp.argmax(logits, -1).astype(jnp.int32)
        ref.append(np.asarray(t))

    # One launch: NS steps, argmax in-kernel, cache advanced by NS.
    fn = mega.decode_multi_fn(B, s_max, NS)
    toks, last_logits, c2 = fn(
        model.params, tok0, jax.tree.map(jnp.copy, cache)
    )

    print("chained single-step tokens:", np.stack(ref).T.tolist())
    print("one-launch multi tokens:   ", np.asarray(toks).T.tolist())
    assert (np.asarray(toks) == np.stack(ref)).all()
    assert int(c2.kv_len[0]) == int(c.kv_len[0])
    print("token-exact across", NS, "steps; kv_len", int(c2.kv_len[0]))

    # The Engine takes this path automatically for greedy mega serving:
    from triton_distributed_tpu.models.engine import Engine

    eng = Engine(model, temperature=0.0, mode="mega")
    out = eng.serve(np.arange(8, dtype=np.int32)[None].repeat(B, 0),
                    gen_len=12, max_length=64)
    print("engine mega serve:", out.shape, "ok")


if __name__ == "__main__":
    main()
