"""Tutorial 10: the megakernel — a whole decode step as ONE Pallas kernel.

Parity: reference ``mega_triton_kernel`` (``docs/mega_triton_kernel.md``)
— the top rung of its decode ladder (torch → cudagraph → triton_dist_AR
→ megakernel, 3.33 ms for Qwen3-8B TP8 on H800). There, a persistent
kernel owns every SM; tasks are tile-granular with a shared-memory
scoreboard.

TPU redesign (see megakernel/kernels.py): the Pallas grid is sequential
on the TensorCore, so the schedule IS the scoreboard for intra-chip
deps; tasks are op-granular with double-buffered weight streaming
inside, activations never leave VMEM, and the only cross-chip task
(ALLREDUCE) synchronizes via DMA semaphores. The task graph is built by
a ModelBuilder and dispatched by a scalar-prefetched task table —
same shape as the reference's generated if/elif megakernel source.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.megakernel import MegaQwen3, SchedulePolicy, TaskType
from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(tp=min(4, len(jax.devices())))
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    B = 2
    cache = model.new_cache(B, max_length=64)

    # A few golden (plain jit) steps to populate the cache.
    step_gold = model.decode_fn("xla")
    for toks in ([3, 5], [7, 11], [13, 17]):
        _, cache = step_gold(model.params, jnp.asarray(toks, jnp.int32), cache)

    mega = MegaQwen3(model, policy=SchedulePolicy.ZIG_ZAG)
    compiled, _, _ = mega.build(B, 64)
    counts = {}
    for t in compiled.order:
        counts[t.task_type.name] = counts.get(t.task_type.name, 0) + 1
    print(f"task graph: {compiled.num_tasks} tasks = {counts}")

    tok = jnp.asarray([19, 23], jnp.int32)
    logits_gold, _ = step_gold(model.params, tok, jax.tree.map(jnp.copy, cache))
    logits_mega, _ = mega.decode_step(tok, cache)
    np.testing.assert_allclose(
        np.asarray(logits_mega), np.asarray(logits_gold), rtol=2e-3, atol=2e-3
    )
    assert TaskType.ALLREDUCE.name in counts
    print("megakernel decode step matches the jitted ladder rung: OK")


if __name__ == "__main__":
    main()
