"""Tutorial 08: GEMM + ReduceScatter overlap — the TP output projection.

Parity: reference ``tutorials/08-overlapping-gemm-reduce-scatter.py`` —
producer GEMM notifies per-tile barriers as tiles land; scatter +
ring-reduce consumes them (``gemm_reduce_scatter.py``,
``reduce_scatter.py``).

TPU redesign: one Pallas kernel computes this rank's contribution to
chunk c = (me+1+s) mod n at step s and immediately pushes it into the
owner's inbound slot over ICI — the DMA rides under the next chunk's
GEMM. The last step reduces the n-1 landed contributions with the local
chunk. Mirror image of tutorial 07.
"""

from _common import setup

jax = setup()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.ops import gemm_rs_op
from triton_distributed_tpu.runtime.mesh import initialize_distributed


def main():
    ctx = initialize_distributed(tp=min(4, len(jax.devices())))
    n = ctx.axis_size("tp")
    rng = np.random.default_rng(0)
    m, k_loc, n_cols = n * 16, 64, 128
    # a column-sharded [M, K]; b row-sharded [K, N] — C = sum of partials.
    a = jnp.asarray(rng.standard_normal((m, n * k_loc)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((n * k_loc, n_cols)), jnp.float32)

    out = gemm_rs_op(a, b, "tp", ctx=ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=2e-4, atol=2e-4
    )
    print(f"overlapped GEMM+RS over {n} ranks: OK")


if __name__ == "__main__":
    main()
