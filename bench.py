"""Headline benchmark — prints ONE JSON line.

Run on real TPU hardware by the driver. Reports the flagship end-to-end
number (currently: fused TP-MLP-shape GEMM throughput on one chip; will
become the Qwen3 TP decode step as the stack widens — see BASELINE.md).

``vs_baseline`` is measured TFLOP/s divided by the chip's bf16 peak — the
same "fraction of roofline" framing the reference uses for its overlap
efficiency charts (README.md:190-209).
"""

import json
import sys

import jax
import jax.numpy as jnp


# bf16 matmul peak TFLOP/s per chip (v5e ≈ 197, v5p ≈ 459, v4 ≈ 275).
_PEAK_TFLOPS = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}


def chip_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    return 197.0


def main() -> None:
    import functools
    import time

    import numpy as np

    # Qwen3-8B-ish TP GEMM shape. Timing notes: through the axon relay,
    # ``block_until_ready`` resolves early and identical executions are
    # memoized, so we (a) chain iterations with a data dependency inside one
    # jit and (b) fence by fetching a scalar to host.
    M, K, N = 4096, 4096, 4096
    ITERS = 64
    key = jax.random.key(0)
    a = (jax.random.normal(key, (M, K), jnp.float32) * 0.01).astype(jnp.bfloat16)
    b = (jax.random.normal(key, (K, N), jnp.float32) * 0.01).astype(jnp.bfloat16)

    @functools.partial(jax.jit, static_argnums=2)
    def chain(a, b, iters):
        def body(i, a):
            return jnp.dot(a, b, preferred_element_type=jnp.bfloat16)
        return jax.lax.fori_loop(0, iters, body, a)[0, 0]

    np.asarray(chain(a, b, ITERS))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(chain(a, b, ITERS))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    ms = best * 1e3
    tflops = 2 * M * K * N / (ms * 1e-3) / 1e12
    peak = chip_peak_tflops()
    print(
        json.dumps(
            {
                "metric": "tp_mlp_gemm_bf16_tflops",
                "value": round(tflops, 2),
                "unit": "TFLOP/s",
                "vs_baseline": round(tflops / peak, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
