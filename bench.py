"""Headline benchmark — prints ONE JSON line.

Flagship number: Qwen3-0.6B bf16 single-chip decode step latency
(bs=1, 512-token context), the chip-local analog of the quantity the
reference headlines for its TP8 decode ladder
(``docs/mega_triton_kernel.md:27-37`` — torch / cudagraph /
triton_dist_AR / megakernel ms-per-step). Multi-chip TP isn't measurable
on this one-chip runner, so:

``vs_baseline`` = achieved HBM bandwidth fraction of the chip's peak —
decode is bandwidth-bound (weights + KV streamed once per token), the
decode analog of the reference's "fraction of comm hidden" roofline
framing (README.md:190-209).

Timing notes (axon relay): ``block_until_ready`` resolves early and
identical executions are memoized, so all decode steps are chained
inside ONE jit via ``lax.fori_loop`` (data-dependent greedy feedback)
and fenced by fetching the final token to host.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# HBM peak GB/s per chip.
_PEAK_GBS = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}


def chip_peak_gbs() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, val in _PEAK_GBS.items():
        if key in kind:
            return val
    return 819.0


def main() -> None:
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("Qwen/Qwen3-0.6B", ctx=ctx, max_length=1024)
    cfg = model.cfg

    PROMPT, STEPS = 512, 32
    cache = model.new_cache(1)
    tokens = jnp.asarray(np.arange(PROMPT) % cfg.vocab_size, jnp.int32)
    logits, cache = model.prefill(tokens, cache, "xla")
    tok = jnp.argmax(logits)[None].astype(jnp.int32)

    step = model.decode_fn("xla")

    def decode_n(params, tok, cache, n):
        def body(_, carry):
            tok, cache = carry
            logits, cache = step(params, tok, cache)
            return jnp.argmax(logits, -1).astype(jnp.int32), cache

        return jax.lax.fori_loop(0, n, body, (tok, cache))

    run = jax.jit(decode_n, static_argnums=3)
    out_tok, _ = run(model.params, tok, cache, STEPS)
    np.asarray(out_tok)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out_tok, _ = run(model.params, tok, cache, STEPS)
        np.asarray(out_tok)
        best = min(best, (time.perf_counter() - t0) / STEPS)
    ms = best * 1e3

    # Bandwidth roofline: weights read once per step + KV context read.
    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(model.params)
    )
    kv_bytes = (
        2 * cfg.num_layers * cfg.num_kv_heads * PROMPT * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    gbs = (param_bytes + kv_bytes) / (ms * 1e-3) / 1e9
    print(
        json.dumps(
            {
                "metric": "qwen3_0.6b_decode_ms_per_step",
                "value": round(ms, 3),
                "unit": "ms",
                "vs_baseline": round(gbs / chip_peak_gbs(), 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
