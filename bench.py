"""Headline benchmark — prints ONE JSON line.

Flagship number: Qwen3-0.6B bf16 single-chip decode ladder (bs=1,
512-token context), the chip-local analog of the reference's TP8 decode
ladder (``docs/mega_triton_kernel.md:27-37`` — torch / cudagraph /
triton_dist_AR / megakernel ms-per-step). Rungs here: ``jit`` (XLA
decode step), ``pallas`` (framework Pallas kernels in the decode path),
``mega`` (whole step as one Pallas kernel, only where it compiles).
``value`` is the best successful rung; the full ladder rides along in
``ladder``.

``vs_baseline`` = achieved HBM bandwidth fraction of the chip's peak —
decode is bandwidth-bound (weights + KV streamed once per token), the
decode analog of the reference's "fraction of comm hidden" roofline
framing (README.md:190-209).

Robustness (round-1 lesson): the experimental 'axon' TPU plugin can be
slow or unavailable; ``jax.devices()`` in-process either hangs or
raises. The backend is therefore probed in a SUBPROCESS with a timeout
and retries; on failure the bench falls back to the CPU platform so a
parseable number is always emitted (marked ``"platform": "cpu"``).

Timing notes (axon relay): ``block_until_ready`` resolves early and
identical executions are memoized, so decode steps are chained inside
ONE jit via ``lax.fori_loop`` (data-dependent greedy feedback) and
fenced by fetching the final token to host.
"""

import json
import os
import subprocess
import sys
import time

# HBM peak GB/s per chip.
_PEAK_GBS = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}

_PROBE_ATTEMPTS = 2
_PROBE_TIMEOUT_S = 270
_PROBE_SLEEP_S = 15


def _probe_tpu() -> bool:
    """Check (in a subprocess, with timeout + retry) that the TPU backend
    actually comes up. Keeps a hung plugin from wedging the bench."""
    code = "import jax; d = jax.devices(); assert d[0].platform != 'cpu'"
    for attempt in range(_PROBE_ATTEMPTS):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=_PROBE_TIMEOUT_S,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
            sys.stderr.write(
                f"[bench] TPU probe attempt {attempt + 1} failed rc="
                f"{r.returncode}: {r.stderr.decode()[-500:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[bench] TPU probe attempt {attempt + 1} timed out after "
                f"{_PROBE_TIMEOUT_S}s\n"
            )
        if attempt + 1 < _PROBE_ATTEMPTS:
            time.sleep(_PROBE_SLEEP_S)
    return False


def chip_peak_gbs(jax) -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, val in _PEAK_GBS.items():
        if key in kind:
            return val
    return 819.0


def main() -> None:
    on_tpu = _probe_tpu()
    if not on_tpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        )
    import jax

    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    model_name = "Qwen/Qwen3-0.6B" if on_tpu else "tiny"
    model = AutoLLM.from_pretrained(model_name, ctx=ctx, max_length=1024)
    cfg = model.cfg

    PROMPT = 512
    STEPS = 32 if on_tpu else 8
    cache0 = model.new_cache(1)
    tokens = jnp.asarray(np.arange(PROMPT) % cfg.vocab_size, jnp.int32)
    logits, cache0 = model.prefill(tokens, cache0, "xla")
    tok0 = jnp.argmax(logits)[None].astype(jnp.int32)

    def make_runner(mode):
        step = model.decode_fn(mode)

        def decode_n(params, tok, cache, n):
            def body(_, carry):
                tok, cache = carry
                logits, cache = step(params, tok, cache)
                return jnp.argmax(logits, -1).astype(jnp.int32), cache

            return jax.lax.fori_loop(0, n, body, (tok, cache))

        return jax.jit(decode_n, static_argnums=3)

    from triton_distributed_tpu.runtime.utils import median_time

    def time_rung(run_once) -> float:
        return median_time(run_once) / STEPS * 1e3

    ladder: dict[str, float] = {}
    errors: dict[str, str] = {}
    for name, mode in (("jit", "xla"), ("pallas", "pallas")):
        try:
            run = make_runner(mode)

            def once(run=run):
                out_tok, _ = run(model.params, tok0, cache0, STEPS)
                np.asarray(out_tok)

            ladder[name] = time_rung(once)
        except Exception as e:  # keep the ladder going rung by rung
            errors[name] = f"{type(e).__name__}: {e}"[:300]

    # Megakernel rung: whole decode step as ONE Pallas kernel, with the
    # same fori_loop chaining as the other rungs (greedy feedback keeps
    # the steps data-dependent; one jit dispatch for all STEPS). Skipped
    # off-TPU (interpret mode is semantics-only, not a timing rung).
    if on_tpu:
        try:
            from triton_distributed_tpu.megakernel import MegaQwen3

            mega = MegaQwen3(model)
            mstep = mega.decode_fn(1, int(cache0.k.shape[3]))

            def mega_decode_n(params, tok, cache, n):
                def body(_, carry):
                    tok, cache = carry
                    logits, cache = mstep(params, tok, cache)
                    return jnp.argmax(logits, -1).astype(jnp.int32), cache

                return jax.lax.fori_loop(0, n, body, (tok, cache))

            mrun = jax.jit(mega_decode_n, static_argnums=3)

            def mega_once():
                out_tok, _ = mrun(model.params, tok0, cache0, STEPS)
                np.asarray(out_tok)

            ladder["mega"] = time_rung(mega_once)
        except Exception as e:
            errors["mega"] = f"{type(e).__name__}: {e}"[:300]

        # Multi-step megakernel: NS greedy steps per kernel launch
        # (in-kernel argmax + SMEM token feedback) — amortizes the
        # platform's per-launch/per-op dispatch tax, the dominant cost
        # of single-step decode on this chip.
        try:
            from triton_distributed_tpu.megakernel import MegaQwen3

            NS = 8
            mmulti = MegaQwen3(model).decode_multi_fn(
                1, int(cache0.k.shape[3]), NS
            )

            def mega_multi_n(params, tok, cache, nl):
                def body(_, carry):
                    tok, cache = carry
                    toks, _lg, cache = mmulti(params, tok, cache)
                    return toks[NS - 1], cache

                return jax.lax.fori_loop(0, nl, body, (tok, cache))

            mmrun = jax.jit(mega_multi_n, static_argnums=3)

            def mega_multi_once():
                out_tok, _ = mmrun(model.params, tok0, cache0, STEPS // NS)
                np.asarray(out_tok)

            # Cross-check before timing: the single- and multi-step
            # kernels run identical math, so their greedy chains must
            # agree token-for-token — a mismatch means the multi kernel
            # mis-executes on this chip, and its timing would be
            # meaningless.
            if "mega" in ladder:
                def single_seq(params, tok, cache, n):
                    def body(i, carry):
                        tok, cache, seq = carry
                        logits, cache = mstep(params, tok, cache)
                        tok = jnp.argmax(logits, -1).astype(jnp.int32)
                        return tok, cache, seq.at[i].set(tok[0])

                    seq0 = jnp.zeros((n,), jnp.int32)
                    return jax.lax.fori_loop(
                        0, n, body, (tok, cache, seq0)
                    )[2]

                def multi_seq(params, tok, cache, nl):
                    def body(i, carry):
                        tok, cache, seq = carry
                        toks, _lg, cache = mmulti(params, tok, cache)
                        seq = jax.lax.dynamic_update_slice(
                            seq, toks[:, 0], (i * NS,)
                        )
                        return toks[NS - 1], cache, seq

                    seq0 = jnp.zeros((nl * NS,), jnp.int32)
                    return jax.lax.fori_loop(
                        0, nl, body, (tok, cache, seq0)
                    )[2]

                s_seq = np.asarray(
                    jax.jit(single_seq, static_argnums=3)(
                        model.params, tok0, cache0, STEPS
                    )
                )
                m_seq = np.asarray(
                    jax.jit(multi_seq, static_argnums=3)(
                        model.params, tok0, cache0, STEPS // NS
                    )
                )
                if (s_seq != m_seq).any():
                    raise RuntimeError(
                        "multi-step tokens diverge from single-step: "
                        f"{s_seq.tolist()} vs {m_seq.tolist()}"
                    )

            ladder["mega_multi"] = time_rung(mega_multi_once)
        except Exception as e:
            errors["mega_multi"] = f"{type(e).__name__}: {e}"[:300]

    if not ladder:
        print(json.dumps({
            "metric": "qwen3_decode_ms_per_step",
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "platform": jax.default_backend(),
            "errors": errors,
        }))
        raise SystemExit(1)

    best_name = min(ladder, key=ladder.get)
    ms = ladder[best_name]

    # Bandwidth roofline: weights read once per step + KV context read.
    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(model.params)
    )
    kv_bytes = (
        2 * cfg.num_layers * cfg.num_kv_heads * PROMPT * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    gbs = (param_bytes + kv_bytes) / (ms * 1e-3) / 1e9
    out = {
        "metric": f"qwen3_{'0.6b' if on_tpu else 'tiny'}_decode_ms_per_step",
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(gbs / chip_peak_gbs(jax), 4),
        "platform": jax.default_backend(),
        "best_rung": best_name,
        "ladder": {k: round(v, 3) for k, v in ladder.items()},
    }
    if errors:
        out["errors"] = errors
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
