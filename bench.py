"""Headline benchmark — prints a JSON result line (the LAST line wins).

On the happy path exactly one JSON line is printed. On the fallback
path up to TWO are: a minimal ``value: null`` line the moment TPU work
is abandoned, then a refined line if the CPU stub completes — each
line supersedes the previous, so consumers must parse the LAST
parseable JSON line of stdout (the driver's tail-parse does).

Flagship number: Qwen3-0.6B bf16 single-chip decode ladder (bs=1,
512-token context), the chip-local analog of the reference's TP8 decode
ladder (``docs/mega_triton_kernel.md:27-37`` — torch / cudagraph /
triton_dist_AR / megakernel ms-per-step). Rungs here: ``jit`` (XLA
decode step), ``pallas`` (framework Pallas kernels in the decode path),
``mega`` (whole step as one Pallas kernel, only where it compiles).
``value`` is the best successful rung; the full ladder rides along in
``ladder``.

``vs_baseline`` = achieved HBM bandwidth fraction of the chip's peak —
decode is bandwidth-bound (weights + KV streamed once per token), the
decode analog of the reference's "fraction of comm hidden" roofline
framing (README.md:190-209).

Robustness (round-1 lesson; round-2 lesson: a relay OUTAGE mid-run
hangs forever rather than raising, and one outage zeroed the round's
perf evidence — VERDICT r2 #1; round-3 lesson: giving up on the probe
after ~14 min and then burning ~30 min on a CPU ladder loses to a relay
whose windows are ~30 min every few hours — VERDICT r3 weak #1). The
backend is probed in a SUBPROCESS, in a RETRY LOOP that runs for the
whole global budget minus a small CPU reserve; the ladder itself then
runs in a WORKER subprocess that appends one JSON line per completed
rung to a progress file, while the parent watchdogs progress, kills a
hung worker, re-probes the relay (again: until the budget ends, not a
fixed count), and relaunches skipping completed rungs — so a mid-run
outage costs the remaining rungs at worst, never the whole ladder.

Round-4 lesson (VERDICT r4 weak #1 — the all-down run emitted NOTHING
because the probe loop overran its deadline and the in-process CPU stub
then overran the driver's kill): the output contract is now
EMIT-FIRST-REFINE-LATER. (a) a probe is only STARTED if it can finish
before the reserve boundary (pre-probe deadline check); (b) the global
deadline defaults BELOW the driver's 2700 s hard kill; (c) the moment
the bench falls back from TPU, a minimal valid JSON line — ``platform:
"cpu"``, ``value: null``, plus a ``last_known_tpu`` field carrying the
newest on-chip ladder from ``perf/ONCHIP_*.jsonl``, labeled cached — is
printed IMMEDIATELY, and only then does a subprocess-bounded CPU stub
(jit rung, 4 steps) attempt to refine it with a fresh measurement; if
the stub lands, a second (refined) line is printed and, being last,
supersedes the minimal one. A wedged stub can no longer take the
artifact down with it.

Timing notes (axon relay): ``block_until_ready`` resolves early and
identical executions are memoized, so decode steps are chained inside
ONE jit via ``lax.fori_loop`` (data-dependent greedy feedback) and
fenced by fetching the final token to host.
"""

import json
import os
import subprocess
import sys
import time

# HBM peak GB/s per chip.
_PEAK_GBS = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


_PROBE_TIMEOUT_S = _env_int("TDT_BENCH_PROBE_TIMEOUT_S", 180)
_PROBE_SLEEP_S = _env_int("TDT_BENCH_PROBE_SLEEP_S", 20)
# Worker import + model build + prefill compile. The watchdog timer
# resets on every progress line, so this bounds each init PHASE (ctx /
# params / prefill — the worker emits between them), not their sum.
_INIT_TIMEOUT_S = _env_int("TDT_BENCH_INIT_TIMEOUT_S", 900)
_RUNG_TIMEOUT_S = _env_int("TDT_BENCH_RUNG_TIMEOUT_S", 600)
# mega_multi's start→first-progress window holds ~4 fresh jit compiles
# plus two full chained decode executions (the token cross-check) — a
# healthy rung needs far more headroom than the others.
_MULTI_RUNG_TIMEOUT_S = _env_int("TDT_BENCH_MULTI_RUNG_TIMEOUT_S", 1800)
_WORKER_ATTEMPTS = _env_int("TDT_BENCH_WORKER_ATTEMPTS", 8)
# Default BELOW the driver's 2700 s hard kill: the bench must always
# finish (and print) first. r4's 2700-with-zero-margin died mid-stub.
_GLOBAL_DEADLINE_S = _env_int("TDT_BENCH_DEADLINE_S", 2580)
# Wall-clock reserved at the tail for the CPU fallback stub (jit rung
# only, 4 steps) so a parseable number is ALWAYS emitted. Everything
# before this reserve belongs to TPU probing — relay windows are ~30 min
# every few hours, so giving up early and burning the budget on a CPU
# ladder is exactly backwards (VERDICT r3 weak #1).
_CPU_RESERVE_S = _env_int("TDT_BENCH_CPU_RESERVE_S", 480)


def _compile_cache_env(env: dict) -> dict:
    """Point a child process at the shared persistent compilation cache
    (``perf/.jax_cache``) unless the caller already chose one. Shared
    by the bench worker spawn and ``perf/onchip_session.py`` (one
    policy, one place): retried/relaunched steps re-pay tracing only,
    not XLA compilation — the piece that burned round-3 windows
    (VERDICT r4 next #8). Harmless if the backend ignores it."""
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "perf", ".jax_cache"
    ))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    return env


def _probe_tpu_once() -> bool:
    """One probe (in a subprocess, with timeout) that the TPU backend
    comes up AND EXECUTES. Catches the observed half-up relay state
    where device enumeration answers but any compute hangs (a doomed
    worker would otherwise burn the init-timeout budget per attempt).

    ``TDT_BENCH_FORCE_PROBE=ok|fail`` (tests only) short-circuits the
    probe: ``ok`` on a TPU-less host drives the full worker
    orchestration — watchdog kill, relaunch, +lite fallback,
    relay-answered labeling — against a worker that hangs exactly like
    a wedged relay (the machinery otherwise only ever runs against a
    live chip, where it has failed in novel ways three rounds
    straight)."""
    force = os.environ.get("TDT_BENCH_FORCE_PROBE")
    if force:
        if force not in ("ok", "fail"):
            # Fail closed: a leaked/typoed override must not silently
            # launder a healthy chip into a "relay down" round.
            raise ValueError(
                f"TDT_BENCH_FORCE_PROBE={force!r} (want ok|fail)"
            )
        sys.stderr.write(f"[bench] TEST OVERRIDE: probe forced {force}\n")
        return force == "ok"
    code = (
        "import jax, numpy as np; d = jax.devices(); "
        "assert d[0].platform != 'cpu'; "
        "import jax.numpy as jnp; x = jnp.ones((8, 128)) + 1; "
        "assert float(np.asarray(x).sum()) == 2048.0"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=_PROBE_TIMEOUT_S,
            capture_output=True,
        )
        if r.returncode == 0:
            return True
        sys.stderr.write(
            f"[bench] TPU probe failed rc={r.returncode}: "
            f"{r.stderr.decode()[-500:]}\n"
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(
            f"[bench] TPU probe timed out after {_PROBE_TIMEOUT_S}s\n"
        )
    return False


def _probe_tpu_until(deadline: float) -> bool:
    """Probe-retry continuously until the relay answers or ``deadline``
    (absolute ``time.time()``) passes. An outage hangs probes rather
    than failing them, so each cycle costs ~_PROBE_TIMEOUT_S; the loop
    keeps cycling because a window can open at ANY point in the budget
    — the whole strategy is to still be probing when it does.

    The deadline is checked BEFORE each probe: a probe that cannot
    complete before the reserve boundary is never started, so the
    reserve is a true reserve (r4's post-probe check overran it by up
    to a full probe+sleep cycle and starved the CPU stub — VERDICT r4
    weak #1a). With a deadline already in the past this returns False
    without probing at all: the caller prints the minimal line first,
    so nothing is lost, and the driver's clock is never gambled with.
    """
    attempt = 0
    while deadline - time.time() > _PROBE_TIMEOUT_S:
        attempt += 1
        if _probe_tpu_once():
            return True
        remaining = deadline - time.time()
        sys.stderr.write(
            f"[bench] relay down (probe {attempt}); "
            f"{max(remaining, 0) / 60:.0f} min of probe budget left\n"
        )
        if remaining <= _PROBE_SLEEP_S + _PROBE_TIMEOUT_S:
            break
        time.sleep(_PROBE_SLEEP_S)
    if attempt == 0:
        # The budget never fit even ONE probe — a healthy chip would be
        # skipped with no trace. Say so, or a misconfigured
        # TDT_BENCH_DEADLINE_S is indistinguishable from an outage
        # (ADVICE r5).
        sys.stderr.write(
            f"[bench] probe budget exhausted before any probe ran "
            f"({deadline - time.time():.0f}s left < one probe of "
            f"{_PROBE_TIMEOUT_S}s); check TDT_BENCH_DEADLINE_S\n"
        )
    return False


def chip_peak_gbs(jax) -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, val in _PEAK_GBS.items():
        if key in kind:
            return val
    return 819.0


def _emit(fh, obj) -> None:
    fh.write(json.dumps(obj) + "\n")
    fh.flush()
    os.fsync(fh.fileno())


def _tuned_mega_config(device_kind: str, model_name: str):
    """Megakernel config for the mega rungs: ``TDT_BENCH_MEGA_CFG``
    ("tile_n:tile_k:nbuf") wins, else ``perf/MEGA_TUNED.json`` (written
    by ``perf/mega_tile_sweep.py`` for the best token-exact config on
    this chip+model, validated against both before use), else None
    (library defaults). Lets an on-chip sweep improve the driver's
    end-of-round ladder without a code edit.

    Returns ``(config_or_None, note_str)`` — the note goes in the
    progress file so a dropped override/tuning is visible. A malformed
    EXPLICIT env override raises: silently timing defaults would
    invalidate the operator's A/B without a trace.
    """
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    parse = MegaConfig.from_spec

    env = os.environ.get("TDT_BENCH_MEGA_CFG")
    if env:
        try:
            return parse(env), f"env TDT_BENCH_MEGA_CFG={env}"
        except Exception as e:
            raise ValueError(
                f"malformed TDT_BENCH_MEGA_CFG={env!r} "
                "(want tn:tk:nbuf[:fuse_norms[:cross_prefetch]])"
            ) from e
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "perf", "MEGA_TUNED.json")
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None, "defaults (no tuning file)"
    # Tuning is per chip and per model — ignore a file from another.
    if rec.get("device") != device_kind or rec.get("model") != model_name:
        return None, (
            f"defaults (tuning file is for {rec.get('device')}/"
            f"{rec.get('model')}, this run is {device_kind}/{model_name})"
        )
    try:
        return parse(rec["config"]), f"perf/MEGA_TUNED.json {rec['config']}"
    except Exception:
        return None, "defaults (malformed tuning file ignored)"


def run_ladder(
    progress_fh,
    on_tpu: bool,
    skip: frozenset[str],
    model_name: str | None = None,
) -> None:
    """Run the decode ladder, emitting one JSON line per event (worker
    body; also called in-process for the CPU fallback).

    ``model_name`` may carry a ``+lite`` suffix: same geometry but
    num_layers=8 / vocab 32768 — the relay-gentle fallback used when
    full-model init wedged the relay (a reduced-model TPU ladder beats
    a CPU fallback as round evidence)."""
    if on_tpu and os.environ.get("TDT_BENCH_FORCE_WORKER_HANG"):
        # Tests only: simulate a wedged relay DETERMINISTICALLY (the
        # real axon hang depends on backend state — a live relay or a
        # CPU fallback would otherwise make the orchestration test
        # flaky in both directions).
        _emit(progress_fh, {"start": "init"})
        time.sleep(10_000)
    if not on_tpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        )
    import jax

    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    _emit(progress_fh, {"start": "init"})
    ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
    _emit(progress_fh, {"init_phase": "ctx"})
    model_name = model_name or ("Qwen/Qwen3-0.6B" if on_tpu else "tiny")
    overrides = {}
    if model_name.endswith("+lite"):
        overrides = {"num_layers": 8, "vocab_size": 32768}
    # init is one jitted device-side program (no bulk weight transfer
    # over the relay — see Qwen3._set_params_jit).
    model = AutoLLM.from_pretrained(
        model_name.removesuffix("+lite"), ctx=ctx, max_length=1024,
        **overrides,
    )
    jax.block_until_ready(model.params)
    _emit(progress_fh, {"init_phase": "params"})
    cfg = model.cfg

    PROMPT = 512
    STEPS = 32 if on_tpu else 4
    cache0 = model.new_cache(1)
    tokens = jnp.asarray(np.arange(PROMPT) % cfg.vocab_size, jnp.int32)
    logits, cache0 = model.prefill(tokens, cache0, "xla")
    _emit(progress_fh, {"init_phase": "prefill"})
    tok0 = jnp.argmax(logits)[None].astype(jnp.int32)

    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(model.params)
    )
    kv_bytes = (
        2 * cfg.num_layers * cfg.num_kv_heads * PROMPT * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    _emit(progress_fh, {
        "init": {
            "platform": jax.default_backend(),
            "peak_gbs": chip_peak_gbs(jax),
            "param_bytes": int(param_bytes),
            "kv_bytes": int(kv_bytes),
            "model": model_name,
            "steps": STEPS,
        }
    })

    # Shared greedy-chain builders for the mega rungs (one definition
    # serves the bf16 and q8 cross-checks/timers).
    def _single_chain(step_fn):
        def single_seq(params, tok, cache, n):
            def body(i, carry):
                tok, cache, seq = carry
                logits, cache = step_fn(params, tok, cache)
                tok = jnp.argmax(logits, -1).astype(jnp.int32)
                return tok, cache, seq.at[i].set(tok[0])

            seq0 = jnp.zeros((n,), jnp.int32)
            return jax.lax.fori_loop(0, n, body, (tok, cache, seq0))[2]

        return single_seq

    def _multi_chain(multi_fn, ns, record):
        def multi_seq(params, tok, cache, nl):
            def body(i, carry):
                tok, cache, seq = carry
                toks, _lg, cache = multi_fn(params, tok, cache)
                if record:
                    seq = jax.lax.dynamic_update_slice(
                        seq, toks[:, 0], (i * ns,)
                    )
                return toks[ns - 1], cache, seq

            seq0 = jnp.zeros((nl * ns if record else 1,), jnp.int32)
            out = jax.lax.fori_loop(0, nl, body, (tok, cache, seq0))
            return out[2] if record else out[0]

        return multi_seq

    def _mega_cross_check(name, step_fn, multi_fn, ns, params):
        """Single- vs multi-step greedy chains over the same kernel
        math must agree token-for-token before the timing counts."""
        s_seq = np.asarray(
            jax.jit(_single_chain(step_fn), static_argnums=3)(
                params, tok0, cache0, STEPS
            )
        )
        m_seq = np.asarray(
            jax.jit(_multi_chain(multi_fn, ns, record=True),
                    static_argnums=3)(params, tok0, cache0, STEPS // ns)
        )
        if (s_seq != m_seq).any():
            raise RuntimeError(
                f"{name}: multi-step tokens diverge from single-step: "
                f"{s_seq.tolist()} vs {m_seq.tolist()}"
            )
        _emit(progress_fh, {"cross_check": name, "ok": True})

    def make_runner(mode):
        step = model.decode_fn(mode)

        def decode_n(params, tok, cache, n):
            def body(_, carry):
                tok, cache = carry
                logits, cache = step(params, tok, cache)
                return jnp.argmax(logits, -1).astype(jnp.int32), cache

            return jax.lax.fori_loop(0, n, body, (tok, cache))

        return jax.jit(decode_n, static_argnums=3)

    from triton_distributed_tpu.runtime.utils import median_time

    def time_rung(run_once) -> float:
        return median_time(run_once) / STEPS * 1e3

    for name, mode in (("jit", "xla"), ("pallas", "pallas")):
        if name in skip:
            continue
        _emit(progress_fh, {"start": name, "budget_s": _RUNG_TIMEOUT_S})
        try:
            run = make_runner(mode)

            def once(run=run):
                out_tok, _ = run(model.params, tok0, cache0, STEPS)
                np.asarray(out_tok)

            _emit(progress_fh, {"rung": name, "ms": time_rung(once)})
        except Exception as e:  # keep the ladder going rung by rung
            _emit(progress_fh, {
                "rung": name, "error": f"{type(e).__name__}: {e}"[:300],
            })

    # Megakernel rung: whole decode step as ONE Pallas kernel, with the
    # same fori_loop chaining as the other rungs (greedy feedback keeps
    # the steps data-dependent; one jit dispatch for all STEPS). Skipped
    # off-TPU (interpret mode is semantics-only, not a timing rung).
    mega_ok = False
    mega_cfg = None
    if on_tpu:
        # Resolve ONCE: both rungs and the cross-check's single-step
        # kernel must build with the same config even if the file
        # changes mid-run.
        mega_cfg, cfg_note = _tuned_mega_config(
            jax.devices()[0].device_kind, model_name
        )
        _emit(progress_fh, {"mega_config": cfg_note})
    if on_tpu and "mega" not in skip:
        _emit(progress_fh, {"start": "mega", "budget_s": _RUNG_TIMEOUT_S})
        try:
            from triton_distributed_tpu.megakernel import MegaQwen3

            mega = MegaQwen3(model, cfg=mega_cfg)
            mstep = mega.decode_fn(1, int(cache0.k.shape[3]))

            def mega_decode_n(params, tok, cache, n):
                def body(_, carry):
                    tok, cache = carry
                    logits, cache = mstep(params, tok, cache)
                    return jnp.argmax(logits, -1).astype(jnp.int32), cache

                return jax.lax.fori_loop(0, n, body, (tok, cache))

            mrun = jax.jit(mega_decode_n, static_argnums=3)

            def mega_once():
                out_tok, _ = mrun(model.params, tok0, cache0, STEPS)
                np.asarray(out_tok)

            _emit(progress_fh, {"rung": "mega", "ms": time_rung(mega_once)})
            mega_ok = True
        except Exception as e:
            _emit(progress_fh, {
                "rung": "mega", "error": f"{type(e).__name__}: {e}"[:300],
            })

    if on_tpu and "mega_multi" not in skip:
        # Multi-step megakernel: NS greedy steps per kernel launch
        # (in-kernel argmax + SMEM token feedback) — amortizes the
        # platform's per-launch/per-op dispatch tax, the dominant cost
        # of single-step decode on this chip.
        # Budget covers ~4 fresh jit compiles plus two full chained
        # decode executions (the token cross-check) before the first
        # progress write.
        _emit(progress_fh, {
            "start": "mega_multi", "budget_s": _MULTI_RUNG_TIMEOUT_S,
        })
        try:
            from triton_distributed_tpu.megakernel import MegaQwen3

            # Launch width: the ~2 ms dispatch tax amortizes as tax/NS,
            # so NS=16 halves the per-step overhead vs 8 if capacity
            # (max_length - prompt) allows; STEPS must stay divisible.
            NS = _env_int("TDT_BENCH_NS", 8)
            if NS <= 0 or STEPS % NS:
                NS = 8
            if not mega_ok:
                # The token cross-check below needs the single-step
                # kernel even when its timing rung ran in an earlier
                # worker attempt (or failed).
                mstep = MegaQwen3(
                    model, cfg=mega_cfg
                ).decode_fn(1, int(cache0.k.shape[3]))
            mmulti = MegaQwen3(model, cfg=mega_cfg).decode_multi_fn(
                1, int(cache0.k.shape[3]), NS
            )

            mmrun = jax.jit(
                _multi_chain(mmulti, NS, record=False), static_argnums=3
            )

            def mega_multi_once():
                np.asarray(mmrun(model.params, tok0, cache0, STEPS // NS))

            _mega_cross_check(
                "mega_multi", mstep, mmulti, NS, model.params
            )

            _emit(progress_fh, {
                "rung": "mega_multi", "ms": time_rung(mega_multi_once),
                # Amortized per-step cost is the headline for this rung:
                # NS steps ride one launch, so ms already divides by
                # STEPS (time_rung) — record NS for the reader.
                "steps_per_launch": NS,
            })
        except Exception as e:
            _emit(progress_fh, {
                "rung": "mega_multi",
                "error": f"{type(e).__name__}: {e}"[:300],
            })

    if on_tpu and "mega_q8" not in skip:
        # Weight-only int8 decode: HALF the HBM bytes of the bf16 step
        # (decode is bandwidth-bound, so ~half the floor). Reported as
        # its own rung — the headline value stays bf16 for
        # apples-to-apples with the reference's bf16 ladder
        # (docs/mega_triton_kernel.md:27-37); a serving user opts in
        # via MegaConfig(wq8=True).
        _emit(progress_fh, {
            "start": "mega_q8", "budget_s": _MULTI_RUNG_TIMEOUT_S,
        })
        try:
            import dataclasses as _dc

            from triton_distributed_tpu.megakernel import MegaQwen3
            from triton_distributed_tpu.megakernel.code_generator import (
                MegaConfig,
            )

            NS8 = _env_int("TDT_BENCH_NS", 8)
            if NS8 <= 0 or STEPS % NS8:
                NS8 = 8
            qcfg = _dc.replace(mega_cfg or MegaConfig(), wq8=True)
            mega8 = MegaQwen3(model, cfg=qcfg)
            qp = mega8.quantized_params()
            q_single = mega8.decode_fn(1, int(cache0.k.shape[3]))
            q_multi = mega8.decode_multi_fn(1, int(cache0.k.shape[3]), NS8)

            _mega_cross_check("mega_q8", q_single, q_multi, NS8, qp)

            q8_run = jax.jit(
                _multi_chain(q_multi, NS8, record=False), static_argnums=3
            )

            def q8_once():
                np.asarray(q8_run(qp, tok0, cache0, STEPS // NS8))

            _emit(progress_fh, {
                "rung": "mega_q8", "ms": time_rung(q8_once),
                "steps_per_launch": NS8,
                "note": "weight-only int8 (separate regime; headline "
                        "stays bf16)",
            })
        except Exception as e:
            _emit(progress_fh, {
                "rung": "mega_q8",
                "error": f"{type(e).__name__}: {e}"[:300],
            })

    _emit(progress_fh, {"done": True})


def _watch_worker(
    progress_path: str, skip: frozenset[str], model: str,
    hard_kill_at: float,
) -> tuple[bool, str | None]:
    """Launch a TPU worker and watchdog its progress file. Returns
    ``(finished, hung_rung)`` — ``hung_rung`` names the rung being run
    when progress stalled (``"__init__"`` for an init-phase stall, None
    when the worker died on its own). ``hard_kill_at`` is the absolute
    time past which the worker is killed REGARDLESS of per-rung budgets
    — the parent must still summarize whatever rungs are on disk and
    print before the driver's hard kill (VERDICT r4 weak #1b)."""
    with open(progress_path, "a") as fh:
        fh.write("")  # ensure exists
    # Hang attribution must only look at THIS attempt's events — a
    # relaunched worker that hangs before its first emit would
    # otherwise be blamed on the previous attempt's last rung (wrong
    # rung skipped, wrong timeout applied).
    n_before = len(_read_events(progress_path))
    argv = [sys.executable, os.path.abspath(__file__), "--worker",
            progress_path, "--skip", ",".join(sorted(skip)),
            "--model", model]
    proc = subprocess.Popen(
        argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env=_compile_cache_env(dict(os.environ)),
    )

    def _reap(kill: bool) -> None:
        # A worker stalling in jax/relay TEARDOWN (after its work is on
        # disk) must not crash the bench — the results are safe — but it
        # must not be LEFT RUNNING either (an orphan holds the TPU
        # client and wedges the next run): escalate to kill on timeout.
        try:
            if kill:
                proc.kill()
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            try:
                proc.kill()
                proc.wait(timeout=30)
            except (subprocess.TimeoutExpired, OSError):
                pass
        except OSError:
            pass

    last_size = os.path.getsize(progress_path)
    last_change = time.time()
    while True:
        time.sleep(5)
        events = _read_events(progress_path)[n_before:]
        if any("done" in e for e in events):
            _reap(kill=False)
            return True, None
        if time.time() > hard_kill_at:
            # NOT a hang: the worker may be healthily mid-rung — a
            # distinct marker keeps this out of hang attribution (a
            # false 'hung xN' record would steer next-round tuning).
            sys.stderr.write(
                "[bench] global deadline: killing worker to summarize "
                "completed rungs\n"
            )
            _reap(kill=True)
            return False, "__deadline__"
        if proc.poll() is not None:
            # Worker died (crash, OOM): not a hang; its per-rung error
            # lines are already on disk.
            return False, None
        size = os.path.getsize(progress_path)
        if size != last_size:
            last_size, last_change = size, time.time()
            continue
        starts = [e for e in events if "start" in e]
        current = starts[-1]["start"] if starts else None
        if current in (None, "init"):
            limit = _INIT_TIMEOUT_S
        else:
            # Each rung declares its own watchdog budget in its start
            # event (the worker knows which rungs are compile-heavy) —
            # no rung-name special cases here.
            limit = starts[-1].get("budget_s", _RUNG_TIMEOUT_S)
        if time.time() - last_change > limit:
            _reap(kill=True)
            return False, "__init__" if current in (None, "init") else current


def _last_known_tpu() -> dict | None:
    """Newest on-chip ladder cached in ``perf/ONCHIP_*.jsonl`` (the
    measurement queue's log — each record's ``stdout_tail`` holds the
    step's final JSON line). A relay that stays down for a whole round
    must not erase the evidence that the chip HAS run this ladder: the
    minimal fallback line carries the cached number, clearly labeled,
    so the artifact is never silent about known TPU state (VERDICT r4
    next #1)."""
    import glob

    best = None
    pat = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "perf", "ONCHIP_*.jsonl"
    )
    for path in glob.glob(pat):
        for rec in _read_events(path):
            if not isinstance(rec, dict):
                continue  # _read_events keeps any valid JSON line
            tail = rec.get("stdout_tail", "")
            if not isinstance(tail, str):
                continue
            for line in reversed(tail.splitlines()):
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                # Only the record's final PARSEABLE JSON line counts: a
                # later line that parses but isn't a TPU ladder
                # supersedes any earlier ladder in the same tail
                # (ADVICE r5 — the old skip-and-continue could resurrect
                # a superseded line).
                if (isinstance(obj, dict)
                        and obj.get("platform") == "tpu"
                        and "ladder" in obj
                        and (best is None
                             or rec.get("t_start", 0) > best["t_start"])):
                    src = f"{os.path.basename(path)}:{rec.get('step', '?')}"
                    best = {
                        "note": "CACHED prior on-chip result, not this run",
                        "source": src,
                        "t_start": rec.get("t_start", 0),
                        "age_h": round(
                            (time.time() - rec.get("t_start", 0)) / 3600, 1
                        ),
                        "result": obj,
                    }
                break
    return best


def _read_events(progress_path: str) -> list[dict]:
    events = []
    try:
        with open(progress_path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return events


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        progress_path = sys.argv[2]
        flags = dict(zip(sys.argv[3::2], sys.argv[4::2]))
        skip = frozenset(s for s in flags.get("--skip", "").split(",") if s)
        with open(progress_path, "a") as fh:
            run_ladder(
                fh, on_tpu=True, skip=skip, model_name=flags.get("--model")
            )
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "--worker-cpu":
        # CPU stub as a SUBPROCESS so the parent can bound it with a
        # timeout — r4's in-process stub overran the driver's kill with
        # the final JSON never printed (VERDICT r4 weak #1b).
        with open(sys.argv[2], "a") as fh:
            run_ladder(fh, on_tpu=False, skip=frozenset({"pallas"}))
        return 0

    import tempfile

    t_start = time.time()
    # Serialize against a concurrently-running measurement queue
    # (perf/onchip_session.py holds the same flock per step): one chip,
    # one measurer. Proceed anyway after 10 min — the driver's bench
    # must never deadlock behind a wedged queue step.
    tpu_lock = None
    _lock_release = None
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "perf"
        ))
        from _tpulock import HELD_ENV as _HELD
        from _tpulock import acquire as _lock_acquire
        from _tpulock import release as _lock_release

        tpu_lock = _lock_acquire(timeout_s=600)
        if tpu_lock is None and not os.environ.get(_HELD):
            sys.stderr.write(
                "[bench] TPU lock contended; proceeding (numbers may "
                "be noisy)\n"
            )
    except Exception:
        pass  # lock helper missing/broken must never sink the bench
    # Everything up to the CPU reserve is probe budget: keep retrying
    # for the WHOLE window (relay windows are ~30 min every few hours;
    # VERDICT r3: 14 min of probing then a 30-min CPU ladder was the
    # failure mode — inverted here).
    hard_deadline = t_start + _GLOBAL_DEADLINE_S
    probe_deadline = hard_deadline - _CPU_RESERVE_S
    on_tpu = _probe_tpu_until(probe_deadline)
    # Whether the relay EVER answered this run — ``on_tpu`` is flipped
    # off when the worker fails, and the fallback note must not claim
    # "relay down" when the relay answered and the code failed.
    relay_answered = on_tpu
    hang_counts: dict[str, int] = {}
    init_stalls = 0
    fd, progress_path = tempfile.mkstemp(
        prefix="bench_progress_", suffix=".jsonl"
    )
    os.close(fd)

    if on_tpu:
        done: set[str] = set()
        model = os.environ.get("TDT_BENCH_MODEL", "Qwen/Qwen3-0.6B")
        for attempt in range(_WORKER_ATTEMPTS):
            # Attempt 0 always runs: the probe just succeeded, and a
            # success that lands at/after the probe deadline must still
            # buy one worker launch — otherwise a healthy TPU falls
            # through to the CPU stub (ADVICE r4 #2). The hard-kill
            # line inside _watch_worker bounds it.
            if attempt and time.time() > probe_deadline:
                sys.stderr.write("[bench] probe-budget deadline reached\n")
                break
            skip = done | {r for r, c in hang_counts.items() if c >= 2}
            finished, hung = _watch_worker(
                progress_path, frozenset(skip), model, hard_deadline - 90
            )
            events = _read_events(progress_path)
            done = {e["rung"] for e in events if "rung" in e and "ms" in e}
            if finished:
                break
            if hung == "__deadline__":
                break  # out of budget — summarize what's on disk
            if hung == "__init__":
                init_stalls += 1
                sys.stderr.write("[bench] init stalled; re-probing\n")
                if not done and not model.endswith("+lite"):
                    # Full-model first contact wedged before any rung
                    # landed — drop to the relay-gentle lite config so
                    # the round still gets a platform:tpu ladder. Hangs
                    # observed under the full model say nothing about
                    # the lite one; let it try every rung afresh.
                    model += "+lite"
                    hang_counts.clear()
                    sys.stderr.write(f"[bench] falling back to {model}\n")
            elif hung:
                hang_counts[hung] = hang_counts.get(hung, 0) + 1
                sys.stderr.write(f"[bench] rung {hung} hung; re-probing\n")
            # Mid-run re-probe: don't relaunch into a dead relay — keep
            # probing until it answers again or the probe budget runs
            # out (a mid-run outage can end and the window reopen).
            if attempt + 1 < _WORKER_ATTEMPTS and not _probe_tpu_until(
                probe_deadline
            ):
                sys.stderr.write(
                    "[bench] relay down through probe budget; stopping\n"
                )
                break
        events = _read_events(progress_path)
        if not any("rung" in e and "ms" in e for e in events):
            on_tpu = False  # fall back to the CPU ladder below

    cached_tpu = None
    if not on_tpu:
        # No more chip work — stop blocking the measurement queue
        # while the (multi-minute) CPU ladder runs.
        if tpu_lock is not None and _lock_release is not None:
            _lock_release(tpu_lock)
            tpu_lock = None
        # Keep any TPU rung errors from the attempts above — a fully
        # broken TPU path must stay visible in the machine-readable
        # output, not be laundered into a clean CPU run.
        tpu_errors = {
            e["rung"]: e["error"]
            for e in _read_events(progress_path)
            if "rung" in e and "error" in e
        }
        # Watchdog-killed rungs and init stalls never wrote an error
        # event — surface them alongside the real errors.
        for rung, count in hang_counts.items():
            tpu_errors.setdefault(
                rung, f"hung (killed by watchdog) x{count}"
            )
        if init_stalls and "init" not in tpu_errors:
            tpu_errors["init"] = (
                f"init stalled x{init_stalls} (killed by watchdog)"
            )
        # EMIT FIRST: a minimal-but-valid line lands NOW, carrying the
        # newest cached on-chip ladder, so the artifact can never be
        # empty again — then (budget permitting) the refined CPU stub
        # prints a second line that supersedes this one. The cache read
        # is best-effort: a malformed ONCHIP record must not take the
        # emit down with it (that would be r4's failure all over).
        try:
            cached_tpu = _last_known_tpu()
        except Exception as e:
            cached_tpu = None
            sys.stderr.write(f"[bench] last_known_tpu read failed: {e}\n")
        # Compute the stub budget BEFORE printing the minimal line, so
        # the note never promises a refinement that was never going to
        # be attempted (ADVICE r5).
        stub_budget = hard_deadline - time.time() - 60
        stub_note = (
            "CPU stub pending (a refined line follows if it completes)"
            if stub_budget >= 120 else
            f"no budget for CPU stub ({stub_budget:.0f}s left); this "
            "minimal line is final"
        )
        minimal = {
            "metric": "qwen3_decode_ms_per_step",
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "platform": "cpu",
            # relay_answered ⇒ the relay was UP and the worker failed —
            # say so, or the driver misreads a code regression as an
            # outage.
            "note": (
                "relay answered but no TPU rung completed (see "
                f"tpu_errors); {stub_note}" if relay_answered else
                f"relay down for the whole run; {stub_note}"
            ),
        }
        if cached_tpu is not None:
            minimal["last_known_tpu"] = cached_tpu
        if tpu_errors:
            minimal["tpu_errors"] = tpu_errors
        print(json.dumps(minimal), flush=True)
        # REFINE LATER: last-minutes STUB, not a ladder — jit rung only
        # (interpret-mode Pallas timing on a 1-core host is meaningless
        # and burned ~30 min in round 3) — in a subprocess bounded so
        # the parent always returns before the driver's hard kill.
        events = []
        if stub_budget >= 120:
            cpu_path = progress_path + ".cpu"
            try:
                subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--worker-cpu", cpu_path],
                    timeout=stub_budget,
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    env=_compile_cache_env(dict(os.environ)),
                )
            except (subprocess.TimeoutExpired, OSError):
                sys.stderr.write(
                    "[bench] CPU stub timed out/failed; minimal line "
                    "stands\n"
                )
            events = _read_events(cpu_path)
        else:
            sys.stderr.write(
                "[bench] no budget for CPU stub; minimal line stands\n"
            )
    else:
        tpu_errors = {}

    ladder = {
        e["rung"]: e["ms"] for e in events if "rung" in e and "ms" in e
    }
    errors = {
        e["rung"]: e["error"] for e in events
        if "rung" in e and "error" in e and e["rung"] not in ladder
    }
    if on_tpu:
        # Rungs abandoned after repeated watchdog kills never emit an
        # event — record them so they don't silently vanish from the
        # machine-readable output. Same for init stalls on attempts
        # that preceded a successful relaunch.
        for rung, count in hang_counts.items():
            if rung not in ladder and rung not in errors:
                errors[rung] = f"hung (killed by watchdog) x{count}"
        if init_stalls and "init" not in errors:
            errors["init"] = (
                f"init stalled x{init_stalls} (killed by watchdog)"
            )
    # LAST init event: after a +lite fallback the surviving worker's
    # init (model name, param bytes) is the one the summary describes.
    init = next((e["init"] for e in reversed(events) if "init" in e), None)
    cross = next(
        (e for e in events if e.get("cross_check") == "mega_multi"), None
    )

    if not ladder or init is None:
        if on_tpu:
            # Defensive only — on_tpu implies at least one timed rung.
            print(json.dumps({
                "metric": "qwen3_decode_ms_per_step",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "platform": "tpu",
                "errors": errors or {"init": "no rung completed"},
            }))
        elif errors:
            # The stub RAN and failed: supersede the minimal line (it
            # promised "a refined line follows") with the stub's error
            # detail — strictly more info, same null value.
            update = dict(minimal)
            update["note"] = "CPU stub failed (see errors); " + (
                "relay answered but no TPU rung completed (see "
                "tpu_errors)" if relay_answered
                else "relay down for the whole run"
            )
            update["errors"] = errors
            print(json.dumps(update))
        # else: the minimal line printed at fallback time stands — a
        # stub that never ran/landed nothing has nothing to add.
        return 1

    # Headline = best BF16 rung: mega_q8 halves the weight bytes and
    # would win trivially, but the reference ladder it is compared
    # against (docs/mega_triton_kernel.md:27-37) is bf16 — the int8
    # number rides along in the ladder dict instead.
    bf16 = {k: v for k, v in ladder.items() if k != "mega_q8"} or ladder
    best_name = min(bf16, key=bf16.get)
    ms = ladder[best_name]
    # Bandwidth roofline: weights read once per step + KV context read.
    gbs = (init["param_bytes"] + init["kv_bytes"]) / (ms * 1e-3) / 1e9
    # Name the metric after the model the surviving worker actually ran
    # (may be the +lite fallback): Qwen/Qwen3-0.6B -> qwen3_0.6b,
    # Qwen/Qwen3-0.6B+lite -> qwen3_0.6b_lite, tiny -> qwen3_tiny.
    mname = init["model"].split("/")[-1].lower()
    mname = mname.removeprefix("qwen3-").replace("+", "_").replace("-", "_")
    out = {
        "metric": f"qwen3_{mname}_decode_ms_per_step",
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(gbs / init["peak_gbs"], 4),
        "platform": init["platform"],
        "best_rung": best_name,
        "ladder": {k: round(v, 3) for k, v in ladder.items()},
    }
    if not on_tpu:
        out["note"] = "CPU fallback stub (%s)%s" % (
            "relay answered but no TPU rung completed — see tpu_errors"
            if relay_answered else "relay down",
            "; see last_known_tpu for the cached on-chip ladder"
            if cached_tpu is not None else "",
        )
        if cached_tpu is not None:
            out["last_known_tpu"] = cached_tpu
    elif init["model"].endswith("+lite"):
        # Loud in the summary, not just the metric name: the geometry
        # is REDUCED (8 layers / 32k vocab) and not comparable to a
        # full-model ladder (VERDICT r4 weak #7).
        out["note"] = ("REDUCED +lite geometry (num_layers=8, vocab "
                       "32768) — full-model init wedged the relay; not "
                       "comparable to the full 0.6B ladder")
    if cross is not None:
        out["mega_multi_cross_check"] = bool(cross.get("ok"))
    cross8 = next(
        (e for e in events if e.get("cross_check") == "mega_q8"), None
    )
    if cross8 is not None:
        out["mega_q8_cross_check"] = bool(cross8.get("ok"))
    spl = next(
        (e.get("steps_per_launch") for e in events
         if e.get("rung") == "mega_multi" and "steps_per_launch" in e),
        None,
    )
    if spl is not None:
        out["mega_multi_steps_per_launch"] = int(spl)
    if errors:
        out["errors"] = errors
    if tpu_errors:
        out["tpu_errors"] = tpu_errors
    print(json.dumps(out))
    if not errors and not tpu_errors:
        # Clean run: drop the progress files (kept on failures so the
        # per-rung event log stays inspectable).
        for path in (progress_path, progress_path + ".cpu"):
            try:
                os.unlink(path)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
