"""Qwen3 (dense) tensor-parallel model.

Parity: reference ``models/qwen.py`` — ``Qwen3`` with per-layer fwd modes
``torch`` / ``triton_dist`` / ``triton_dist_AR`` (:84-96), prefill
``inference``:209 and the decode path driven by ``Engine``
(``models/engine.py``). Weight names follow the HF Qwen3 checkpoint so
:func:`load_hf_state_dict` maps 1:1.

TPU design: the whole forward is ONE per-shard SPMD program under
``shard_map`` + ``jax.jit`` — every device runs the same trace on its
weight shards (column/row-parallel), the analog of the reference's
one-process-per-GPU torchrun SPMD. Layers are stacked on a leading L axis
and driven by ``lax.scan`` (one compile for all layers); the jitted,
donated decode step is the CUDA-graph analog (``engine.py:75-105``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.tp_attn import (
    TPAttnDims,
    TPAttnParams,
    tp_attn_decode,
    tp_attn_decode_paged,
    tp_attn_decode_sharded,
    tp_attn_prefill,
    tp_attn_prefill_paged_chunk,
    tp_attn_prefill_paged_chunk_cold,
)
from triton_distributed_tpu.layers.tp_mlp import TPMLPParams, tp_mlp_fwd
from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.models.kv_cache import KVCache, cache_specs, init_cache
from triton_distributed_tpu.runtime.mesh import DistContext, current_context
from triton_distributed_tpu.runtime.pytree import register_param_dataclass

Mode = Literal["xla", "pallas"]


@dataclasses.dataclass
class Qwen3LayerParams:
    ln1: jax.Array  # [d] input_layernorm
    attn: TPAttnParams
    ln2: jax.Array  # [d] post_attention_layernorm
    mlp: TPMLPParams


@dataclasses.dataclass
class Qwen3Params:
    embed: jax.Array    # [V, d] replicated
    layers: Qwen3LayerParams  # leaves stacked with leading [L, ...]
    norm: jax.Array     # [d]
    lm_head: jax.Array  # [d, V] column-sharded


for _cls, _fields in ((Qwen3LayerParams, ["ln1", "attn", "ln2", "mlp"]),
                      (Qwen3Params, ["embed", "layers", "norm", "lm_head"])):
    register_param_dataclass(_cls, _fields)


def rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * w.astype(jnp.float32)).astype(x.dtype)


def pad_vocab(v: int, n: int) -> int:
    """Vocab width padded to a multiple of 128·tp, so each shard's
    column count is lane-aligned (Qwen3's 151936 = 2^7·1187 leaves a
    64/96/48 residue at tp=2/4/8). The ONE definition behind
    ``_pad_lm_head``, ``MegaQwen3._dims``'s unloaded fallback, and
    ``quantized_init``."""
    align = 128 * n
    return -(-v // align) * align


class Qwen3:
    """Host-level model wrapper (parity: reference ``Qwen3``,
    ``models/qwen.py``). Holds sharded params + jitted SPMD programs."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        axis: str = "tp",
        ctx: DistContext | None = None,
    ):
        self.cfg = cfg
        self.ctx = ctx or current_context()
        self.axis = axis
        n = self.ctx.axis_size(axis)
        if cfg.num_q_heads % n or cfg.num_kv_heads % n:
            raise ValueError(f"heads not divisible by tp={n}")
        if cfg.intermediate_size % n:
            raise ValueError(f"d_ff not divisible by tp={n}")
        self.dims = TPAttnDims(
            hq_loc=cfg.num_q_heads // n,
            hkv_loc=cfg.num_kv_heads // n,
            head_dim=cfg.head_dim,
            rope_theta=cfg.rope_theta,
        )
        self.params: Qwen3Params | None = None
        self._decode_jit: dict = {}
        self._prefill_jit: dict = {}

    # -- parameter construction ------------------------------------------
    @property
    def param_specs(self) -> Qwen3Params:
        ax = self.axis
        return Qwen3Params(
            embed=P(),
            layers=Qwen3LayerParams(
                ln1=P(),
                attn=TPAttnParams(
                    wqkv=P(None, None, ax), wo=P(None, ax, None),
                    q_norm=P(), k_norm=P(),
                ),
                ln2=P(),
                mlp=TPMLPParams(w1=P(None, None, ax), w2=P(None, ax, None)),
            ),
            norm=P(),
            lm_head=P(None, ax),
        )

    def init_params(self, key: jax.Array) -> Qwen3Params:
        """Random init (tests/benchmarks; parity: the reference's
        ``rand_fill`` paths used in its perf scripts)."""
        cfg = self.cfg
        n = self.ctx.axis_size(self.axis)
        hd, d = cfg.head_dim, cfg.hidden_size
        L = cfg.num_layers
        dt = cfg.dtype

        def build(key):
            ks = iter(jax.random.split(key, 9))

            def rnd(k, *shape, scale=None):
                scale = scale if scale is not None else shape[-2] ** -0.5
                return (
                    jax.random.normal(k, shape, jnp.float32) * scale
                ).astype(dt)

            # Fused qkv, laid out per shard [q_loc | k_loc | v_loc].
            wq = rnd(next(ks), L, d, cfg.num_q_heads * hd)
            wk = rnd(next(ks), L, d, cfg.num_kv_heads * hd)
            wv = rnd(next(ks), L, d, cfg.num_kv_heads * hd)
            wqkv = _fuse_by_shard([wq, wk, wv], n)
            gate = rnd(next(ks), L, d, cfg.intermediate_size)
            up = rnd(next(ks), L, d, cfg.intermediate_size)
            w1 = _fuse_by_shard([gate, up], n)
            return Qwen3Params(
                embed=rnd(next(ks), cfg.vocab_size, d, scale=0.02),
                layers=Qwen3LayerParams(
                    ln1=jnp.ones((L, d), dt),
                    attn=TPAttnParams(
                        wqkv=wqkv,
                        wo=rnd(next(ks), L, cfg.num_q_heads * hd, d),
                        q_norm=jnp.ones((L, hd), dt),
                        k_norm=jnp.ones((L, hd), dt),
                    ),
                    ln2=jnp.ones((L, d), dt),
                    mlp=TPMLPParams(
                        w1=w1, w2=rnd(next(ks), L, cfg.intermediate_size, d)
                    ),
                ),
                norm=jnp.ones((d,), dt),
                lm_head=rnd(next(ks), d, cfg.vocab_size),
            )

        return self._set_params_jit(build, key)

    def _pad_lm_head(self, params: Qwen3Params) -> Qwen3Params:
        # Pad the LM head's vocab axis to a multiple of 128·tp: each
        # shard's column count becomes a 128-multiple, so tiled kernels
        # (the megakernel's wide lm stream) stay lane-aligned under TP
        # (Qwen3's 151936 = 2^7·1187 leaves a 64/96/48 residue at
        # tp=2/4/8). ``_logits`` slices the pads back off — zero-weight
        # columns would otherwise score 0 and could beat real logits.
        v = params.lm_head.shape[1]
        vp = pad_vocab(v, self.ctx.axis_size(self.axis))
        if vp != v:
            params = dataclasses.replace(
                params, lm_head=jnp.pad(params.lm_head, ((0, 0), (0, vp - v)))
            )
        return params

    @property
    def param_shardings(self):
        return jax.tree.map(
            lambda s: self.ctx.sharding(*s),
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _set_params_jit(self, build, key: jax.Array) -> Qwen3Params:
        """Generate + pad + shard the whole param pytree in ONE compiled
        program, executed device-side. Random init was previously ~50
        eager ops; on the axon relay each eager dispatch is a remote
        compile round trip, which blew the bench's init budget (and
        correlates with relay wedges). One jit = one compile, and the
        weights never transit the host."""
        self.params = jax.jit(
            lambda k: self._pad_lm_head(build(k)),
            out_shardings=self.param_shardings,
        )(key)
        return self.params

    def set_params(self, params: Qwen3Params) -> Qwen3Params:
        params = self._pad_lm_head(params)
        self.params = jax.tree.map(
            jax.device_put, params, self.param_shardings
        )
        return self.params

    # -- per-shard forward bodies ----------------------------------------
    def _mlp_fwd(self, mlp_params, h: jax.Array, mode) -> jax.Array:
        """Per-shard MLP call — overridden by the MoE model."""
        return tp_mlp_fwd(mlp_params, h, axis=self.axis, mode=mode, ctx=self.ctx)

    def _embed(self, params: Qwen3Params, tokens: jax.Array) -> jax.Array:
        return jnp.take(params.embed, tokens, axis=0)

    def _logits(self, params: Qwen3Params, x: jax.Array) -> jax.Array:
        """[B, d] → full logits [B, V] (lm_head column-sharded + gather;
        vocab padding from ``set_params`` sliced back off)."""
        loc = jnp.dot(
            x, params.lm_head, preferred_element_type=jnp.float32
        )
        full = jax.lax.all_gather(loc, self.axis, axis=1, tiled=True)
        return full[:, : self.cfg.vocab_size]

    def _decode_shard(self, params, tokens, cache: KVCache, *, mode: Mode):
        """One decode step, per-shard: ``tokens [B]`` → logits [B, V]."""
        cfg = self.cfg
        x = self._embed(params, tokens)
        ar = "pallas_ar" if mode == "pallas" else "xla_ar"

        def layer_fn(carry, inp):
            x = carry
            lp, kc, vc = inp
            h = rms_norm(x, lp.ln1, cfg.rms_eps)
            a, kc, vc = tp_attn_decode(
                lp.attn, h, kc, vc, cache.kv_len, self.dims,
                axis=self.axis, mode=ar, ctx=self.ctx,
            )
            x = x + a
            h = rms_norm(x, lp.ln2, cfg.rms_eps)
            x = x + self._mlp_fwd(lp.mlp, h, ar)
            return x, (kc, vc)

        x, (k_new, v_new) = jax.lax.scan(
            layer_fn, x, (params.layers, cache.k, cache.v)
        )
        x = rms_norm(x, params.norm, cfg.rms_eps)
        logits = self._logits(params, x)
        return logits, KVCache(k=k_new, v=v_new, kv_len=cache.kv_len + 1)

    def _decode_shard_paged(self, params, tokens, cache, *, mode: Mode):
        """One decode step over a :class:`PagedKVCache`, per-shard.

        Same layer scan as :meth:`_decode_shard`, but the attention
        appends through the page table and reads the pool directly
        (``paged_flash_decode``). Parity: the reference megakernel's
        paged decode (``mega_triton_kernel/models/paged_kv_cache.py``).
        """
        from triton_distributed_tpu.models.paged_kv_cache import PagedKVCache

        cfg = self.cfg
        x = self._embed(params, tokens)
        ar = "pallas_ar" if mode == "pallas" else "xla_ar"

        def layer_fn(carry, inp):
            x = carry
            # kp/vp: [P, hkv_loc, page, hd] layer pool; ks/vs are the
            # int8 per-page-per-head scales, or None on a full-width
            # pool (lax.scan threads the empty subtree through).
            lp, kp, vp, ks, vs = inp
            h = rms_norm(x, lp.ln1, cfg.rms_eps)
            a, kp, vp, ks, vs = tp_attn_decode_paged(
                lp.attn, h, kp, vp, cache.page_table, cache.kv_len,
                self.dims, axis=self.axis, mode=ar, ctx=self.ctx,
                k_scale=ks, v_scale=vs,
            )
            x = x + a
            h = rms_norm(x, lp.ln2, cfg.rms_eps)
            x = x + self._mlp_fwd(lp.mlp, h, ar)
            return x, (kp, vp, ks, vs)

        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer_fn, x,
            (params.layers, cache.k_pages, cache.v_pages,
             cache.k_scale, cache.v_scale),
        )
        x = rms_norm(x, params.norm, cfg.rms_eps)
        logits = self._logits(params, x)
        return logits, PagedKVCache(
            k_pages=k_new, v_pages=v_new,
            page_table=cache.page_table, kv_len=cache.kv_len + 1,
            k_scale=ks_new, v_scale=vs_new,
        )

    def _prefill_batch_shard(
        self, params, tokens, cache: KVCache, true_lens, *, mode: Mode
    ):
        """Prefill ``B_rows`` sequences in ONE program, per-shard (the
        single-sequence :meth:`prefill` is the B_rows=1 case).

        ``tokens [B_rows, s_loc]``: each row's sequence slice;
        activations stay sequence-sharded through all layers (ag_gemm
        gathers rows on the fly — reference ``dist_triton_fwd`` layout).
        Rows run as a ``lax.scan`` (sequential on device — prefill rows
        are compute-bound, so row parallelism buys little — but one
        dispatch replaces the host loop the reference engine also pays,
        ``models/engine.py:113``). ``true_lens[i]`` is row i's real
        prompt length: positions past it are right-padding, inert under
        causal masking; logits are taken at ``true_lens[i] - 1`` and
        ``kv_len[i]`` set to ``true_lens[i]`` so decode overwrites the
        pad KV slots. Each row computes its K/V stack without touching
        the cache; one batched write lands entries [0, B_rows) (the
        cache batch may be larger).
        """
        cfg = self.cfg
        me = jax.lax.axis_index(self.axis)
        s_loc = tokens.shape[1]

        def row_fn(_, inp):
            toks, true_len = inp
            x = self._embed(params, toks)  # [s_loc, d]

            def layer_fn(x, lp):
                h = rms_norm(x, lp.ln1, cfg.rms_eps)
                a, k_full, v_full = tp_attn_prefill(
                    lp.attn, h, self.dims, axis=self.axis, mode=mode,
                    ctx=self.ctx,
                )
                x = x + a
                h = rms_norm(x, lp.ln2, cfg.rms_eps)
                x = x + self._mlp_fwd(lp.mlp, h, mode)
                return x, (k_full, v_full)

            x, (k_all, v_all) = jax.lax.scan(layer_fn, x, params.layers)
            x = rms_norm(x, params.norm, cfg.rms_eps)
            # The last real token lives at global position true_len - 1
            # on shard (idx // s_loc); select its row, broadcast by psum.
            idx = true_len - 1
            own = jnp.where(me == idx // s_loc, 1.0, 0.0).astype(jnp.float32)
            row = jnp.take(x, idx % s_loc, axis=0)
            x_last = jax.lax.psum(row.astype(jnp.float32) * own, self.axis)
            logits = self._logits(params, x_last[None].astype(x.dtype))[0]
            # k_all [L, hkv_loc, S, hd] per row.
            return None, (logits, k_all, v_all)

        _, (logits, ks, vs) = jax.lax.scan(row_fn, None, (tokens, true_lens))
        # ks [B_rows, L, hkv, S, hd] → [L, B_rows, hkv, S, hd] at [0, S).
        k_new = jax.lax.dynamic_update_slice(
            cache.k, jnp.swapaxes(ks, 0, 1).astype(cache.k.dtype),
            (0, 0, 0, 0, 0),
        )
        v_new = jax.lax.dynamic_update_slice(
            cache.v, jnp.swapaxes(vs, 0, 1).astype(cache.v.dtype),
            (0, 0, 0, 0, 0),
        )
        kv_len = jax.lax.dynamic_update_slice(cache.kv_len, true_lens, (0,))
        return logits, KVCache(k=k_new, v=v_new, kv_len=kv_len)

    def _prefill_chunk_shard(
        self, params, tokens, cache, slot, q_offset, new_len, last_idx,
        tree_mask=None, tree_depth=None,
        *, mode: Mode, kv_pages: int | None = None,
        all_logits: bool = False,
    ):
        """Chunked-prefill one slot of a :class:`PagedKVCache`, per-shard.

        ``tokens [C]`` is one suffix chunk (right-padded; pads write
        masked/overwritten KV and are causally inert), ``q_offset`` the
        slot's already-cached length, ``new_len`` the slot's kv_len after
        this chunk (set absolutely, so interleaved decode steps bumping
        the in-flight slot's counter can never leave it skewed), and
        ``last_idx`` the chunk index whose logits are returned (the
        prompt's last real token on the final chunk; ignored upstream on
        earlier chunks). Same layer scan as :meth:`_decode_shard_paged`
        with chunk attention against prefix pages + chunk.

        ``tree_mask [C, C]``/``tree_depth [C]`` put the chunk in tree
        mode (speculative tree verify): rows are draft-tree nodes in DFS
        storage order, ``tree_mask[i, j]`` is 0 where node j is an
        ancestor-or-self of node i and ``-1e30`` otherwise (sibling
        branches never attend to each other), and each node ropes at
        ``q_offset + tree_depth[i]`` — its position on its OWN root
        path — while its KV still scatters at storage ``q_offset + i``.
        The [C, C] mask expands to the gathered dense view's [C, S_kv]
        additive bias once here (prefix columns fully visible, columns
        past the chunk left to causality), shared by every layer.
        """
        cfg = self.cfg
        x = self._embed(params, tokens)  # [C, d]
        table_row = cache.page_table[slot]
        ar = "pallas_ar" if mode == "pallas" else "xla_ar"
        rope_pos = attn_bias = None
        if tree_mask is not None:
            c = tokens.shape[0]
            page = cache.k_pages.shape[3]
            pps = table_row.shape[0] if kv_pages is None else kv_pages
            cols = jnp.arange(pps * page, dtype=jnp.int32)
            rel = jnp.clip(cols - q_offset, 0, c - 1)
            in_chunk = (cols >= q_offset) & (cols < q_offset + c)
            attn_bias = jnp.where(
                in_chunk[None, :],
                jnp.take(tree_mask.astype(jnp.float32), rel, axis=1),
                0.0,
            )  # [C, S_kv]
            rope_pos = q_offset + tree_depth

        def layer_fn(carry, inp):
            x = carry
            lp, kp, vp, ks, vs = inp  # ks/vs: int8 scales or None
            h = rms_norm(x, lp.ln1, cfg.rms_eps)
            a, kp, vp, ks, vs = tp_attn_prefill_paged_chunk(
                lp.attn, h, kp, vp, table_row, q_offset, self.dims,
                kv_pages=kv_pages, axis=self.axis, mode=ar, ctx=self.ctx,
                k_scale=ks, v_scale=vs, q_end=new_len,
                rope_pos=rope_pos, attn_bias=attn_bias,
            )
            x = x + a
            h = rms_norm(x, lp.ln2, cfg.rms_eps)
            x = x + self._mlp_fwd(lp.mlp, h, ar)
            return x, (kp, vp, ks, vs)

        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer_fn, x,
            (params.layers, cache.k_pages, cache.v_pages,
             cache.k_scale, cache.v_scale),
        )
        x = rms_norm(x, params.norm, cfg.rms_eps)
        if all_logits:
            # Per-position logits [C, V] — the speculative verifier
            # scores every drafted token from ONE chunk forward.
            logits = self._logits(params, x)
        else:
            x_last = jnp.take(x, last_idx, axis=0)
            logits = self._logits(params, x_last[None])[0]
        from triton_distributed_tpu.models.paged_kv_cache import PagedKVCache

        return logits, PagedKVCache(
            k_pages=k_new, v_pages=v_new, page_table=cache.page_table,
            kv_len=cache.kv_len.at[slot].set(new_len.astype(jnp.int32)),
            k_scale=ks_new, v_scale=vs_new,
        )

    def prefill_paged_chunk(
        self,
        tokens,          # [C] int32 — one (padded) suffix chunk
        slot: int,
        q_offset: int,
        new_len: int,
        last_idx: int,
        cache,           # PagedKVCache
        mode: Mode = "xla",
        kv_pages: int | None = None,
        all_logits: bool = False,
        tree_mask=None,   # [C, C] f32 — 0 visible / -1e30 masked
        tree_depth=None,  # [C] int32 — per-node depth below q_offset
    ):
        """Jitted chunked prefill of ``slot``'s suffix over the paged
        pool — the prefix-cache data plane: matched prefix pages are
        attended, only the chunk is computed. Keyed on chunk width and
        the ``kv_pages`` gather bucket only (offset/slot/lengths are
        traced), so a handful of compiled programs serve every
        admission. Returns ``(last_idx logits [V], cache)`` — or
        ``(per-position logits [C, V], cache)`` with ``all_logits=True``
        (the speculative verify path scores every chunk position).
        ``tree_mask``/``tree_depth`` (passed together) run the chunk as
        a speculative draft TREE under a tree-attention mask — one
        compiled tree program per chunk width, the mask and depths ride
        as traced operands."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            paged_cache_specs,
        )

        quant = cache.k_scale is not None
        tree = tree_mask is not None
        if tree != (tree_depth is not None):
            raise ValueError("tree_mask and tree_depth go together")
        key = ("chunk", mode, int(tokens.shape[0]), kv_pages, all_logits,
               quant, tree)
        if key not in self._prefill_jit:
            tree_specs = (P(), P()) if tree else ()
            f = self.ctx.shard_map(
                functools.partial(self._prefill_chunk_shard, mode=mode,
                                  kv_pages=kv_pages, all_logits=all_logits),
                in_specs=(
                    self.param_specs, P(),
                    paged_cache_specs(self.axis, quant),
                    P(), P(), P(), P(), *tree_specs,
                ),
                out_specs=(P(), paged_cache_specs(self.axis, quant)),
            )
            self._prefill_jit[key] = jax.jit(
                lambda p, t, c, s, o, n, li, *tr: f(p, t, c, s, o, n, li,
                                                    *tr),
                donate_argnums=(2,),
            )
        tree_args = ()
        if tree:
            tree_args = (jnp.asarray(tree_mask, jnp.float32),
                         jnp.asarray(tree_depth, jnp.int32))
        return self._prefill_jit[key](
            self.params, jnp.asarray(tokens, jnp.int32), cache,
            jnp.asarray(slot, jnp.int32), jnp.asarray(q_offset, jnp.int32),
            jnp.asarray(new_len, jnp.int32), jnp.asarray(last_idx, jnp.int32),
            *tree_args,
        )

    # -- sharded long-context slot programs ------------------------------
    #
    # A slot whose KV exceeds the per-rank page budget splits into a
    # RESIDENT paged window (local positions, its own explicit
    # ``table_row`` — the slot's pages are not in the batched device
    # table) and a COLD dense window of tier-demoted pages (pool dtype +
    # per-page scales, read-only). Both programs merge the two attention
    # partials with ``lse_combine`` — the distributed-flash-decode
    # combine shape (docs/serving.md "Long-context serving").

    def _prefill_chunk_cold_shard(
        self, params, tokens, cache, k_cold, v_cold, ks_cold, vs_cold,
        table_row, s_cold, q_offset, q_end, last_idx, *, mode: Mode,
    ):
        """Chunk-prefill a SHARDED slot, per-shard: like
        :meth:`_prefill_chunk_shard` but the KV scatter lands at LOCAL
        resident positions through the explicit ``table_row`` and the
        attention adds the cold-window partial. The batched device
        ``kv_len``/``page_table`` are untouched — a sharded slot is
        invisible to the batched decode step."""
        from triton_distributed_tpu.models.paged_kv_cache import PagedKVCache

        cfg = self.cfg
        x = self._embed(params, tokens)  # [C, d]
        ar = "pallas_ar" if mode == "pallas" else "xla_ar"

        def layer_fn(carry, inp):
            x = carry
            lp, kp, vp, ks, vs, kc, vc, ksc, vsc = inp
            h = rms_norm(x, lp.ln1, cfg.rms_eps)
            a, kp, vp, ks, vs = tp_attn_prefill_paged_chunk_cold(
                lp.attn, h, kp, vp, table_row, kc, vc, s_cold, q_offset,
                self.dims, axis=self.axis, mode=ar, ctx=self.ctx,
                k_scale=ks, v_scale=vs, ks_cold=ksc, vs_cold=vsc,
                q_end=q_end,
            )
            x = x + a
            h = rms_norm(x, lp.ln2, cfg.rms_eps)
            x = x + self._mlp_fwd(lp.mlp, h, ar)
            return x, (kp, vp, ks, vs)

        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer_fn, x,
            (params.layers, cache.k_pages, cache.v_pages,
             cache.k_scale, cache.v_scale, k_cold, v_cold,
             ks_cold, vs_cold),
        )
        x = rms_norm(x, params.norm, cfg.rms_eps)
        x_last = jnp.take(x, last_idx, axis=0)
        logits = self._logits(params, x_last[None])[0]
        return logits, PagedKVCache(
            k_pages=k_new, v_pages=v_new, page_table=cache.page_table,
            kv_len=cache.kv_len, k_scale=ks_new, v_scale=vs_new,
        )

    def prefill_paged_chunk_cold(
        self,
        tokens,          # [C] int32 — one (padded) suffix chunk
        table_row,       # [budget_pages] int32 — the slot's resident row
        q_offset: int,   # absolute chunk start
        q_end: int,      # absolute end of REAL rows
        last_idx: int,
        cache,           # PagedKVCache
        k_cold, v_cold,  # [L, Hkv, S_bucket, hd] pool-dtype cold window
        ks_cold=None, vs_cold=None,  # [L, Hkv, S_bucket/page] f32
        s_cold: int = 0,             # valid cold tokens (≤ S_bucket)
        mode: Mode = "xla",
    ):
        """Jitted sharded-slot chunk prefill. Keyed on chunk width, the
        cold bucket width (a power-of-two page count — log-many
        programs over a prompt's life) and the resident row length;
        offsets and ``s_cold`` ride as traced operands."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            paged_cache_specs,
        )

        quant = cache.k_scale is not None
        s_bucket = int(k_cold.shape[2])
        row_len = int(table_row.shape[0])
        key = ("chunk_cold", mode, int(tokens.shape[0]), quant, s_bucket,
               row_len)
        if key not in self._prefill_jit:
            cold_spec = P(None, self.axis, None, None)
            scale_spec = P(None, self.axis, None) if quant else None
            f = self.ctx.shard_map(
                functools.partial(self._prefill_chunk_cold_shard,
                                  mode=mode),
                in_specs=(
                    self.param_specs, P(),
                    paged_cache_specs(self.axis, quant),
                    cold_spec, cold_spec, scale_spec, scale_spec,
                    P(), P(), P(), P(), P(),
                ),
                out_specs=(P(), paged_cache_specs(self.axis, quant)),
            )
            self._prefill_jit[key] = jax.jit(
                lambda p, t, c, kc, vc, ksc, vsc, tr, sc, o, e, li: f(
                    p, t, c, kc, vc, ksc, vsc, tr, sc, o, e, li
                ),
                donate_argnums=(2,),
            )
        return self._prefill_jit[key](
            self.params, jnp.asarray(tokens, jnp.int32), cache,
            k_cold, v_cold, ks_cold, vs_cold,
            jnp.asarray(table_row, jnp.int32),
            jnp.asarray(s_cold, jnp.int32),
            jnp.asarray(q_offset, jnp.int32),
            jnp.asarray(q_end, jnp.int32),
            jnp.asarray(last_idx, jnp.int32),
        )

    def _decode_shard_sharded(
        self, params, token, cache, k_cold, v_cold, ks_cold, vs_cold,
        table_row, kv_len_loc, s_cold, *, mode: Mode,
    ):
        """One decode step of ONE sharded slot, per-shard: resident
        paged partial + cold dense partial, ``lse_combine``d. The
        batched ``kv_len``/``page_table`` are untouched."""
        from triton_distributed_tpu.models.paged_kv_cache import PagedKVCache

        cfg = self.cfg
        x = self._embed(params, token)  # [1, d]
        ar = "pallas_ar" if mode == "pallas" else "xla_ar"

        def layer_fn(carry, inp):
            x = carry
            lp, kp, vp, ks, vs, kc, vc, ksc, vsc = inp
            h = rms_norm(x, lp.ln1, cfg.rms_eps)
            a, kp, vp, ks, vs = tp_attn_decode_sharded(
                lp.attn, h, kp, vp, table_row, kv_len_loc, kc, vc,
                s_cold, self.dims, axis=self.axis, mode=ar, ctx=self.ctx,
                k_scale=ks, v_scale=vs, ks_cold=ksc, vs_cold=vsc,
            )
            x = x + a
            h = rms_norm(x, lp.ln2, cfg.rms_eps)
            x = x + self._mlp_fwd(lp.mlp, h, ar)
            return x, (kp, vp, ks, vs)

        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer_fn, x,
            (params.layers, cache.k_pages, cache.v_pages,
             cache.k_scale, cache.v_scale, k_cold, v_cold,
             ks_cold, vs_cold),
        )
        x = rms_norm(x, params.norm, cfg.rms_eps)
        logits = self._logits(params, x)  # [1, V]
        return logits, PagedKVCache(
            k_pages=k_new, v_pages=v_new, page_table=cache.page_table,
            kv_len=cache.kv_len, k_scale=ks_new, v_scale=vs_new,
        )

    def decode_step_sharded(
        self,
        token,           # [1] int32 — the slot's new token
        cache,           # PagedKVCache
        table_row,       # [budget_pages] int32
        kv_len_loc: int,  # tokens in the resident region
        k_cold, v_cold,  # [L, Hkv, S_bucket, hd] pool-dtype cold window
        ks_cold=None, vs_cold=None,
        s_cold: int = 0,
        mode: Mode = "xla",
    ):
        """Jitted sharded-slot decode step → ``(logits [1, V], cache)``.
        Keyed on the cold bucket width and resident row length."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            paged_cache_specs,
        )

        quant = cache.k_scale is not None
        s_bucket = int(k_cold.shape[2])
        row_len = int(table_row.shape[0])
        key = ("sharded", mode, quant, s_bucket, row_len)
        if key not in self._decode_jit:
            cold_spec = P(None, self.axis, None, None)
            scale_spec = P(None, self.axis, None) if quant else None
            f = self.ctx.shard_map(
                functools.partial(self._decode_shard_sharded, mode=mode),
                in_specs=(
                    self.param_specs, P(),
                    paged_cache_specs(self.axis, quant),
                    cold_spec, cold_spec, scale_spec, scale_spec,
                    P(), P(), P(),
                ),
                out_specs=(P(), paged_cache_specs(self.axis, quant)),
            )
            self._decode_jit[key] = jax.jit(
                lambda p, t, c, kc, vc, ksc, vsc, tr, kl, sc: f(
                    p, t, c, kc, vc, ksc, vsc, tr, kl, sc
                ),
                donate_argnums=(2,),
            )
        return self._decode_jit[key](
            self.params, jnp.asarray(token, jnp.int32), cache,
            k_cold, v_cold, ks_cold, vs_cold,
            jnp.asarray(table_row, jnp.int32),
            jnp.asarray([kv_len_loc], jnp.int32),
            jnp.asarray([s_cold], jnp.int32),
        )

    # -- jitted SPMD entry points ----------------------------------------
    def decode_fn(self, mode: Mode = "xla"):
        """The un-jitted shard_map'd step ``(params, tokens, cache) →
        (logits, cache)`` — composable inside callers' own jit/scan
        (bench chains steps through ``lax.fori_loop``)."""
        return self.ctx.shard_map(
            functools.partial(self._decode_shard, mode=mode),
            in_specs=(self.param_specs, P(), cache_specs(self.axis)),
            out_specs=(P(), cache_specs(self.axis)),
        )

    def decode_fn_paged(self, mode: Mode = "xla", quantized: bool = False):
        """Paged-cache analog of :meth:`decode_fn`:
        ``(params, tokens, PagedKVCache) → (logits, PagedKVCache)``.
        ``quantized`` matches an int8 pool's pytree (scale leaves ride
        the shard_map specs)."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            paged_cache_specs,
        )

        return self.ctx.shard_map(
            functools.partial(self._decode_shard_paged, mode=mode),
            in_specs=(self.param_specs, P(),
                      paged_cache_specs(self.axis, quantized)),
            out_specs=(P(), paged_cache_specs(self.axis, quantized)),
        )

    def decode_step(self, tokens: jax.Array, cache, mode: Mode = "xla"):
        """Jitted one-token step for the whole batch (CUDA-graph analog).
        ``tokens [B]`` int32 → ``(logits [B, V] f32, cache)``. Accepts a
        dense :class:`KVCache` or a :class:`PagedKVCache` (full-width or
        int8-quantized — keyed separately, the pytrees differ)."""
        from triton_distributed_tpu.models.paged_kv_cache import PagedKVCache

        paged = isinstance(cache, PagedKVCache)
        quant = paged and cache.k_scale is not None
        key = (mode, "paged", quant) if paged else mode
        if key not in self._decode_jit:
            f = (
                self.decode_fn_paged(mode, quantized=quant) if paged
                else self.decode_fn(mode)
            )
            self._decode_jit[key] = jax.jit(
                lambda p, t, c: f(p, t, c), donate_argnums=(2,)
            )
        return self._decode_jit[key](self.params, tokens, cache)

    def prefill(
        self,
        tokens: jax.Array,
        cache: KVCache,
        mode: Mode = "xla",
        true_len: jax.Array | int | None = None,
    ):
        """Prefill one sequence (``tokens [S]``, S divisible by tp;
        right-pad to reach divisibility and pass the real length as
        ``true_len`` — trailing pads are inert under causal masking).
        Returns (last-real-token logits [V], cache with entry 0 filled).
        The B_rows=1 case of :meth:`prefill_batched` (one forward path)."""
        key = (mode, int(tokens.shape[0]))
        if true_len is None:
            true_len = tokens.shape[0]
        if key not in self._prefill_jit:
            f = self.ctx.shard_map(
                functools.partial(self._prefill_batch_shard, mode=mode),
                in_specs=(
                    self.param_specs, P(None, self.axis),
                    cache_specs(self.axis), P(),
                ),
                out_specs=(P(), cache_specs(self.axis)),
            )
            # No cache donation here: callers may alias slices of a
            # larger cache — donating would delete their buffer. The
            # per-token donation win lives in decode_step.
            self._prefill_jit[key] = jax.jit(
                lambda p, t, c, tl: f(p, t[None], c, tl[None])
            )
        logits, cache = self._prefill_jit[key](
            self.params, tokens, cache, jnp.asarray(true_len, jnp.int32)
        )
        return logits[0], cache

    def prefill_batched(
        self,
        tokens: jax.Array,  # [B, S] int32, S divisible by tp
        cache: KVCache,
        mode: Mode = "xla",
        true_lens: jax.Array | None = None,
    ):
        """Prefill every sequence of the batch in ONE jitted program
        (row scan on device; see ``_prefill_batch_shard``). Returns
        (last-real-token logits [B, V], filled cache)."""
        b, s = tokens.shape
        if true_lens is None:
            true_lens = jnp.full((b,), s, jnp.int32)
        key = ("batched", mode, b, s)
        if key not in self._prefill_jit:
            f = self.ctx.shard_map(
                functools.partial(self._prefill_batch_shard, mode=mode),
                in_specs=(
                    self.param_specs, P(None, self.axis),
                    cache_specs(self.axis), P(),
                ),
                out_specs=(P(), cache_specs(self.axis)),
            )
            self._prefill_jit[key] = jax.jit(
                lambda p, t, c, tl: f(p, t, c, tl), donate_argnums=(2,)
            )
        return self._prefill_jit[key](
            self.params, tokens, cache, jnp.asarray(true_lens, jnp.int32)
        )

    def new_cache(self, batch_size: int, max_length: int | None = None) -> KVCache:
        return init_cache(
            self.cfg, batch_size, self.ctx, self.axis, max_length
        )


def _fuse_by_shard(parts: list[jax.Array], n: int) -> jax.Array:
    """Stack column-parallel weights so each device shard is the
    concatenation of its slice of every part: ``[L, d, sum(cols)]`` with
    per-shard layout ``[p0_loc | p1_loc | ...]``."""
    L, d = parts[0].shape[:2]
    split = [p.reshape(L, d, n, p.shape[2] // n) for p in parts]
    fused = jnp.concatenate(split, axis=3)  # [L, d, n, sum_loc]
    return fused.reshape(L, d, fused.shape[2] * fused.shape[3])


def load_hf_state_dict(cfg: ModelConfig, state: dict, n: int) -> Qwen3Params:
    """Map an HF Qwen3 state dict (numpy/jnp arrays, torch layout
    ``weight [out, in]``) to :class:`Qwen3Params` (parity: reference
    weight loading, ``models/qwen.py:147-165``)."""
    L = cfg.num_layers

    def get(name):
        return jnp.asarray(state[name]).astype(cfg.dtype)

    def stack(fmt, transpose=True):
        ws = [get(fmt.format(i)) for i in range(L)]
        ws = [w.T if transpose else w for w in ws]
        return jnp.stack(ws)

    wq = stack("model.layers.{}.self_attn.q_proj.weight")
    wk = stack("model.layers.{}.self_attn.k_proj.weight")
    wv = stack("model.layers.{}.self_attn.v_proj.weight")
    gate = stack("model.layers.{}.mlp.gate_proj.weight")
    up = stack("model.layers.{}.mlp.up_proj.weight")
    embed = get("model.embed_tokens.weight")
    lm_head = (
        embed.T
        if cfg.tie_word_embeddings
        else get("lm_head.weight").T
    )
    return Qwen3Params(
        embed=embed,
        layers=Qwen3LayerParams(
            ln1=stack("model.layers.{}.input_layernorm.weight", transpose=False),
            attn=TPAttnParams(
                wqkv=_fuse_by_shard([wq, wk, wv], n),
                wo=stack("model.layers.{}.self_attn.o_proj.weight"),
                q_norm=stack(
                    "model.layers.{}.self_attn.q_norm.weight", transpose=False
                ),
                k_norm=stack(
                    "model.layers.{}.self_attn.k_norm.weight", transpose=False
                ),
            ),
            ln2=stack(
                "model.layers.{}.post_attention_layernorm.weight", transpose=False
            ),
            mlp=TPMLPParams(
                w1=_fuse_by_shard([gate, up], n),
                w2=stack("model.layers.{}.mlp.down_proj.weight"),
            ),
        ),
        norm=get("model.norm.weight"),
        lm_head=lm_head,
    )
