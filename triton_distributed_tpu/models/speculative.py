"""Self-drafting speculative decoding: n-gram/tree draft + one-forward
verify.

Steady-state decode pays one full target forward per emitted token —
the serial bottleneck the paper's philosophy (hide latency behind work
already in flight, arXiv:2504.19442) says to amortize. Speculative
decoding does exactly that on the sequential-decode axis: draft K cheap
candidate tokens, score ALL of them in ONE target forward through the
existing chunked paged-prefill path (``Qwen3.prefill_paged_chunk``
returns per-position logits with ``all_logits=True``), accept the
longest correct prefix, and roll the KV back past the rejection point
(``paged_kv_cache.rollback_kv``). One target step then emits
``accepted + 1`` tokens instead of one.

**Self-drafting**: the drafter is a prompt-lookup n-gram table over the
request's OWN prompt + generated tokens (the PLD / lookahead-free
idea) — no second model, no extra weights, runs anywhere the engine
runs. Repetitive and structured traffic (code, templated answers,
retrieval-heavy prompts, greedy cycles) drafts extremely well; chaotic
text degrades gracefully to K=0, i.e. plain decode.

**Exactness**: greedy acceptance compares each draft against the
argmax of the target logits at its position — output is bit-identical
to non-speculative greedy decode. For ``temperature>0`` the acceptance
is the standard rejection-sampling rule specialized to a deterministic
(delta) proposal: accept draft ``d`` with probability ``p(d)`` under
the FILTERED target distribution (``sampling.target_probs`` — the very
distribution ``sampling.sample`` draws from), and on rejection sample
from the residual ``p`` with ``d`` zeroed, renormalized. The emitted
distribution is exactly the target's (tests carry the statistical
proof).

**Acceptance bookkeeping** (the T3-style tracking/trigger discipline,
arXiv:2401.16677): per-slot ``SpecState`` counts proposed/accepted and
adapts K — additive growth on full acceptance, multiplicative back-off
on any rejection — so a slot whose traffic stops drafting well stops
paying verify overhead.

**Tree speculation**: a linear draft bets everything on ONE
continuation; when the radix tree (or the KV tier's spilled chains)
has seen SEVERAL continuations of the slot's suffix, ``TreeDraft``
stacks them into a token trie and verifies every branch in the SAME
single forward. The chunk already pads to ``round_chunk`` rows, so the
extra branches ride in rows a linear draft would have wasted on
padding. A tree-attention mask (additive 0/-1e30 bias threaded down to
the flash kernel) keeps siblings invisible to each other, and each
node ropes at ``kv + depth`` — so an accepted branch's KV rows are
bit-identical to the rows linear decode would have written, and the
commit is a plain row-move (``paged_kv_cache.move_kv_rows``) followed
by the usual kv_len rollback. Acceptance walks the tree root-down
drawing the TARGET token first (argmax, or one per-request subkey per
emitted token — the same key consumption as non-speculative decode)
and descending into the drafted child that matches: the emitted stream
never depends on the tree's shape, so greedy stays bit-identical,
sampling stays exactly distribution-preserving, and seeded replays
stay bit-exact even when the draft source (another request's radix
residue) is not replayable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import sampling
from triton_distributed_tpu.models.paged_kv_cache import gather_bucket
from triton_distributed_tpu.models.prefix_cache import round_chunk
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.runtime.faults import fault_point, mutate_point
from triton_distributed_tpu.runtime.profiling import trace_span


class NGramDraft:
    """Prompt-lookup drafter: an n-gram table over one request's token
    history (prompt + every emitted token).

    For each n in ``[min_ngram, max_ngram]`` the table maps every
    n-gram to its two most recent end positions. Drafting takes the
    history's tail n-gram (longest n first), finds its PREVIOUS
    occurrence, and proposes the tokens that followed it — the
    continuation the sequence used last time it was here.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]"
            )
        self.history: list[int] = []
        # n → {ngram tuple: (latest end pos, previous end pos | None)}
        self._index: dict[int, dict] = {
            n: {} for n in range(min_ngram, max_ngram + 1)
        }

    def observe(self, tokens) -> None:
        """Append ``tokens`` to the history, updating every n-gram's
        latest/previous occurrence incrementally (O(ngrams) per
        token)."""
        for t in tokens:
            self.history.append(int(t))
            end = len(self.history)
            for n, idx in self._index.items():
                if end >= n:
                    key = tuple(self.history[end - n:end])
                    prev = idx.get(key)
                    idx[key] = (end, prev[0] if prev is not None else None)

    def propose(self, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the history's tail, from
        the longest n-gram with a previous occurrence; ``[]`` when
        nothing matches (the caller decodes normally)."""
        end = len(self.history)
        if k <= 0 or end == 0:
            return []
        for n in sorted(self._index, reverse=True):
            if end < n:
                continue
            entry = self._index[n].get(tuple(self.history[end - n:end]))
            if entry is None:
                continue
            # The latest occurrence IS the tail itself; the previous
            # one (if any) carries the continuation.
            pos = entry[1] if entry[0] == end else entry[0]
            if pos is None:
                continue
            cont = self.history[pos:pos + k]
            if cont:
                return list(cont)
        return []


class SpecState:
    """Per-slot speculative state: the drafter plus adaptive draft
    length K and accept/propose counters.

    The K controller tracks ACCEPTED-RUN length rather than classic
    AIMD: a fully accepted draft grows K by 2 (the run was at least as
    long as we dared), a rejection resets K to ``accepted + 1`` (next
    time, dare one past the run we actually got), floored at ``k_min``.
    A rejected verify still emits one token, so over-drafting costs
    only the wasted tail compute of one chunk — the controller's job is
    to bound that waste when acceptance collapses, not to give up
    drafting on the first miss.

    Tree mode adds a WIDTH ledger (``record_tree``): a full-depth
    accept widens the next tree by one branch (cap ``w_max``), a
    zero-accept round narrows it by one — at width 1 the slot is back
    to today's linear chain (or no draft at all when the drafter goes
    quiet), so cold traffic pays nothing for the tree machinery."""

    def __init__(
        self,
        k_max: int,
        *,
        k_min: int = 1,
        max_ngram: int = 3,
        min_ngram: int = 1,
        w_max: int = 1,
    ):
        self.k_max = max(int(k_max), 1)
        self.k_min = max(min(int(k_min), self.k_max), 1)
        self.k = self.k_max
        self.w_max = max(int(w_max), 1)
        self.width = self.w_max
        self.draft = NGramDraft(max_ngram, min_ngram)
        self.proposed = 0
        self.accepted = 0

    def observe(self, tokens) -> None:
        self.draft.observe(tokens)

    def propose(self, budget: int) -> list[int]:
        """Draft up to ``min(current K, budget)`` tokens."""
        return self.draft.propose(min(self.k, int(budget)))

    def record(self, proposed: int, accepted: int) -> None:
        """Fold one verify's outcome into the counters + adaptive K."""
        self.proposed += proposed
        self.accepted += accepted
        if proposed:
            if accepted == proposed:
                self.k = min(self.k + 2, self.k_max)
            else:
                self.k = min(max(accepted + 1, self.k_min), self.k_max)

    def record_tree(self, nodes: int, depth: int, accepted: int) -> None:
        """Fold one TREE verify: ``nodes`` drafted trie nodes (root
        excluded), ``depth`` the deepest drafted path, ``accepted`` the
        accepted path length. K adapts on accepted-vs-depth — the
        per-path analog of the linear rule — and width widens on a
        full-depth accept, narrowing back toward linear when a whole
        tree missed."""
        self.proposed += nodes
        self.accepted += accepted
        if nodes:
            if depth and accepted >= depth:
                self.k = min(self.k + 2, self.k_max)
                self.width = min(self.width + 1, self.w_max)
            else:
                self.k = min(max(accepted + 1, self.k_min), self.k_max)
                if accepted == 0:
                    self.width = max(self.width - 1, 1)

    @property
    def accept_rate(self) -> float:
        return self.accepted / max(self.proposed, 1)


def cap_draft(k: int, kv_len: int, budget: int, max_length: int) -> int:
    """Largest usable draft length this step: at most ``k``, at most
    ``budget - 1`` (the verify emits up to ``draft + 1`` tokens — never
    draft past the generation budget, so verify overshoot can't blow
    the page capacity the admission guard sized), and small enough that
    the PADDED chunk (``round_chunk(draft + 1)``) stays under
    ``max_length`` — pad rows write KV too, and past ``max_length`` the
    table runs out of entries. Returns ``-1`` when not even a
    zero-draft chunk fits (the caller must fall back to a plain decode
    step for this slot)."""
    k = min(int(k), int(budget) - 1)
    while k >= 0 and int(kv_len) + round_chunk(k + 1) > int(max_length):
        k -= 1
    return k


def verify_greedy(logits: np.ndarray, draft: list[int]) -> tuple[int, int]:
    """Greedy acceptance: ``logits [n+1, V]`` are the target's
    per-position outputs for inputs ``[pending, d_1..d_n]``. Accept
    ``d_i`` while it equals the argmax at its position; the token at
    the first mismatch (or the bonus position after a full accept) is
    emitted from the target's own argmax — so the emitted stream is
    exactly what non-speculative greedy decode would produce. Returns
    ``(accepted, next_token)``."""
    preds = np.argmax(logits, axis=-1)
    a = 0
    while a < len(draft) and int(preds[a]) == int(draft[a]):
        a += 1
    return a, int(preds[a])


def verify_sampled(
    logits: np.ndarray,
    draft: list[int],
    key: jax.Array,
    temperature: float,
    top_p: float = 1.0,
    top_k: int = 0,
) -> tuple[int, int, jax.Array]:
    """Distribution-preserving acceptance for ``temperature > 0``.

    With a deterministic (delta) draft proposal, the rejection-sampling
    rule collapses to: accept ``d_i`` with probability ``p_i(d_i)``
    under the filtered target distribution; on rejection, emit a sample
    of the residual ``p_i`` with ``d_i`` zeroed and renormalized. The
    marginal of each emitted token is exactly ``p_i`` — speculative
    sampling changes latency, never the distribution. Returns
    ``(accepted, next_token, key)``.
    """
    n = len(draft)
    probs = np.asarray(
        sampling.target_probs(
            jnp.asarray(logits[: n + 1]), temperature, top_p, top_k
        )
    )
    for i, d in enumerate(draft):
        d = int(d)
        key, sub = jax.random.split(key)
        if float(jax.random.uniform(sub)) < float(probs[i, d]):
            continue
        resid = probs[i].astype(np.float64)
        resid[d] = 0.0
        total = resid.sum()
        key, sub = jax.random.split(key)
        if total <= 0.0:
            # p(d) was numerically 1 yet the draw rejected — the
            # residual is empty; the target distribution IS d's
            # one-hot, so resample it directly.
            nxt = int(
                sampling.sample(
                    jnp.asarray(logits[i]), sub, temperature, top_p, top_k
                )
            )
        else:
            nxt = int(
                jax.random.categorical(sub, jnp.log(jnp.asarray(resid / total)))
            )
        return i, nxt, key
    key, sub = jax.random.split(key)
    bonus = int(
        sampling.sample(jnp.asarray(logits[n]), sub, temperature, top_p, top_k)
    )
    return n, bonus, key


def spec_verify_slot(
    model,
    cache,
    slot: int,
    pending: int,
    draft: list[int],
    kv_len: int,
    mode,
    *,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_p: float = 1.0,
    top_k: int = 0,
):
    """One speculative verify of ``slot``: run ``[pending] + draft``
    through a single chunked paged-prefill forward (per-position
    logits), accept a prefix, and return
    ``(emitted tokens, cache, accepted, key)``. ``emitted`` is None
    when the chunk produced non-finite logits — the returned cache is
    still valid (the old one was donated to the chunk program) and the
    caller must fail this slot's request as ``nan_logits``.

    The chunk program writes KV for every input row and sets the slot's
    device ``kv_len`` to ``kv_len + 1 + len(draft)``; the CALLER owns
    the rollback to ``kv_len + accepted + 1`` (host-authoritative
    engines resync their tables, the fixed-batch engine calls
    ``rollback_kv``). Emitted tokens are ``draft[:accepted]`` plus one
    token from the target's own logits — the correction at the first
    mismatch, or the bonus token after a full accept — so every verify
    emits at least one token.
    """
    fault_point("spec.verify", slot=slot)
    toks = [int(pending)] + [int(d) for d in draft]
    n = len(toks)
    c = round_chunk(n)
    page = int(cache.k_pages.shape[3])
    pps = int(cache.page_table.shape[1])
    buf = np.zeros(c, np.int32)
    buf[:n] = toks
    kv_pages = gather_bucket(int(kv_len) + c, page, pps)
    with trace_span("spec:verify", slot=slot, drafted=len(draft),
                    offset=int(kv_len), _ring=False):
        logits, cache = model.prefill_paged_chunk(
            buf, slot, int(kv_len), int(kv_len) + n, n - 1, cache, mode,
            kv_pages=kv_pages, all_logits=True,
        )
    arr = np.asarray(logits[:n], np.float32)
    arr = mutate_point("spec.logits", arr, slot=slot)
    if not np.isfinite(arr).all():
        # Same contract as the batched-decode guard: never silently
        # argmax/sample a non-finite row (np.argmax over NaN returns
        # index 0). Signalled as ``emitted=None`` rather than raised:
        # the chunk program already consumed (donated) the caller's
        # cache arrays, so the caller MUST receive the new cache to
        # stay serviceable — a raise here would strand it on deleted
        # buffers. Callers map None to a ``nan_logits`` failure of
        # exactly this slot's request.
        return None, cache, 0, key
    if temperature <= 0.0:
        accepted, nxt = verify_greedy(arr, draft)
    else:
        accepted, nxt, key = verify_sampled(
            arr, draft, key, temperature, top_p, top_k
        )
    # One emit site covers both engines (each verify chunk routes
    # through here); rollbacks surface via the spec:rollback span.
    obs_events.emit(
        "spec_verify", slot=slot, drafted=len(draft), accepted=accepted
    )
    emitted = [int(d) for d in draft[:accepted]] + [nxt]
    return emitted, cache, accepted, key


class TreeDraft:
    """A multi-branch draft: a token trie rooted at the slot's pending
    token, flattened in insertion (DFS) order for one verify chunk.

    Node 0 is the ROOT — the pending token the engine was about to feed
    back. Nodes ``1..n-1`` are drafted continuations. Because children
    are appended after their parent, a node's storage index is always
    >= its depth, which is what makes the commit row-move strictly
    leftward (``dst <= src``) and overlap-safe.
    """

    def __init__(self, pending: int):
        self.tokens: list[int] = [int(pending)]
        self.parent: list[int] = [-1]
        self.depth: list[int] = [0]
        self._children: list[dict[int, int]] = [{}]

    def __len__(self) -> int:
        return len(self.tokens)

    @property
    def num_drafted(self) -> int:
        return len(self.tokens) - 1

    @property
    def max_depth(self) -> int:
        return max(self.depth)

    @property
    def is_chain(self) -> bool:
        """True when the trie is a single path (every node has at most
        one child) — the degenerate tree that behaves exactly like a
        linear draft."""
        return all(len(c) <= 1 for c in self._children)

    def chain_tokens(self) -> list[int]:
        """The drafted tokens of a single-path trie, root excluded —
        only meaningful when :attr:`is_chain` holds (insertion order IS
        path order for a chain)."""
        return [int(t) for t in self.tokens[1:]]

    def child(self, node: int, token: int) -> int | None:
        return self._children[node].get(int(token))

    def add_path(self, path, budget: int | None = None) -> int:
        """Insert one candidate continuation below the root, sharing
        any already-inserted prefix (siblings merge by token, so
        overlapping proposals from the radix tree, the KV tier, and the
        n-gram drafter dedup for free). Stops growing when ``budget``
        total nodes would be exceeded. Returns nodes added."""
        cur = 0
        added = 0
        for t in path:
            t = int(t)
            nxt = self._children[cur].get(t)
            if nxt is None:
                if budget is not None and len(self.tokens) >= budget:
                    break
                nxt = len(self.tokens)
                self.tokens.append(t)
                self.parent.append(cur)
                self.depth.append(self.depth[cur] + 1)
                self._children.append({})
                self._children[cur][t] = nxt
                added += 1
            cur = nxt
        return added

    def mask(self, c: int) -> np.ndarray:
        """The ``[c, c]`` additive attention bias for a ``c``-row
        chunk: row ``i`` sees column ``j`` (bias 0) iff ``j`` is an
        ancestor-of-or-equal-to ``i``; everything else gets -1e30. Pad
        rows ``i >= n`` get plain causal rows — their outputs are
        garbage either way (always rolled back), a causal row just
        keeps them shaped like ordinary prefill padding. Columns
        OUTSIDE the chunk (the committed prefix) are the caller's
        business: the model layer extends the bias with zeros there, so
        every row keeps the committed history visible."""
        n = len(self.tokens)
        m = np.full((c, c), -1e30, np.float32)
        for i in range(n):
            j = i
            while j >= 0:
                m[i, j] = 0.0
                j = self.parent[j]
        for i in range(n, c):
            m[i, : i + 1] = 0.0
        return m

    def depths(self, c: int) -> np.ndarray:
        """Per-row rope depth for a ``c``-row chunk: node ``i`` ropes
        at ``kv + depth[i]`` — the position linear decode would have
        used — so an accepted branch's KV rows are bit-identical to
        linearly-written ones and the commit can be a pure row-move.
        Pad rows rope at their storage index, same as ordinary
        prefill."""
        n = len(self.tokens)
        return np.asarray(self.depth + list(range(n, c)), np.int32)


def verify_tree_greedy(
    logits: np.ndarray, tree: TreeDraft
) -> tuple[list[int], list[int]]:
    """Greedy tree acceptance: walk from the root, at each node taking
    the TARGET's argmax for that node's prefix and descending into the
    drafted child carrying that token, if any. Every emitted token is
    the target's own argmax — bit-identical to non-speculative greedy
    decode regardless of what was drafted. Returns ``(path, emitted)``:
    the accepted node indices root-down (root excluded) and their
    tokens plus the final correction/bonus argmax."""
    preds = np.argmax(logits, axis=-1)
    path: list[int] = []
    emitted: list[int] = []
    cur = 0
    while True:
        t = int(preds[cur])
        emitted.append(t)
        nxt = tree.child(cur, t)
        if nxt is None:
            return path, emitted
        path.append(nxt)
        cur = nxt


def verify_tree_sampled(
    logits: np.ndarray,
    tree: TreeDraft,
    next_key,
    temperature: float,
    top_p: float = 1.0,
    top_k: int = 0,
) -> tuple[list[int], list[int]]:
    """Distribution-preserving tree acceptance: sample-then-match.

    At each node the TARGET token is drawn first — ``sampling.sample``
    under the node's filtered distribution with one fresh subkey from
    ``next_key()`` per EMITTED token, the exact key consumption of
    non-speculative decode — and the walk descends into the drafted
    child carrying that token, if any. Each emitted token is therefore
    an ancestral sample of the target's own filtered distribution for
    its own prefix: the emitted stream's law is EXACTLY the
    non-speculative one (no residual renormalization to get wrong) and
    it does not depend on the draft tree's shape — which is what keeps
    seeded replays bit-exact across migration even though the tree was
    built from a non-replayable source (another request's radix
    residue). The branch-acceptance probability at a node with drafted
    children ``C`` is ``sum_{c in C} p(c)`` — the multi-branch
    generalization of the linear delta-proposal accept rule. Returns
    ``(path, emitted)`` as :func:`verify_tree_greedy`."""
    path: list[int] = []
    emitted: list[int] = []
    cur = 0
    while True:
        t = int(
            sampling.sample(
                jnp.asarray(logits[cur]), next_key(), temperature, top_p, top_k
            )
        )
        emitted.append(t)
        nxt = tree.child(cur, t)
        if nxt is None:
            return path, emitted
        path.append(nxt)
        cur = nxt


def spec_verify_tree(
    model,
    cache,
    slot: int,
    tree: TreeDraft,
    kv_len: int,
    mode,
    *,
    next_key=None,
    temperature: float = 0.0,
    top_p: float = 1.0,
    top_k: int = 0,
):
    """One TREE verify of ``slot``: every trie node runs through a
    single chunked paged-prefill forward under the tree-attention mask
    and depth-rope, then the sample/argmax-then-match walk accepts one
    root path. Returns ``(emitted tokens, cache, path)``; ``emitted``
    is None on non-finite logits (same donated-cache contract as
    :func:`spec_verify_slot`, caller fails the slot as ``nan_logits``).

    The chunk writes KV for every node at ``kv + storage index`` and
    advances the slot's device kv_len past the whole chunk; the CALLER
    commits the accepted path with :func:`commit_tree_path` and then
    rolls kv_len back to ``kv + len(path) + 1`` exactly as in the
    linear path.
    """
    fault_point("spec.verify", slot=slot)
    n = len(tree)
    c = round_chunk(n)
    page = int(cache.k_pages.shape[3])
    pps = int(cache.page_table.shape[1])
    buf = np.zeros(c, np.int32)
    buf[:n] = tree.tokens
    kv_pages = gather_bucket(int(kv_len) + c, page, pps)
    with trace_span("spec:tree", slot=slot, nodes=tree.num_drafted,
                    depth=tree.max_depth, offset=int(kv_len), _ring=False):
        logits, cache = model.prefill_paged_chunk(
            buf, slot, int(kv_len), int(kv_len) + n, n - 1, cache, mode,
            kv_pages=kv_pages, all_logits=True,
            tree_mask=tree.mask(c), tree_depth=tree.depths(c),
        )
    arr = np.asarray(logits[:n], np.float32)
    arr = mutate_point("spec.logits", arr, slot=slot)
    if not np.isfinite(arr).all():
        return None, cache, []
    if temperature <= 0.0:
        path, emitted = verify_tree_greedy(arr, tree)
    else:
        path, emitted = verify_tree_sampled(
            arr, tree, next_key, temperature, top_p, top_k
        )
    obs_events.emit(
        "spec_verify", slot=slot, drafted=tree.num_drafted,
        accepted=len(path), tree=True,
    )
    return emitted, cache, path


def commit_tree_path(cache, slot: int, kv_len: int, path: list[int]):
    """Commit an accepted root path: row-move the accepted nodes' KV
    from their DFS storage slots (``kv + node index``) to the
    contiguous positions linear decode would have written
    (``kv+1..kv+len(path)``). DFS order guarantees ``index >= depth``
    so every move is leftward; ``move_kv_rows`` skips self-moves, so a
    primary-branch (already contiguous) accept is a no-op. The caller
    still owns the kv_len rollback afterwards."""
    from triton_distributed_tpu.models.paged_kv_cache import move_kv_rows

    src = [int(kv_len) + int(i) for i in path]
    dst = [int(kv_len) + j for j in range(1, len(path) + 1)]
    return move_kv_rows(cache, slot, src, dst)
