"""Models + serving (parity: reference ``python/triton_dist/models/``).

``AutoLLM`` mirrors ``models/__init__.py:32-48`` — dispatch by model
name/config to Qwen3 dense or MoE, loading HF weights when a checkpoint
directory is given and random-initializing otherwise (the reference's
perf scripts also run on random weights).
"""

from __future__ import annotations

import json
import os

import jax

from triton_distributed_tpu.models.config import ModelConfig, get_config  # noqa: F401
from triton_distributed_tpu.models.continuous import (  # noqa: F401
    ContinuousEngine,
    Request,
    RequestError,
    RequestFailedError,
    RequestResult,
)
from triton_distributed_tpu.models.engine import Engine  # noqa: F401
from triton_distributed_tpu.models.paged_kv_cache import (  # noqa: F401
    PoolAuditError,
    audit_pool,
)
from triton_distributed_tpu.models.kv_cache import KVCache, init_cache  # noqa: F401
from triton_distributed_tpu.models.prefix_cache import (  # noqa: F401
    PrefixCache,
)
from triton_distributed_tpu.models.speculative import (  # noqa: F401
    NGramDraft,
    SpecState,
)
from triton_distributed_tpu.models.qwen import (  # noqa: F401
    Qwen3,
    Qwen3Params,
    load_hf_state_dict,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class AutoLLM:
    """Parity: ``AutoLLM.from_pretrained`` (reference
    ``models/__init__.py:32-48``)."""

    @staticmethod
    def from_pretrained(
        name_or_path: str,
        *,
        ctx: DistContext | None = None,
        axis: str = "tp",
        seed: int = 0,
        **overrides,
    ) -> Qwen3:
        ctx = ctx or current_context()
        if os.path.isdir(name_or_path):
            cfg, state = _load_hf_checkpoint(name_or_path, **overrides)
            n = ctx.axis_size(axis)
            if cfg.num_experts:
                from triton_distributed_tpu.models.qwen_moe import (
                    Qwen3MoE,
                    load_hf_moe_state_dict,
                )

                model = Qwen3MoE(cfg, axis=axis, ctx=ctx)
                model.set_params(load_hf_moe_state_dict(cfg, state, n))
                return model
            model = Qwen3(cfg, axis=axis, ctx=ctx)
            model.set_params(load_hf_state_dict(cfg, state, n))
            return model
        cfg = get_config(name_or_path, **overrides)
        if cfg.num_experts:
            from triton_distributed_tpu.models.qwen_moe import Qwen3MoE

            model = Qwen3MoE(cfg, axis=axis, ctx=ctx)
        else:
            model = Qwen3(cfg, axis=axis, ctx=ctx)
        model.init_params(jax.random.key(seed))
        return model


def _load_hf_checkpoint(path: str, **overrides):
    """Read config.json + *.safetensors from a local HF checkpoint dir."""
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    cfg = ModelConfig(
        model_name=hf.get("_name_or_path", path),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_q_heads=hf["num_attention_heads"],
        num_kv_heads=hf["num_key_value_heads"],
        head_dim=hf.get(
            "head_dim", hf["hidden_size"] // hf["num_attention_heads"]
        ),
        rope_theta=hf.get("rope_theta", 1e6),
        rms_eps=hf.get("rms_norm_eps", 1e-6),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        # MoE checkpoints (Qwen3MoeForCausalLM): presence of experts in
        # the config routes to the MoE model + state-dict mapper.
        num_experts=hf.get("num_experts", 0),
        num_experts_per_tok=hf.get("num_experts_per_tok", 0),
        moe_intermediate_size=hf.get("moe_intermediate_size", 0),
        # Follow the checkpoint (HF default is FALSE; official Qwen3-MoE
        # releases set it true) — assuming true silently renormalizes
        # router weights upstream leaves unnormalized.
        norm_topk_prob=hf.get("norm_topk_prob", False),
        **overrides,
    )
    if hf.get("num_experts", 0):
        # Interleaved dense/sparse layers (decoder_sparse_step > 1 or
        # mlp_only_layers) store real dense MLP weights for some layers;
        # the uniform-sparse mapper would clobber them with placeholders
        # and then fail on a cryptic missing-router KeyError. Refuse
        # loudly instead. (The shipped Qwen3-MoE checkpoints are
        # uniformly sparse: step=1, mlp_only_layers=[].)
        step = hf.get("decoder_sparse_step", 1)
        dense_layers = hf.get("mlp_only_layers") or []
        if step != 1 or dense_layers:
            raise NotImplementedError(
                "interleaved dense/sparse MoE checkpoints are not "
                f"supported (decoder_sparse_step={step}, "
                f"mlp_only_layers={dense_layers}); only uniformly "
                "sparse Qwen3-MoE layouts load"
            )
    from safetensors import safe_open

    state = {}
    for fname in sorted(os.listdir(path)):
        if fname.endswith(".safetensors"):
            with safe_open(os.path.join(path, fname), framework="np") as f:
                for key in f.keys():
                    state[key] = f.get_tensor(key)
    if not state:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    return cfg, state
