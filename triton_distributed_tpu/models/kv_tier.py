"""Durable KV tier: host-RAM/disk page store behind the radix tree.

At production scale the shared-prefix population (system prompts,
few-shot templates, long documents) is far bigger than any HBM pool;
until this tier existed, ``PrefixCache.evict_until`` dropped cold pages
to nothing (evicted prefixes silently re-prefilled) and the crash
recovery state of PRs 9–10 (``ContinuousEngine._snapshots``,
``FleetSupervisor._snaps``) lived only in process memory — a supervisor
restart forfeited every in-flight request's snapshot. This module is
the capacity-bounded store both problems spill into
(docs/serving.md "Tiered KV", docs/scale-out.md "Durable snapshots"):

- **Host-RAM tier**: an LRU of encoded entries bounded by
  ``capacity_bytes``. Entries are stored as their WIRE bytes (header +
  checksummed body), so RAM corruption is as detectable as disk
  corruption and the fault seams mutate one representation.
- **Optional disk tier** (``dir=``): write-through, one file per entry,
  atomic write-then-rename (a crash mid-write can only leave a ``.tmp``
  sibling, never a half entry). Entries evicted from the RAM LRU stay
  readable from disk; a fresh process over the same ``dir`` sees every
  durable entry (the supervisor-restart path).
- **Two entry kinds**: ``prefix`` pages keyed by token-chain digest
  (:func:`chain_digest`) carrying one radix page's KV payload
  (``gather_pages``/``write_page`` byte-exact, int8 codes + per-page
  scales as a pair), and ``snap`` slot snapshots keyed by ticket id
  carrying the ``models/slot_state.py`` wire dict.
- **Integrity-checked fault-back**: every entry rides a version header
  + CRC32 over the body. A checksum mismatch, truncated file, wrong
  magic, or key mismatch NEVER yields wrong bits — the entry is
  dropped (counter + ``tier_drop`` event) and :meth:`PageStore.get`
  returns None, so the caller degrades to re-prefill / replay.
- **Fault seams** (``runtime/faults.py``): ``tier.put`` / ``tier.get``
  can refuse (raise-style), corrupt (mutate-style — caught by the
  checksum), or slow (delay rule). Both are containment boundaries:
  an injected failure degrades the tier, never the request.

Everything here is host-side and zero-jax: payload arrays ride the
``models/slot_state.py`` base64 wire codec, so tier entries are the
same line-JSON-safe dicts the migration wire already speaks.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import threading
import time
import zlib
from collections import OrderedDict

import numpy as np

from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.runtime.faults import mutate_point

TIER_VERSION = 1
_MAGIC = b"TDT1"

PREFIX_KIND = "prefix"
SNAP_KIND = "snap"
# Cold pages of a LIVE sharded long-context slot (docs/serving.md
# "Long-context serving"). Entries are per-page ``prefix_payload``
# dicts keyed "<uid>:<page-index>"; they belong to exactly one running
# request and are deleted at its teardown, so the disk prune (which
# only bounds PREFIX/SNAP) never reaps a page a live decode still
# needs.
LONGCTX_KIND = "longctx"


class TierIntegrityError(RuntimeError):
    """An entry's bytes failed the header/checksum validation — the
    payload cannot be trusted and must be dropped, never decoded into
    KV bits."""


def chain_digest(tokens) -> str:
    """Stable digest of an exact token chain — the ``prefix`` entry
    key. One digest per chain: a spilled radix page is keyed by the
    FULL chain from the root through its own chunk, so fault-back can
    probe page-by-page while walking a new prompt."""
    return hashlib.sha1(
        np.asarray([int(t) for t in tokens], np.int64).tobytes()
    ).hexdigest()


def request_digest(prompt, gen_len: int) -> str:
    """Digest identifying a request's (prompt, gen_len) — how the
    supervisor's restart-resume store matches a re-submitted request to
    a crash-leftover snapshot (ticket ids do not survive a restart)."""
    h = hashlib.sha1()
    h.update(f"g{int(gen_len)}:".encode())
    h.update(np.asarray([int(t) for t in prompt], np.int64).tobytes())
    return h.hexdigest()


# -- prefix-page payload codec --------------------------------------------
#
# One radix page's content as a line-JSON-safe dict, arrays riding the
# slot_state base64 codec (the SAME codec migration snapshots use — one
# array wire format in the repo). ``chain`` is the page's full token
# chain (a page_size multiple; the page holds chain[-page_size:]), kept
# IN the payload so fault-back can verify the digest didn't collide and
# the audit can cross-check key ↔ chain consistency.


def prefix_payload(chain, page_size: int, kv_dtype: str | None,
                   k_page, v_page, k_scale=None, v_scale=None) -> dict:
    from triton_distributed_tpu.models.slot_state import _arr_to_wire

    return {
        "chain": [int(t) for t in chain],
        "page_size": int(page_size),
        "kv_dtype": kv_dtype,
        "k": _arr_to_wire(np.asarray(k_page)),
        "v": _arr_to_wire(np.asarray(v_page)),
        "ks": None if k_scale is None else _arr_to_wire(np.asarray(k_scale)),
        "vs": None if v_scale is None else _arr_to_wire(np.asarray(v_scale)),
    }


def decode_prefix_payload(payload: dict):
    """``(chain, page_size, kv_dtype, k, v, ks, vs)`` from a ``prefix``
    entry; raises :class:`TierIntegrityError` on any malformed field
    (the caller drops the entry and re-prefills)."""
    from triton_distributed_tpu.models.slot_state import (
        SnapshotError,
        _arr_from_wire,
    )

    try:
        chain = [int(t) for t in payload["chain"]]
        page_size = int(payload["page_size"])
        kv_dtype = payload.get("kv_dtype")
        k = _arr_from_wire(payload["k"])
        v = _arr_from_wire(payload["v"])
        ks = _arr_from_wire(payload.get("ks"))
        vs = _arr_from_wire(payload.get("vs"))
    except (KeyError, TypeError, ValueError, SnapshotError) as e:
        raise TierIntegrityError(
            f"malformed prefix payload: {type(e).__name__}: {e}"
        ) from e
    if k is None or v is None:
        raise TierIntegrityError("prefix payload missing page arrays")
    return chain, page_size, kv_dtype, k, v, ks, vs


def payload_nbytes(payload: dict) -> int:
    """Approximate payload size (the base64 blobs dominate) — what the
    engine's ``tier_bytes`` counter accumulates per fault-back."""
    total = 0
    for v in payload.values():
        if isinstance(v, dict) and "b64" in v:
            total += len(v["b64"])
    return total


# -- entry wire format ----------------------------------------------------


def _encode(kind: str, key: str, payload: dict) -> bytes:
    body = json.dumps(payload, separators=(",", ":")).encode()
    head = json.dumps({
        "v": TIER_VERSION, "kind": kind, "key": key,
        "len": len(body), "crc": zlib.crc32(body),
    }, separators=(",", ":")).encode()
    return _MAGIC + head + b"\n" + body


def _decode(kind: str, key: str, blob: bytes) -> dict:
    """Validate + decode one entry blob; raises
    :class:`TierIntegrityError` on wrong magic, unparseable or
    mismatched header, truncation, or a CRC mismatch."""
    if not blob.startswith(_MAGIC):
        raise TierIntegrityError("bad magic (not a tier entry)")
    head_raw, sep, body = blob[len(_MAGIC):].partition(b"\n")
    if not sep:
        raise TierIntegrityError("truncated entry (no header terminator)")
    try:
        head = json.loads(head_raw)
    except ValueError as e:
        raise TierIntegrityError(f"unparseable header: {e}") from e
    if head.get("v") != TIER_VERSION:
        raise TierIntegrityError(f"version mismatch: {head.get('v')!r}")
    if head.get("kind") != kind or head.get("key") != key:
        raise TierIntegrityError(
            f"entry is ({head.get('kind')!r}, {head.get('key')!r}), "
            f"expected ({kind!r}, {key!r})"
        )
    if len(body) != head.get("len"):
        raise TierIntegrityError(
            f"truncated body: {len(body)} != {head.get('len')}"
        )
    if zlib.crc32(body) != head.get("crc"):
        raise TierIntegrityError("checksum mismatch")
    try:
        return json.loads(body)
    except ValueError as e:  # crc passed but json broke: still contained
        raise TierIntegrityError(f"unparseable body: {e}") from e


class PageStore:
    """Capacity-bounded host-RAM tier with an optional write-through
    disk tier (see module docstring). Thread-safe: the engine's
    admission path, the supervisor's monitor thread, and router worker
    threads all touch one store."""

    def __init__(self, capacity_bytes: int = 64 << 20,
                 dir: str | None = None,  # noqa: A002 — the public knob name
                 disk_capacity_bytes: int | None = None,
                 fsync: bool = True):
        self.capacity_bytes = int(capacity_bytes)
        self.dir = dir
        # fsync=False trades power-loss durability for write latency:
        # the atomic rename still makes every entry visible whole to a
        # RESTARTED process (page cache survives a process crash), and
        # an OS crash can only tear an entry the CRC then drops —
        # degrade to re-prefill/replay, never wrong bits. The engine's
        # snapshot write-through runs on the scheduling loop and picks
        # this; the supervisor's resume store keeps the default.
        self.fsync = bool(fsync)
        self.disk_capacity_bytes = (
            None if disk_capacity_bytes is None else int(disk_capacity_bytes)
        )
        if dir:
            os.makedirs(dir, exist_ok=True)
        self._ram: "OrderedDict[tuple[str, str], bytes]" = OrderedDict()
        self._ram_bytes = 0
        self._lock = threading.RLock()
        # Memo for :meth:`resident_chains`: (mutation counter, chains).
        # Every RAM-membership change bumps ``_mut``, invalidating it —
        # the tree-speculation drafter calls resident_chains once per
        # verify round, and re-decoding every header each time would
        # put a JSON parse loop on the decode path.
        self._mut = 0
        self._chain_memo: tuple[int, list[list[int]]] | None = None
        self._digest_memo: tuple[int, dict] | None = None
        # Monotone per-kind non-emptiness flags (see :meth:`may_contain`):
        # one listdir at construction counts entries a PRIOR process
        # left on disk; every successful put flips the flag for good.
        self._kind_seen: dict[str, bool] = {
            PREFIX_KIND: False, SNAP_KIND: False,
        }
        if dir:
            for kd in (PREFIX_KIND, SNAP_KIND):
                try:
                    self._kind_seen[kd] = any(
                        n.endswith(".tier")
                        for n in os.listdir(os.path.join(dir, kd))
                    )
                except OSError:
                    pass
        self.stats = {
            "puts": 0,
            "put_bytes": 0,
            "hits": 0,
            "disk_hits": 0,
            "misses": 0,
            "evictions": 0,       # RAM LRU evictions (disk copy survives)
            "disk_evictions": 0,  # disk-bound prunes — permanent deletions
            "drops": 0,       # integrity failures — entry removed
            "refused": 0,     # puts refused (fault seam / oversized)
            "errors": 0,      # I/O or injected get errors (degraded)
        }
        # Resolved ONCE (the engine `_metric_handles` convention).
        self._m_drops = obs_metrics.counter(
            "tdt_tier_drops_total",
            "Tier entries dropped on integrity failure (checksum / "
            "truncation / header mismatch) — degraded to re-prefill "
            "or replay, never wrong bits.",
        )
        self._m_evictions = obs_metrics.counter(
            "tdt_tier_store_evictions_total",
            "Entries LRU-evicted from the tier's RAM capacity (the "
            "disk copy, when a disk tier is attached, survives).",
        )
        self._m_disk_evictions = obs_metrics.counter(
            "tdt_tier_disk_evictions_total",
            "Entries pruned from the disk tier's byte bound — "
            "PERMANENT deletions, unlike RAM evictions.",
        )
        # Last-write-wins and UNLABELED like tdt_engine_free_pages
        # (one store per serving process is the deployment shape).
        self._g_bytes = obs_metrics.gauge(
            "tdt_tier_ram_bytes", "Bytes held by the tier's RAM LRU.",
        )

    # -- paths -------------------------------------------------------------

    def _path(self, kind: str, key: str) -> str:
        # Filenames are key digests (keys may hold '/'); the header's
        # embedded key is what guards against digest collisions.
        name = hashlib.sha1(key.encode()).hexdigest() + ".tier"
        return os.path.join(self.dir, kind, name)

    # -- write -------------------------------------------------------------

    def put(self, kind: str, key: str, payload: dict) -> bool:
        """Store one entry; returns False when refused (injected fault,
        payload larger than the whole RAM capacity) — the caller treats
        a refused spill exactly like the pre-tier drop-to-nothing."""
        try:
            blob = _encode(kind, key, payload)
            blob = mutate_point("tier.put", blob, kind=kind, key=key)
        except Exception:  # noqa: BLE001 — containment boundary
            with self._lock:
                self.stats["refused"] += 1
            return False
        if len(blob) > self.capacity_bytes:
            with self._lock:
                self.stats["refused"] += 1
            return False
        with self._lock:
            self._ram_insert(kind, key, blob)
            self.stats["puts"] += 1
            self.stats["put_bytes"] += len(blob)
            self._kind_seen[kind] = True
        if self.dir:
            self._disk_write(kind, key, blob)
        return True

    def _ram_insert(self, kind: str, key: str, blob: bytes) -> None:
        """Insert into the RAM LRU and evict down to capacity. Caller
        holds ``_lock``. Pops any entry a concurrent promote/put landed
        first — blind insertion would double-count its bytes in the
        ledger (the store may be SHARED across replicas)."""
        old = self._ram.pop((kind, key), None)
        if old is not None:
            self._ram_bytes -= len(old)
        self._ram[(kind, key)] = blob
        self._ram_bytes += len(blob)
        self._mut += 1
        while self._ram_bytes > self.capacity_bytes and len(self._ram) > 1:
            _, evicted = self._ram.popitem(last=False)
            self._ram_bytes -= len(evicted)
            self._mut += 1
            self.stats["evictions"] += 1
            self._m_evictions.inc()
        self._g_bytes.set(self._ram_bytes)

    def _disk_write(self, kind: str, key: str, blob: bytes) -> None:
        path = self._path(kind, key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers see old or new, never half
        except OSError:
            with self._lock:
                self.stats["errors"] += 1
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        if self.disk_capacity_bytes is not None:
            self._disk_prune()

    def _disk_prune(self) -> None:
        """LRU-by-mtime prune of the disk tier to its byte bound."""
        entries = []
        total = 0
        for kind in (PREFIX_KIND, SNAP_KIND):
            d = os.path.join(self.dir, kind)
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if not name.endswith(".tier"):
                    continue
                p = os.path.join(d, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
                total += st.st_size
        entries.sort()
        for _, size, p in entries:
            if total <= self.disk_capacity_bytes:
                break
            try:
                os.unlink(p)
                total -= size
                with self._lock:
                    self.stats["disk_evictions"] += 1
                self._m_disk_evictions.inc()
            except OSError:
                pass

    # -- read --------------------------------------------------------------

    def get(self, kind: str, key: str) -> dict | None:
        """Fetch + integrity-check one entry. None on miss, on an
        injected/real read error, or on ANY integrity failure (the
        entry is then dropped everywhere and counted) — wrong bits can
        never come out of this method."""
        src = "ram"
        with self._lock:
            blob = self._ram.get((kind, key))
            if blob is not None:
                self._ram.move_to_end((kind, key))
        if blob is None and self.dir:
            src = "disk"
            try:
                with open(self._path(kind, key), "rb") as f:
                    blob = f.read()
            except FileNotFoundError:
                blob = None
            except OSError:
                with self._lock:
                    self.stats["errors"] += 1
                return None
        if blob is None:
            with self._lock:
                self.stats["misses"] += 1
            return None
        try:
            blob = mutate_point("tier.get", blob, kind=kind, key=key)
        except Exception:  # noqa: BLE001 — injected refusal: the entry
            with self._lock:  # itself is fine, degrade as a transient miss
                self.stats["errors"] += 1
            return None
        try:
            payload = _decode(kind, key, blob)
        except Exception as e:  # noqa: BLE001 — containment boundary
            self._drop(kind, key, f"{type(e).__name__}: {e}")
            return None
        with self._lock:
            self.stats["hits"] += 1
            if src == "disk":
                self.stats["disk_hits"] += 1
                # Promote: the RAM front absorbs the next lookup.
                self._ram_insert(kind, key, blob)
        return payload

    def peek(self, kind: str, key: str) -> dict | None:
        """Decode an entry WITHOUT stats, LRU movement, fault seams, or
        drop-on-failure — the audit's read path. None when absent or
        unreadable."""
        with self._lock:
            blob = self._ram.get((kind, key))
        if blob is None and self.dir:
            try:
                with open(self._path(kind, key), "rb") as f:
                    blob = f.read()
            except OSError:
                return None
        if blob is None:
            return None
        try:
            return _decode(kind, key, blob)
        except TierIntegrityError:
            return None

    def contains(self, kind: str, key: str) -> bool:
        """Membership WITHOUT decode, stats, or LRU movement — the
        fabric's ``tier_probe`` answer. A True here is advisory (the
        entry may still fail its checksum at pull time); a False is
        authoritative for this instant."""
        with self._lock:
            if (kind, key) in self._ram:
                return True
        if self.dir:
            return os.path.exists(self._path(kind, key))
        return False

    def get_blob(self, kind: str, key: str) -> bytes | None:
        """One entry's raw WIRE bytes (header + checksummed body),
        verbatim — the fabric's ``tier_get`` serve side. No decode, no
        stats, no LRU movement, no fault seams: validation is the
        PULLER's job (:func:`_decode` at the far end), so the PR 12
        codec is the transport and a garbled blob CRC-drops there
        exactly like a corrupt local entry."""
        with self._lock:
            blob = self._ram.get((kind, key))
            if blob is not None:
                return blob
        if self.dir:
            try:
                with open(self._path(kind, key), "rb") as f:
                    return f.read()
            except OSError:
                return None
        return None

    def _drop(self, kind: str, key: str, reason: str) -> None:
        """Remove a failed entry from BOTH tiers: the bits are suspect
        wherever they live, and leaving them would re-fail every later
        lookup of a key the caller now believes absent."""
        with self._lock:
            blob = self._ram.pop((kind, key), None)
            if blob is not None:
                self._ram_bytes -= len(blob)
                self._mut += 1
                self._g_bytes.set(self._ram_bytes)
            self.stats["drops"] += 1
        self._m_drops.inc()
        if self.dir:
            try:
                os.unlink(self._path(kind, key))
            except OSError:
                pass
        obs_events.emit(
            "tier_drop", tier_kind=kind, key=str(key)[:64],
            reason=str(reason)[:160],
        )

    # -- management --------------------------------------------------------

    def delete(self, kind: str, key: str) -> None:
        with self._lock:
            blob = self._ram.pop((kind, key), None)
            if blob is not None:
                self._ram_bytes -= len(blob)
                self._mut += 1
                self._g_bytes.set(self._ram_bytes)
        if self.dir:
            try:
                os.unlink(self._path(kind, key))
            except OSError:
                pass

    def clear(self, kind: str | None = None) -> int:
        """Drop every entry (of ``kind``, or all) from both tiers — the
        supervisor's clean-shutdown path (a drained fleet has no
        in-flight snapshots worth resuming). Returns entries removed."""
        removed = 0
        with self._lock:
            for k in [k for k in self._ram if kind is None or k[0] == kind]:
                self._ram_bytes -= len(self._ram.pop(k))
                removed += 1
            if removed:
                self._mut += 1
            self._g_bytes.set(self._ram_bytes)
        if self.dir:
            for kd in (PREFIX_KIND, SNAP_KIND):
                if kind is not None and kd != kind:
                    continue
                d = os.path.join(self.dir, kd)
                if not os.path.isdir(d):
                    continue
                for name in os.listdir(d):
                    if name.endswith(".tier"):
                        try:
                            os.unlink(os.path.join(d, name))
                            removed += 1
                        except OSError:
                            pass
        return removed

    def may_contain(self, kind: str) -> bool:
        """Cheap monotone emptiness guard: False only while the store
        has NEVER held an entry of ``kind`` — neither this process nor
        (with a disk tier) a prior one over the same dir. Hot paths use
        it to skip per-request digest hashing against a provably-empty
        tier (the admission loop's ``_tier_fill``). Deletes never reset
        it: conservative — may over-probe, never under-probes."""
        return self._kind_seen.get(kind, True)

    def keys(self, kind: str) -> list[str]:
        """Every live key of ``kind`` (RAM ∪ disk). Disk filenames are
        key digests, so the key is read from each entry's header —
        unreadable files are skipped (a later ``get`` would drop them)."""
        out = {k for (kd, k) in self._ram if kd == kind}
        if self.dir:
            d = os.path.join(self.dir, kind)
            if os.path.isdir(d):
                for name in os.listdir(d):
                    if not name.endswith(".tier"):
                        continue
                    try:
                        with open(os.path.join(d, name), "rb") as f:
                            blob = f.read()
                        head_raw, sep, _ = blob[len(_MAGIC):].partition(b"\n")
                        if not blob.startswith(_MAGIC) or not sep:
                            continue
                        key = json.loads(head_raw).get("key")
                        if isinstance(key, str):
                            out.add(key)
                    except (OSError, ValueError):
                        continue
        return sorted(out)

    def resident_chains(self) -> list[list[int]]:
        """Token chains of the RAM-resident ``prefix`` entries — the
        population the tree-speculation drafter scans for continuations
        of a slot's history whose KV was evicted from the radix tree
        but survives in this tier (``PrefixCache.propose_continuations``
        ``tier_chains=``). Header-only decode: the payload arrays stay
        base64; only the chain list is parsed. Memoized until the RAM
        membership mutates, no stats / LRU movement / fault seams (a
        draft read must never perturb the tier), disk-only entries are
        deliberately out of scope (scanning a directory per verify
        round is not a decode-path cost)."""
        with self._lock:
            memo = self._chain_memo
            if memo is not None and memo[0] == self._mut:
                return memo[1]
            mut = self._mut
            blobs = [
                blob for (kd, _), blob in self._ram.items()
                if kd == PREFIX_KIND
            ]
        chains: list[list[int]] = []
        for blob in blobs:
            try:
                _, sep, body = blob[len(_MAGIC):].partition(b"\n")
                if not blob.startswith(_MAGIC) or not sep:
                    continue
                chain = json.loads(body).get("chain")
            except ValueError:
                continue  # a later get() integrity-drops it
            if isinstance(chain, list) and chain:
                chains.append([int(t) for t in chain])
        with self._lock:
            if self._mut == mut:
                self._chain_memo = (mut, chains)
        return chains

    def digest(self) -> dict:
        """Compact content summary for fleet placement and peer
        fault-back: ``{"hash", "counts", "chains"}`` where ``chains``
        is the sorted 16-hex truncations of the RAM-resident ``prefix``
        chain digests (the keys ARE chain digests), ``counts`` the
        per-kind RAM entry counts, and ``hash`` a digest of the chain
        set (cheap change detection for publishers). Memoized on the
        same mutation counter as :meth:`resident_chains` — replicas
        publish it at every batch boundary without re-scanning. RAM
        scope only: disk-resident entries still answer ``tier_probe``,
        but a directory walk per publish is not a batch-boundary cost.
        Truncated digests can collide harmlessly — placement scores and
        probe gating degrade to an extra probe; actual pulls re-key on
        FULL digests and re-validate the payload chain."""
        with self._lock:
            memo = self._digest_memo
            if memo is not None and memo[0] == self._mut:
                return memo[1]
            counts: dict[str, int] = {}
            chains: list[str] = []
            for (kd, key) in self._ram:
                counts[kd] = counts.get(kd, 0) + 1
                if kd == PREFIX_KIND:
                    chains.append(key[:16])
            chains.sort()
            out = {
                "hash": hashlib.sha1(
                    "\n".join(chains).encode()
                ).hexdigest()[:16],
                "counts": counts,
                "chains": chains,
            }
            self._digest_memo = (self._mut, out)
            return out

    @property
    def ram_bytes(self) -> int:
        with self._lock:
            return self._ram_bytes

    def snapshot(self) -> dict:
        """Counters + occupancy for ``last_stats["tier"]`` and the
        bench."""
        with self._lock:
            out = dict(self.stats)
            out["ram_bytes"] = self._ram_bytes
            out["ram_entries"] = len(self._ram)
        out["capacity_bytes"] = self.capacity_bytes
        out["dir"] = self.dir
        return out

    def audit(self) -> list[str]:
        """Structural invariants over the RAM tier (disk entries are
        verified on every ``get``): every blob decodes under its own
        (kind, key), prefix entries' chain matches their digest key,
        and the byte ledger matches the blobs held. Returns violation
        strings (empty == clean)."""
        problems: list[str] = []
        with self._lock:
            items = list(self._ram.items())
            ram_bytes = self._ram_bytes
        total = 0
        for (kind, key), blob in items:
            total += len(blob)
            try:
                payload = _decode(kind, key, blob)
            except TierIntegrityError as e:
                problems.append(f"entry ({kind}, {key[:16]}…): {e}")
                continue
            if kind == PREFIX_KIND:
                chain = payload.get("chain")
                if not isinstance(chain, list) or chain_digest(chain) != key:
                    problems.append(
                        f"prefix entry {key[:16]}…: digest key does not "
                        "match its payload token chain"
                    )
        if total != ram_bytes:
            problems.append(
                f"RAM byte ledger {ram_bytes} != {total} held"
            )
        return problems


# -- KV fabric ------------------------------------------------------------
#
# Cross-replica prefix exchange (docs/scale-out.md "KV fabric"): every
# replica's tier entries become pullable by peers, so N replicas hold
# ONE N-sized cache instead of N small ones. The reference stack's move
# is making remote memory a first-class directly-addressable tier
# (NVSHMEM symmetric gets, Triton-distributed's remote-pull
# primitives); this applies it one level up — tier entries travel as
# their checksummed wire bytes, so the PR 12 codec IS the transport and
# a garbled remote entry CRC-drops to re-prefill exactly like a
# corrupt local one.

# One wire line bound for fabric responses; mirrors the server's
# MAX_LINE_BYTES (models/ cannot import serving/ — layering).
_MAX_WIRE_LINE = 1 << 20


def tier_digest_match_len(digest, tokens) -> int:
    """Whole-page match length of ``tokens`` against a published tier
    digest (:meth:`PageStore.digest` wrapped with the engine's
    ``"ps"``): contiguous pages from the root whose truncated chain
    digests appear in the digest's chain set, capped so at least one
    token is left to prefill (the engine's fault-back walk does the
    same). 0 on a missing/foreign digest — placement then falls back
    to radix affinity alone."""
    if not isinstance(digest, dict):
        return 0
    try:
        ps = int(digest.get("ps") or 0)
    except (TypeError, ValueError):
        return 0
    chains = digest.get("chains")
    if ps <= 0 or not chains or not isinstance(chains, (list, set)):
        return 0
    have = set(chains)
    n = len(tokens)
    matched = 0
    i = ps
    while i < n:
        if chain_digest(tokens[:i])[:16] not in have:
            break
        matched = i
        i += ps
    return matched


class LocalFabricPeer:
    """In-process peer: a direct reference to another replica's store
    (the ``--replicas`` threaded-fleet shape). Pulls still return the
    encoded wire blob, so the client decodes/validates identically to
    a wire pull — one transport semantics, two carriers."""

    def __init__(self, name: str, store: PageStore):
        self.name = str(name)
        self._store = store

    def probe(self, kind: str, key: str) -> bool:
        return self._store.contains(kind, key)

    def get(self, kind: str, key: str) -> bytes | None:
        return self._store.get_blob(kind, key)


class WireFabricPeer:
    """Wire peer: ``tier_probe``/``tier_get`` line-JSON verbs against
    another replica's :class:`~serving.server.ModelServer` (the
    ``--fleet`` process shape). One short-lived connection per call —
    the verbs are engine-lock-free probe traffic, same as
    ``metrics``/``healthz``."""

    def __init__(self, name: str, host: str, port: int, *,
                 connect_timeout_s: float = 0.25):
        self.name = str(name)
        self.host = str(host)
        self.port = int(port)
        self.connect_timeout_s = float(connect_timeout_s)

    def _call(self, payload: dict, timeout_s: float) -> dict:
        with socket.create_connection(
            (self.host, self.port),
            timeout=max(self.connect_timeout_s, 0.05),
        ) as sock:
            sock.settimeout(max(timeout_s, 0.05))
            sock.sendall(
                json.dumps(payload, separators=(",", ":")).encode() + b"\n"
            )
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
                if len(buf) > _MAX_WIRE_LINE:
                    raise TierIntegrityError("fabric response over line bound")
        resp = json.loads(buf.decode())
        if not isinstance(resp, dict) or resp.get("error"):
            raise TierIntegrityError(
                f"fabric peer error: {resp.get('error') if isinstance(resp, dict) else resp!r}"
            )
        return resp

    def probe(self, kind: str, key: str, timeout_s: float = 0.25) -> bool:
        resp = self._call(
            {"cmd": "tier_probe", "kind": kind, "keys": [key]}, timeout_s
        )
        have = resp.get("have")
        return bool(isinstance(have, list) and have and have[0])

    def get(self, kind: str, key: str,
            timeout_s: float = 0.5) -> bytes | None:
        resp = self._call(
            {"cmd": "tier_get", "kind": kind, "key": key}, timeout_s
        )
        if not resp.get("found"):
            return None
        try:
            return base64.b64decode(resp["blob"], validate=True)
        except (KeyError, TypeError, ValueError) as e:
            raise TierIntegrityError(f"undecodable fabric blob: {e}") from e


class FabricClient:
    """Bounded, deadline-checked peer fault-back for one engine's tier.

    ``fetch`` is consulted by ``ContinuousEngine._tier_fill`` on a
    LOCAL tier miss: probe peers for the chain digest, pull the entry's
    wire bytes, validate them through :func:`_decode` (CRC + header —
    the same containment boundary local reads cross), and hand back the
    decoded payload for the engine's unchanged geometry/fingerprint
    validation. Every failure — refused connect, hung peer past the
    deadline, garbled bytes, over-bound response — degrades to None
    (re-prefill) and never blocks admission: pulls are capped by
    ``max_inflight`` across threads and by ``pull_timeout_s`` of total
    wall clock per fetch, and a failing peer cools down for
    ``cooldown_s`` so a dead neighbor costs one timeout, not one per
    request."""

    def __init__(self, *, pull_timeout_s: float = 0.5,
                 max_inflight: int = 2, cooldown_s: float = 5.0,
                 connect_timeout_s: float = 0.25):
        self.pull_timeout_s = float(pull_timeout_s)
        self.cooldown_s = float(cooldown_s)
        # Dial bound for wire peers (docs/scale-out.md "Multi-host
        # fleet"): an unroutable peer host must fail the probe on
        # THIS deadline, not the OS connect default — cross-host peer
        # lists make black-holed addresses a normal failure mode.
        self.connect_timeout_s = float(connect_timeout_s)
        self._sem = threading.BoundedSemaphore(max(1, int(max_inflight)))
        self._lock = threading.Lock()
        self._peers: list = []
        self._cool: dict[str, float] = {}
        self.stats = {
            "probes": 0,
            "pulls": 0,
            "pull_bytes": 0,
            "pull_failures": 0,
            "remote_hits": 0,
        }
        # Resolved once and pre-touched at 0: a cold scrape must show
        # the full fabric series (PR 15 convention).
        self._m_probes = obs_metrics.counter(
            "tdt_fabric_probes_total",
            "Fabric peer probes issued (tier_probe) on local tier "
            "misses.",
        )
        self._m_pulls = obs_metrics.counter(
            "tdt_fabric_pulls_total",
            "Fabric entry pulls attempted (tier_get) after a positive "
            "peer probe.",
        )
        self._m_pull_bytes = obs_metrics.counter(
            "tdt_fabric_pull_bytes_total",
            "Wire bytes of fabric entries pulled and validated.",
        )
        self._m_pull_failures = obs_metrics.counter(
            "tdt_fabric_pull_failures_total",
            "Fabric probe/pull failures (dead peer, deadline, garbled "
            "or over-bound entry) — each degraded to re-prefill, never "
            "wrong bits or a blocked admission.",
        )
        self._m_remote_hits = obs_metrics.counter(
            "tdt_fabric_remote_hits_total",
            "Fabric pulls that validated and served a peer's tier "
            "entry.",
        )
        for m in (self._m_probes, self._m_pulls, self._m_pull_bytes,
                  self._m_pull_failures, self._m_remote_hits):
            m.inc(0)

    # -- peer wiring -------------------------------------------------------

    @property
    def peers(self) -> list:
        with self._lock:
            return list(self._peers)

    def set_peers(self, peers) -> None:
        """Replace the peer set (any objects with ``name``/``probe``/
        ``get``) — the in-process wiring path."""
        with self._lock:
            self._peers = list(peers)
            self._cool.clear()

    def set_wire_peers(self, peers) -> None:
        """Replace the peer set from ``tier_peers`` wire dicts
        (``{"name", "host", "port"}``) — the supervisor broadcast
        path. Malformed rows are skipped, not fatal: a stale broadcast
        must never wedge the serving loop."""
        built = []
        for p in peers or ():
            try:
                built.append(WireFabricPeer(
                    str(p["name"]), str(p["host"]), int(p["port"]),
                    connect_timeout_s=self.connect_timeout_s,
                ))
            except (KeyError, TypeError, ValueError):
                continue
        self.set_peers(built)

    # -- pull --------------------------------------------------------------

    def _fail(self, peer_name: str, kind: str, key: str,
              reason: str) -> None:
        with self._lock:
            self.stats["pull_failures"] += 1
        self._m_pull_failures.inc()
        obs_events.emit(
            "fabric_pull_failed", peer=str(peer_name)[:64],
            tier_kind=kind, key=str(key)[:16], reason=str(reason)[:160],
        )

    def fetch(self, kind: str, key: str) -> dict | None:
        """Probe peers for ``(kind, key)`` and pull + validate the
        first hit. Returns the DECODED payload dict (what
        ``PageStore.get`` returns) or None — the caller cannot tell a
        dead fabric from a fleet-wide miss, which is the point: both
        degrade to re-prefill."""
        deadline = time.monotonic() + self.pull_timeout_s
        if not self._sem.acquire(blocking=False):
            # At the in-flight bound: skip rather than queue — peer
            # fault-back is an optimization, admission latency is not.
            self._fail("*", kind, key, "inflight bound")
            return None
        try:
            return self._fetch_locked(kind, key, deadline)
        finally:
            self._sem.release()

    def _fetch_locked(self, kind: str, key: str,
                      deadline: float) -> dict | None:
        now = time.monotonic()
        for peer in self.peers:
            if now >= deadline:
                self._fail(peer.name, kind, key, "deadline")
                return None
            with self._lock:
                until = self._cool.get(peer.name, 0.0)
            if now < until:
                continue
            try:
                with self._lock:
                    self.stats["probes"] += 1
                self._m_probes.inc()
                probe_key = mutate_point(
                    "fabric.probe", key, peer=peer.name, kind=kind,
                )
                have = peer.probe(kind, probe_key)
            except Exception as e:  # noqa: BLE001 — containment boundary
                self._cool_peer(peer.name)
                self._fail(peer.name, kind, key,
                           f"probe: {type(e).__name__}: {e}")
                now = time.monotonic()
                continue
            now = time.monotonic()
            if not have:
                continue
            if now >= deadline:
                self._fail(peer.name, kind, key, "deadline")
                return None
            try:
                with self._lock:
                    self.stats["pulls"] += 1
                self._m_pulls.inc()
                blob = peer.get(kind, key)
                blob = mutate_point(
                    "fabric.get", blob, peer=peer.name, kind=kind,
                    key=key,
                )
            except Exception as e:  # noqa: BLE001 — containment boundary
                self._cool_peer(peer.name)
                self._fail(peer.name, kind, key,
                           f"pull: {type(e).__name__}: {e}")
                now = time.monotonic()
                continue
            now = time.monotonic()
            if blob is None:
                continue  # raced away between probe and pull
            if now > deadline:
                # The bytes arrived late (hung peer): honoring them
                # would make the timeout advisory. Drop, re-prefill.
                self._fail(peer.name, kind, key, "deadline")
                return None
            try:
                payload = _decode(kind, key, bytes(blob))
            except Exception as e:  # noqa: BLE001 — the codec IS the
                # transport: a garbled remote entry dies HERE, exactly
                # where a corrupt local one does.
                self._fail(peer.name, kind, key,
                           f"integrity: {type(e).__name__}: {e}")
                continue
            with self._lock:
                self.stats["remote_hits"] += 1
                self.stats["pull_bytes"] += len(blob)
            self._m_remote_hits.inc()
            self._m_pull_bytes.inc(len(blob))
            obs_events.emit(
                "fabric_pull", peer=str(peer.name)[:64], tier_kind=kind,
                key=str(key)[:16], nbytes=len(blob),
            )
            return payload
        return None

    def _cool_peer(self, name: str) -> None:
        with self._lock:
            self._cool[name] = time.monotonic() + self.cooldown_s

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["peers"] = [str(getattr(p, "name", "?"))
                            for p in self._peers]
        return out
