"""Radix prefix cache over the paged KV pool.

Beyond-parity subsystem (the reference Engine recomputes every prompt
from token zero): real serving traffic shares long system prompts and
few-shot prefixes across requests, so finished sequences donate their
KV pages to a host-side radix tree instead of the free list. Admission
walks the tree with the new prompt's token ids — every matched page is
mapped into the new sequence's page table by reference (refcounted,
never copied, never recomputed) and only the suffix is prefilled.

Design (the SGLang RadixAttention idea, arXiv:2312.07104, applied to
our ``PagePool``):

- **Keying**: one tree node per page; a node's key is the exact
  ``page_size``-token chunk cached in its page (children indexed by
  first token — token chunks of distinct children never share a first
  token). A node with a partially filled page (< page_size tokens, e.g.
  a sequence's tail) is always a leaf.
- **Sharing**: a fully matched full page is shared in place — the page
  id goes straight into the new slot's table row and the node's
  refcount pins it. Decode appends always land at kv_len ≥ the shared
  prefix, so shared pages are read-only by construction.
- **Copy-on-write**: a *partially* matched page (the match ends inside
  the chunk — a tail page, or a full page longer than the remaining
  prompt) cannot be shared: the new sequence must write its own tokens
  into that page. Its content is cloned into a private page
  (``paged_kv_cache.copy_page``) and only the matched positions count;
  the clone's tail is overwritten/masked exactly like any other
  garbage beyond kv_len.
- **Eviction**: nodes hold pool pages even when no sequence references
  them (that IS the cache). When admission needs pages the pool can't
  supply, unreferenced leaves are evicted in LRU order (cascading to
  parents that become unreferenced leaves) back to the free list.

Everything here is host-side control-plane state, like ``PagePool``
itself: matching/insertion cost is a dict walk per page, noise next to
a prefill step.
"""

from __future__ import annotations

import dataclasses
import heapq
import weakref
from typing import Iterable

from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics


def round_chunk(n: int) -> int:
    """Chunk widths ``prefill_paged_chunk`` accepts: ≤128 → multiple of
    16 (bf16 sublane tile), beyond → multiple of 128 (flash block_q)."""
    n = max(int(n), 1)
    if n <= 128:
        return -(-n // 16) * 16
    return -(-n // 128) * 128


def node_chain(node: "RadixNode") -> list[int]:
    """The full token chain from the root through ``node``'s own chunk
    — the identity a spilled page is keyed by in the durable KV tier
    (``models/kv_tier.py::chain_digest``). Walks parent links, so it
    must run BEFORE eviction detaches the node."""
    chunks = []
    while node is not None and node.chunk:
        chunks.append(node.chunk)
        node = node.parent
    out: list[int] = []
    for c in reversed(chunks):
        out.extend(c)
    return out


class RadixNode:
    """One cached page: ``chunk`` is the exact token ids it holds."""

    __slots__ = ("chunk", "page", "children", "refcount", "parent",
                 "last_use")

    def __init__(self, chunk: tuple[int, ...], page: int,
                 parent: "RadixNode | None"):
        self.chunk = chunk
        self.page = page
        self.children: dict[int, RadixNode] = {}
        self.refcount = 0
        self.parent = parent
        self.last_use = 0

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"RadixNode(page={self.page}, fill={len(self.chunk)}, "
                f"rc={self.refcount}, kids={len(self.children)})")


@dataclasses.dataclass
class PrefixMatch:
    """Result of a longest-prefix walk. ``nodes`` are fully shared full
    pages (already refcounted); ``cow_node`` is a partially matched page
    to clone (refcount-pinned until :meth:`PrefixCache.finish_cow`)."""

    nodes: list[RadixNode]
    cow_node: RadixNode | None
    cow_len: int
    page_size: int

    @property
    def pages(self) -> list[int]:
        return [n.page for n in self.nodes]

    @property
    def matched_len(self) -> int:
        return len(self.nodes) * self.page_size + self.cow_len


class PrefixCache:
    """Host-side radix tree owning retired KV pages of a ``PagePool``."""

    # Live instances, auditable by the shared pytest fixture
    # (tests/conftest.py) after every test.
    _live: "weakref.WeakSet[PrefixCache]" = weakref.WeakSet()

    def __init__(self, pool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        self.root = RadixNode((), -1, None)
        self._clock = 0
        # Durable KV tier hook (docs/serving.md "Tiered KV"): when set
        # (``ContinuousEngine`` installs ``_spill_page``), eviction
        # offers every full victim page — ``spill_fn(chain, page_id)``
        # — BEFORE releasing it, so "evicted" means "demoted to
        # host-RAM/disk" instead of "gone". Best-effort by contract: a
        # spill failure falls back to the pre-tier drop.
        self.spill_fn = None
        PrefixCache._live.add(self)
        self.node_count = 0  # == pages held by the tree
        self.stats = {
            "lookups": 0,
            "hits": 0,
            "hit_tokens": 0,
            "cow_pages": 0,
            "inserted_pages": 0,
            "deduped_pages": 0,
            "evicted_pages": 0,
        }
        # Resolved ONCE (the ContinuousEngine `_metric_handles`
        # convention): evictions run inside the admission path, and a
        # per-eviction registry get-or-create would contend on the
        # process-global lock with the decode loop's increments.
        self._evicted_counter = obs_metrics.counter(
            "tdt_prefix_evicted_pages_total",
            "Radix-tree pages evicted back to the pool.",
        )

    # -- matching ---------------------------------------------------------

    def match(self, tokens) -> PrefixMatch:
        """Longest cached prefix of ``tokens``, capped at
        ``len(tokens) - 1`` so at least one suffix token remains to
        prefill (its logits seed generation). Matched nodes are
        refcount-pinned; pair every match with exactly one of
        :meth:`release_match` (admission abandoned) or the
        finish_cow → :meth:`release_node`-per-node protocol."""
        toks = [int(t) for t in tokens]
        limit = len(toks) - 1
        node = self.root
        nodes: list[RadixNode] = []
        cow_node, cow_len = None, 0
        i = 0
        while i < limit:
            child = node.children.get(toks[i])
            if child is None:
                break
            lcp = 0
            for a, b in zip(child.chunk, toks[i:i + len(child.chunk)]):
                if a != b:
                    break
                lcp += 1
            lcp = min(lcp, limit - i)
            if lcp == len(child.chunk) == self.page_size:
                nodes.append(child)
                node = child
                i += lcp
            else:
                if lcp > 0:
                    cow_node, cow_len = child, lcp
                break
        self._clock += 1
        for n in nodes:
            n.refcount += 1
            n.last_use = self._clock
        if cow_node is not None:
            cow_node.refcount += 1
            cow_node.last_use = self._clock
        self.stats["lookups"] += 1
        matched = len(nodes) * self.page_size + cow_len
        if matched:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += matched
        return PrefixMatch(nodes, cow_node, cow_len, self.page_size)

    def finish_cow(self, m: PrefixMatch) -> None:
        """Drop the COW pin after the device-side page clone is enqueued
        (ordering vs. later reuse of the source page is guaranteed by
        the cache arrays threading through the programs)."""
        if m.cow_node is not None:
            self.release_node(m.cow_node)
            m.cow_node = None
            self.stats["cow_pages"] += 1

    def release_match(self, m: PrefixMatch) -> None:
        """Undo :meth:`match` (admission did not go through) — pins AND
        the lookup accounting, so a stalled request re-matched on every
        retry can't inflate hit-rate counters."""
        matched = m.matched_len
        self.stats["lookups"] -= 1
        if matched:
            self.stats["hits"] -= 1
            self.stats["hit_tokens"] -= matched
        for n in m.nodes:
            self.release_node(n)
        m.nodes = []
        if m.cow_node is not None:
            self.release_node(m.cow_node)
            m.cow_node = None
        m.cow_len = 0

    def release_node(self, node: RadixNode) -> None:
        assert node.refcount > 0, "refcount underflow"
        node.refcount -= 1

    # -- insertion --------------------------------------------------------

    def insert_chain(
        self,
        parent: RadixNode,
        tokens: Iterable[int],
        pages: list[int],
    ) -> None:
        """Donate a finished sequence's private pages below ``parent``
        (its deepest shared node, or the root). ``pages[j]`` holds the
        KV of ``tokens[j*page_size : (j+1)*page_size]``; every page is
        consumed — adopted by a node, or released to the pool when its
        chunk is already cached (dedupe), diverges from a sibling that
        keeps its slot, or lies beyond the token chain (unused
        gen-headroom pages)."""
        toks = [int(t) for t in tokens]
        ps = self.page_size
        self._clock += 1
        node = parent
        i = 0
        k = 0
        while i < len(toks) and k < len(pages):
            chunk = tuple(toks[i:i + ps])
            child = node.children.get(chunk[0])
            if child is None:
                new = RadixNode(chunk, pages[k], node)
                new.last_use = self._clock
                node.children[chunk[0]] = new
                self.node_count += 1
                self.stats["inserted_pages"] += 1
                node = new
                i += len(chunk)
                k += 1
                if len(chunk) < ps:
                    break  # partial tail is a leaf; nothing descends
                continue
            lcp = 0
            for a, b in zip(child.chunk, chunk):
                if a != b:
                    break
                lcp += 1
            if lcp == len(child.chunk) == ps == len(chunk):
                # Identical full page already cached — ours is redundant.
                self.pool.release([pages[k]])
                self.stats["deduped_pages"] += 1
                child.last_use = self._clock
                node = child
                i += ps
                k += 1
                continue
            if (lcp == len(child.chunk) < len(chunk)
                    and child.refcount == 0):
                # Cached partial tail is a strict prefix of our chunk:
                # upgrade the node in place — ours supersedes it.
                self.pool.release([child.page])
                child.chunk = chunk
                child.page = pages[k]
                child.last_use = self._clock
                self.stats["inserted_pages"] += 1
                node = child
                i += len(chunk)
                k += 1
                if len(chunk) < ps:
                    break
                continue
            # Divergent sibling (or a pinned/longer partial we must not
            # touch): the remaining chain can't attach — stop. One
            # first-token slot per parent keeps matching O(1); the rare
            # collision costs cache coverage, never correctness.
            break
        self.pool.release(pages[k:])

    # -- eviction ---------------------------------------------------------

    def retire_sequence(self, tokens, pages: list[int],
                        shared_nodes: list[RadixNode]) -> None:
        """Finished-sequence release protocol, in one place for both
        engines: donate the private pages (those past the shared prefix)
        below the deepest pinned node, then drop the pins. ``tokens`` is
        the full cached token chain — prompt plus every fed-back
        generated token, i.e. positions ``[0, s + gen - 1)``."""
        parent = shared_nodes[-1] if shared_nodes else self.root
        n_sh = len(shared_nodes)
        self.insert_chain(
            parent, tokens[n_sh * self.page_size :], pages[n_sh:]
        )
        for node in shared_nodes:
            self.release_node(node)

    def reclaimable_pages(self) -> int:
        """Pages cascading LRU eviction could return to the pool right
        now: nodes whose subtree holds no refcounted node."""

        def rec(node: RadixNode) -> tuple[int, bool]:
            total, pinned = 0, node.refcount > 0
            for c in node.children.values():
                t, p = rec(c)
                total += t
                pinned = pinned or p
            if node is self.root:
                return total, pinned
            return (total, True) if pinned else (total + 1, False)

        return rec(self.root)[0]

    def evict_until(self, free_target: int) -> int:
        """Evict unreferenced LRU leaves until the pool holds
        ``free_target`` free pages (or nothing is evictable)."""
        heap: list[tuple[int, int, RadixNode]] = []

        def seed(node: RadixNode):
            for c in node.children.values():
                if c.children:
                    seed(c)
                elif c.refcount == 0:
                    heapq.heappush(heap, (c.last_use, id(c), c))

        seed(self.root)
        evicted = 0
        while heap and len(self.pool.free) < free_target:
            _, _, victim = heapq.heappop(heap)
            if (victim.parent is None or victim.children
                    or victim.refcount):
                continue  # stale heap entry
            if (self.spill_fn is not None
                    and len(victim.chunk) == self.page_size):
                # Export the victim to the durable tier (full pages
                # only: fault-back re-maps whole tree pages; a partial
                # tail is one COW/suffix-prefill away and not worth an
                # entry). The chain must be read before the detach
                # below severs the parent links.
                try:
                    self.spill_fn(node_chain(victim), victim.page)
                except Exception:  # noqa: BLE001 — spill is best-effort
                    pass  # fall back to the pre-tier drop
            parent = victim.parent
            del parent.children[victim.chunk[0]]
            victim.parent = None
            self.pool.release([victim.page])
            self.node_count -= 1
            evicted += 1
            if (parent is not self.root and not parent.children
                    and parent.refcount == 0):
                heapq.heappush(heap, (parent.last_use, id(parent), parent))
        self.stats["evicted_pages"] += evicted
        if evicted:
            obs_events.emit("prefix_evict", pages=evicted)
            self._evicted_counter.inc(evicted)
        return evicted

    def allocate(self, n: int) -> list[int] | None:
        """Allocate ``n`` pool pages, evicting cached (unreferenced)
        pages LRU-first when the free list runs short. None when even
        full eviction cannot cover ``n`` (caller queues the request)."""
        if n > len(self.pool.free):
            self.evict_until(n)
        if n > len(self.pool.free):
            return None
        return self.pool.allocate(n)

    def flush(self) -> int:
        """Release every unreferenced page back to the pool (e.g. on a
        pool reshape). Refuses to drop pinned nodes."""
        return self.evict_until(len(self.pool.free) + self.node_count + 1)

    # -- introspection ----------------------------------------------------

    def prefix_digest(self) -> list:
        """Compact, serializable snapshot of the cached prefix
        population: a recursive ``[chunk, children]`` forest mirroring
        the radix tree's token chains (pages, refcounts, and LRU state
        are deliberately omitted — a consumer routes on WHAT is cached,
        not on how it is stored). The serving router
        (``serving/router.py``) keeps one digest per replica as its
        router-side mirror and scores prefix affinity against it with
        :func:`digest_match_len`; replicas re-publish after every
        engine batch, so the view tracks admit/evict churn at batch
        granularity."""

        # Iterative (like :meth:`walk`): a single cached chain deeper
        # than Python's recursion limit must not blow up a replica's
        # digest publish.
        out: list = []
        stack = [(c, out) for c in self.root.children.values()]
        while stack:
            node, siblings = stack.pop()
            entry = [list(node.chunk), []]
            siblings.append(entry)
            stack.extend((c, entry[1]) for c in node.children.values())
        return out

    def audit(self) -> list[str]:
        """Structural invariant check; returns violation strings
        (empty == clean). Verifies what the tree can see on its own —
        the engine-level :meth:`ContinuousEngine.audit` adds the
        refcount-vs-live-slot and pool-partition cross-checks:

        - no page appears under two nodes, or under a node AND on the
          free list,
        - children are indexed by their chunk's first token and parent
          links are consistent,
        - a partially filled page is a leaf, chunks are non-empty and
          at most ``page_size`` tokens,
        - refcounts are non-negative and ``node_count`` matches the
          walk.
        """
        problems: list[str] = []
        free = set(self.pool.free)
        seen: dict[int, RadixNode] = {}
        count = 0
        stack: list[RadixNode] = [self.root]
        while stack:
            node = stack.pop()
            for key, child in node.children.items():
                count += 1
                if not child.chunk:
                    problems.append(f"node page {child.page} has an "
                                    "empty chunk")
                elif key != child.chunk[0]:
                    problems.append(
                        f"child indexed by {key} but chunk starts with "
                        f"{child.chunk[0]} (page {child.page})"
                    )
                if len(child.chunk) > self.page_size:
                    problems.append(
                        f"node page {child.page} chunk overflows the "
                        f"page ({len(child.chunk)} > {self.page_size})"
                    )
                if len(child.chunk) < self.page_size and child.children:
                    problems.append(
                        f"partial page {child.page} "
                        f"({len(child.chunk)} tokens) has children"
                    )
                if child.parent is not node:
                    problems.append(
                        f"node page {child.page} has a broken parent link"
                    )
                if child.refcount < 0:
                    problems.append(
                        f"node page {child.page} refcount underflow "
                        f"({child.refcount})"
                    )
                if child.page in seen:
                    problems.append(f"page {child.page} cached by two "
                                    "tree nodes")
                else:
                    seen[child.page] = child
                if child.page in free:
                    problems.append(
                        f"page {child.page} cached by the tree AND on "
                        "the free list"
                    )
                stack.append(child)
        if count != self.node_count:
            problems.append(
                f"node_count={self.node_count} but the walk found {count}"
            )
        return problems

    @property
    def hit_rate(self) -> float:
        return self.stats["hits"] / max(self.stats["lookups"], 1)

    def walk(self):
        """Yield every node (tests/debugging)."""
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    # -- speculation ------------------------------------------------------

    def propose_continuations(
        self,
        tokens,
        *,
        width: int,
        depth: int,
        tier_chains=None,
    ) -> list[list[int]]:
        """Draft continuations of ``tokens`` for tree speculation: up
        to ``width`` candidate paths of up to ``depth`` tokens each,
        read from what the radix tree remembers FOLLOWING this exact
        history. The cache is a population of full (prompt + generated)
        chains of finished sequences — when several of them shared this
        request's history and then diverged, the divergence shows up
        here as sibling children, and every branch is worth drafting
        (the verify chunk scores them all in one forward).

        Pure read: no pins, no LRU touch, no stats — drafting must
        never perturb eviction order or hit-rate accounting. The walk
        requires the FULL history to be cached (token-exact); any
        mismatch or cache exhaustion before the history's end returns
        no radix paths, because a continuation of a different prefix is
        noise, not signal. Branch exploration is recency-first
        (``last_use`` descending), so the paths lean toward what
        recent traffic actually generated.

        ``tier_chains`` extends the population with the durable KV
        tier's RAM-resident chains (``PageStore.resident_chains``) —
        continuations whose pages were evicted from the tree but whose
        token identity survives in the spill headers. Matching there is
        a flat prefix scan (the tier is keyed by digest, not by token).
        """
        toks = [int(t) for t in tokens]
        width = max(int(width), 0)
        depth = max(int(depth), 0)
        out: list[list[int]] = []
        if depth and width:
            node, stem, i, dead = self.root, [], 0, False
            while i < len(toks):
                child = node.children.get(toks[i])
                if child is None:
                    dead = True
                    break
                lcp = 0
                for a, b in zip(child.chunk, toks[i:i + len(child.chunk)]):
                    if a != b:
                        break
                    lcp += 1
                if lcp < len(child.chunk):
                    if i + lcp == len(toks):
                        # History ends INSIDE this chunk: the chunk's
                        # own tail is the (single) continuation stem,
                        # then the subtree below it.
                        stem = [int(t) for t in child.chunk[lcp:]]
                        node = child
                    else:
                        dead = True  # diverged mid-chunk: wrong prefix
                    break
                if len(child.chunk) < self.page_size and i + lcp < len(toks):
                    dead = True  # partial leaf, history runs past it
                    break
                node = child
                i += lcp

            def descend(n: RadixNode, prefix: list[int]) -> None:
                if len(out) >= width:
                    return
                if len(prefix) >= depth or not n.children:
                    if prefix:
                        out.append(prefix[:depth])
                    return
                for c in sorted(n.children.values(),
                                key=lambda x: -x.last_use):
                    descend(c, prefix + [int(t) for t in c.chunk])
                    if len(out) >= width:
                        return

            if not dead:
                descend(node, stem)
        if tier_chains:
            hits = 0
            for chain in tier_chains:
                if hits >= width:
                    break
                if len(chain) > len(toks) and chain[:len(toks)] == toks:
                    out.append(
                        [int(t) for t in chain[len(toks):len(toks) + depth]]
                    )
                    hits += 1
        return out


def digest_match_len(digest: list | None, tokens) -> int:
    """Longest cached prefix of ``tokens`` visible in a
    :meth:`PrefixCache.prefix_digest` snapshot, in tokens. Matching
    mirrors :meth:`PrefixCache.match`: descend only through fully
    matched chunks, count a partial in-chunk match (the COW case) once
    and stop. No cap at ``len(tokens) - 1`` — this scores affinity for
    routing, it does not reserve pages."""
    if not digest:
        return 0
    # An already-converted int list (the router caches one per ticket
    # so N replicas don't each re-convert the prompt) passes through.
    toks = tokens if type(tokens) is list else [int(t) for t in tokens]
    level: list | None = digest
    i = 0
    while level:
        nxt = None
        for chunk, children in level:
            if not chunk or i >= len(toks) or chunk[0] != toks[i]:
                continue
            # Index-bounded compare — a toks[i:] slice here would copy
            # the remaining prompt once per matched chunk (O(L²/page)
            # per replica per routing decision).
            lcp = 0
            limit = min(len(chunk), len(toks) - i)
            while lcp < limit and chunk[lcp] == toks[i + lcp]:
                lcp += 1
            i += lcp
            if lcp == len(chunk):
                nxt = children  # fully matched page: descend
            break
        level = nxt
    return i
