"""Token sampling (parity: reference ``models/utils.py`` sampling helpers
— greedy, temperature, top-k, top-p nucleus).

``filter_logits`` is the ONE definition of the post-processing chain
(temperature → top-k → top-p): ``sample`` draws a categorical over it,
and ``target_probs`` exposes the same filtered distribution as explicit
probabilities — the speculative verifier's acceptance test must score
draft tokens against EXACTLY the distribution ``sample`` draws from, or
rejection sampling stops being distribution-preserving.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class NonFiniteLogitsError(RuntimeError):
    """The model produced NaN/Inf logits. Raised by the serving-path
    guards (admission sampling, speculative verify) so engines map it
    to a structured ``nan_logits`` request failure instead of silently
    argmax-ing garbage; ``slot`` (when set) attributes it."""

    def __init__(self, msg: str, slot: int | None = None):
        super().__init__(msg)
        self.slot = slot


def greedy(logits: jax.Array) -> jax.Array:
    """``logits [..., V]`` → token ids ``[...]``."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def filter_logits(
    logits: jax.Array,
    temperature: float = 1.0,
    top_p: float = 1.0,
    top_k: int = 0,
) -> jax.Array:
    """Temperature-scale then mask ``logits [..., V]`` to the sampled
    support: tokens outside the top-k / nucleus go to ``-inf``.
    ``top_k=0`` disables the top-k filter; ties at the k-th value all
    survive (standard threshold semantics). Requires ``temperature > 0``
    (greedy is a separate path, not a limit of this one)."""
    logits = logits.astype(jnp.float32) / temperature
    v = logits.shape[-1]
    if top_k and 0 < top_k < v:
        kth = jnp.sort(logits, axis=-1)[..., v - top_k, None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keep the top token).
        keep = cum - probs < top_p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    return logits


def sample(
    logits: jax.Array,
    key: jax.Array,
    temperature: float = 1.0,
    top_p: float = 1.0,
    top_k: int = 0,
) -> jax.Array:
    """Temperature + top-k + nucleus sampling. ``temperature<=0`` →
    greedy."""
    if temperature <= 0.0:
        return greedy(logits)
    filtered = filter_logits(logits, temperature, top_p, top_k)
    return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)


def target_probs(
    logits: jax.Array,
    temperature: float = 1.0,
    top_p: float = 1.0,
    top_k: int = 0,
) -> jax.Array:
    """The exact distribution :func:`sample` draws from, as
    probabilities ``[..., V]`` (speculative decoding scores draft tokens
    against this). ``temperature<=0`` → one-hot at the argmax."""
    if temperature <= 0.0:
        return jax.nn.one_hot(
            jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=jnp.float32
        )
    return jax.nn.softmax(
        filter_logits(logits, temperature, top_p, top_k), axis=-1
    )
