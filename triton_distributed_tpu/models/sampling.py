"""Token sampling (parity: reference ``models/utils.py`` sampling helpers
— greedy, temperature, top-p nucleus)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def greedy(logits: jax.Array) -> jax.Array:
    """``logits [..., V]`` → token ids ``[...]``."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample(
    logits: jax.Array,
    key: jax.Array,
    temperature: float = 1.0,
    top_p: float = 1.0,
) -> jax.Array:
    """Temperature + nucleus sampling. ``temperature<=0`` → greedy."""
    if temperature <= 0.0:
        return greedy(logits)
    logits = logits.astype(jnp.float32) / temperature
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keep the top token).
        keep = cum - probs < top_p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
