"""Deterministic stub engine: the serving control plane with no model.

The process-fleet supervisor (``serving/supervisor.py``) spawns one
child process per replica, and the chaos suite SIGKILLs them mid-batch
— which makes child startup cost part of every tier-1 fleet test. A
real ``ContinuousEngine`` child pays model load + first-compile per
spawn; this stub pays neither, while keeping everything the fleet
actually exercises REAL:

- the radix prefix cache (``models/prefix_cache.py``) and page pool
  are the production classes — admission matches, COW-counts, inserts,
  retires, and evicts through the exact protocol ``ContinuousEngine``
  uses, so prefix digests, affinity routing, hit rates, and pool/tree
  audits over the wire are the real thing;
- outputs are a pure function of the token context (an FNV-1a rolling
  hash picks each next token), so a re-routed request reproduces
  BIT-EXACTLY on any replica — the chaos suite's survivor-equality
  checks mean what they mean on the real model;
- ``last_stats`` carries the fleet-total keys
  (``serving/replica.py::FLEET_TOTAL_KEYS``) with the same semantics,
  so router aggregation and the supervisor bench read one schema.

What it does NOT model: logits, KV bytes, sampling temperature (all
requests decode greedily under the hash), or wall-clock realism —
``delay_s`` exists only to hold a batch in flight long enough for a
mid-batch SIGKILL to land deterministically.

``run_server --model stub`` serves one of these behind the production
``ModelServer``, which is how the supervisor's tests and
``perf/fleet_bench.py`` spawn whole fleets in seconds.
"""

from __future__ import annotations

import time

import numpy as np

from triton_distributed_tpu.models.continuous import RequestResult
from triton_distributed_tpu.models.paged_kv_cache import PagePool
from triton_distributed_tpu.models.prefix_cache import PrefixCache

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_MASK = (1 << 32) - 1


def stub_next_token(context, vocab: int) -> int:
    """The stub's whole "model": FNV-1a over the token context. Pure,
    stateless, identical in every process — the property the fleet's
    bit-exact-reroute guarantee is tested against."""
    h = _FNV_OFFSET
    for t in context:
        h = ((h ^ int(t)) * _FNV_PRIME) & _MASK
    return h % vocab


def stub_generate(prompt, gen_len: int, vocab: int = 211) -> list[int]:
    """Reference continuation for ``prompt`` — what any replica must
    produce. Tests compute goldens with this, no engine needed."""
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(int(gen_len)):
        nxt = stub_next_token(toks, vocab)
        out.append(nxt)
        toks.append(nxt)
    return out


class StubEngine:
    """Continuous-engine-shaped stub over a real radix prefix cache.

    Duck-types the surface ``ModelServer`` and the replica tier speak:
    ``run(reqs, results=True)``, ``last_stats``, ``prefix_digest``,
    ``drain``, ``audit``. One instance per child process; the server's
    engine lock serializes access exactly as for a real engine.
    """

    def __init__(self, *, num_pages: int = 128, page_size: int = 16,
                 vocab: int = 211, delay_s: float = 0.0):
        self.pool = PagePool(num_pages)
        self.page_size = int(page_size)
        self.prefix = PrefixCache(self.pool, self.page_size)
        self.vocab = int(vocab)
        # Per-batch wall-time floor: keeps a batch in flight long
        # enough for the chaos suite's mid-batch kill seams.
        self.delay_s = float(delay_s)
        self.last_stats: dict = self._zero_stats()

    def _zero_stats(self) -> dict:
        return {
            "decode_steps": 0,
            "prefill_tokens": 0,
            "generated_tokens": 0,
            "prefix_hit_tokens": 0,
            "kv_bytes_per_token": 0.0,
            "kv_dtype": "stub",
        }

    def _pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def run(self, requests, *, results: bool = False):
        """Serve a batch; same contract as ``ContinuousEngine.run``.
        Accepts engine ``Request`` objects or ``(prompt, gen_len)``
        tuples. ``decode_steps`` counts emitted tokens (the stub has no
        batched decode, so steps == tokens)."""
        stats = self._zero_stats()
        if self.delay_s:
            time.sleep(self.delay_s)
        outs: list[RequestResult] = []
        for req in requests:
            prompt = getattr(req, "prompt", None)
            if prompt is None:
                prompt, gen_len = req
            else:
                gen_len = req.gen_len
            outs.append(self._serve_one(prompt, int(gen_len), stats))
        self.last_stats = stats
        stats["prefix_cache"] = dict(self.prefix.stats)
        stats["prefix_hit_rate"] = self.prefix.hit_rate
        stats["tree_pages"] = self.prefix.node_count
        stats["free_pages"] = len(self.pool.free)
        if results:
            return outs
        return [np.asarray(r.tokens, np.int32) for r in outs]

    def _serve_one(self, prompt, gen_len: int,
                   stats: dict) -> RequestResult:
        toks = [int(t) for t in prompt]
        s = len(toks)
        if s == 0 or gen_len <= 0:
            return RequestResult(
                np.zeros(0, np.int32), "unservable",
                "stub needs a non-empty prompt and gen_len >= 1",
            )
        total = self._pages_for(s + gen_len)
        # The production admission protocol: match (pins + hit
        # accounting), allocate the uncovered pages (LRU-evicting the
        # tree when the free list runs short), COW-finish, retire.
        m = self.prefix.match(toks)
        new = self.prefix.allocate(total - len(m.nodes))
        if new is None:
            self.prefix.release_match(m)
            return RequestResult(
                np.zeros(0, np.int32), "overloaded",
                f"stub pool cannot cover {total} pages",
            )
        matched = m.matched_len
        shared = list(m.nodes)
        self.prefix.finish_cow(m)
        pages = m.pages + new
        out = stub_generate(toks, gen_len, self.vocab)
        stats["prefill_tokens"] += s - matched
        stats["prefix_hit_tokens"] += matched
        stats["generated_tokens"] += gen_len
        stats["decode_steps"] += gen_len
        # Cache prompt + fed-back generations, positions [0, s+gen-1)
        # — the same chain a real engine retires.
        chain = (toks + out)[: s + gen_len - 1]
        nchain = self._pages_for(len(chain))
        self.prefix.retire_sequence(chain, pages[:nchain], shared)
        self.pool.release(pages[nchain:])
        return RequestResult(np.asarray(out, np.int32))

    # -- replica/server surface -------------------------------------------

    def prefix_digest(self) -> list:
        return self.prefix.prefix_digest()

    def drain(self) -> int:
        """Flush the radix tree back to the pool (replica drain)."""
        return self.prefix.flush()

    def audit(self, *, raise_on_violation: bool = False) -> list[str]:
        """Tree invariants + exact pool partition (no in-flight state
        survives a synchronous ``run``, so free ∪ tree must cover the
        pool whenever the engine lock is held)."""
        problems = list(self.prefix.audit())
        held = len(self.pool.free) + self.prefix.node_count
        if held != self.pool.num_pages:
            problems.append(
                f"pool partition broken: {len(self.pool.free)} free + "
                f"{self.prefix.node_count} tree != {self.pool.num_pages}"
            )
        if problems and raise_on_violation:
            from triton_distributed_tpu.models.paged_kv_cache import (
                PoolAuditError,
            )

            raise PoolAuditError("; ".join(problems))
        return problems
