"""Deterministic stub engine: the serving control plane with no model.

The process-fleet supervisor (``serving/supervisor.py``) spawns one
child process per replica, and the chaos suite SIGKILLs them mid-batch
— which makes child startup cost part of every tier-1 fleet test. A
real ``ContinuousEngine`` child pays model load + first-compile per
spawn; this stub pays neither, while keeping everything the fleet
actually exercises REAL:

- the radix prefix cache (``models/prefix_cache.py``) and page pool
  are the production classes — admission matches, COW-counts, inserts,
  retires, and evicts through the exact protocol ``ContinuousEngine``
  uses, so prefix digests, affinity routing, hit rates, and pool/tree
  audits over the wire are the real thing;
- outputs are a pure function of the token context (an FNV-1a rolling
  hash picks each next token), so a re-routed request reproduces
  BIT-EXACTLY on any replica — the chaos suite's survivor-equality
  checks mean what they mean on the real model;
- ``last_stats`` carries the fleet-total keys
  (``serving/replica.py::FLEET_TOTAL_KEYS``) with the same semantics,
  so router aggregation and the supervisor bench read one schema.

What it does NOT model: logits, KV bytes, sampling temperature (all
requests decode greedily under the hash), or wall-clock realism —
``delay_s`` exists only to hold a batch in flight long enough for a
mid-batch SIGKILL to land deterministically.

``run_server --model stub`` serves one of these behind the production
``ModelServer``, which is how the supervisor's tests and
``perf/fleet_bench.py`` spawn whole fleets in seconds.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from triton_distributed_tpu.models.continuous import RequestResult
from triton_distributed_tpu.models.paged_kv_cache import PagePool
from triton_distributed_tpu.models.prefix_cache import PrefixCache
from triton_distributed_tpu.obs import metrics as obs_metrics

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_MASK = (1 << 32) - 1


def stub_next_token(context, vocab: int) -> int:
    """The stub's whole "model": FNV-1a over the token context. Pure,
    stateless, identical in every process — the property the fleet's
    bit-exact-reroute guarantee is tested against."""
    h = _FNV_OFFSET
    for t in context:
        h = ((h ^ int(t)) * _FNV_PRIME) & _MASK
    return h % vocab


def stub_generate(prompt, gen_len: int, vocab: int = 211) -> list[int]:
    """Reference continuation for ``prompt`` — what any replica must
    produce. Tests compute goldens with this, no engine needed."""
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(int(gen_len)):
        nxt = stub_next_token(toks, vocab)
        out.append(nxt)
        toks.append(nxt)
    return out


class StubEngine:
    """Continuous-engine-shaped stub over a real radix prefix cache.

    Duck-types the surface ``ModelServer`` and the replica tier speak:
    ``run(reqs, results=True)``, ``last_stats``, ``prefix_digest``,
    ``drain``, ``audit``. One instance per child process; the server's
    engine lock serializes access exactly as for a real engine.
    """

    def __init__(self, *, num_pages: int = 128, page_size: int = 16,
                 vocab: int = 211, delay_s: float = 0.0,
                 max_batch: int = 0,
                 prefill_delay_per_ktok: float = 0.0):
        self.pool = PagePool(num_pages)
        self.page_size = int(page_size)
        self.prefix = PrefixCache(self.pool, self.page_size)
        self.vocab = int(vocab)
        # Per-batch wall-time floor: keeps a batch in flight long
        # enough for the chaos suite's mid-batch kill seams. Spread
        # over the batch's tokens (not slept up front), so the
        # incremental snapshot buffer below has real partial progress
        # for a mid-batch SIGKILL to leave behind.
        self.delay_s = float(delay_s)
        # Prompt-proportional prefill wall floor (seconds per 1024
        # COLD prompt tokens): a real engine's prefill scales with the
        # uncached prompt, so a 10k-token document blocks its batch
        # ~10x longer than a chat turn — the head-of-line effect the
        # long-context bench's SLO-scheduler arms measure. 0 keeps the
        # historical shape (prompts are free).
        self.prefill_delay_per_ktok = float(prefill_delay_per_ktok)
        # Decode-slot capacity model: a real engine runs at most
        # `max_batch` slots per continuous-batching round, so an
        # N-request batch costs ceil(N / max_batch) rounds of wall
        # time and requests past the cap don't see a first token until
        # a slot frees. 0 = unbounded (the historical shape: one
        # delay_s per engine batch no matter its size), which keeps
        # the chaos suite fast; the capacity bench sets it so replica
        # throughput is finite and saturation is measurable.
        if int(max_batch) < 0:
            raise ValueError(f"max_batch must be >= 0, got {max_batch}")
        self.max_batch = int(max_batch)
        self.last_stats: dict = self._zero_stats()
        # Slot migration (docs/scale-out.md "Slot migration & handoff"):
        # the stub keeps a per-ticket snapshot of each in-flight
        # request's progress — the control-plane half of the protocol,
        # token-cheap (no KV payload; the hash "model" regenerates KV
        # for free, so prompt+out IS the full portable state).
        self._snap_lock = threading.Lock()
        self._snapshots: dict[str, dict] = {}
        self._handoff = threading.Event()
        # Client-driven cancellation (docs/serving.md "Streaming &
        # cancellation"): ids land from any thread via :meth:`cancel`
        # (the cancel verb is engine-lock-free) and are checked at
        # every token boundary, so a mid-stream cancel tears a stub
        # request down with its partial tokens exactly like the real
        # engine.
        self._cancel_lock = threading.Lock()
        self._cancelled: set[str] = set()
        self._m_mig_saved = obs_metrics.counter(
            "tdt_migration_tokens_saved_total",
            "Generated tokens restored from a snapshot instead of "
            "re-generated (work a replay recovery would repeat).",
        )
        self._m_migrations = obs_metrics.counter(
            "tdt_migrations_total",
            "Slots exported for migration, by reason.",
            labels=("reason",),
        )

    def _zero_stats(self) -> dict:
        return {
            "decode_steps": 0,
            "prefill_tokens": 0,
            "generated_tokens": 0,
            "prefix_hit_tokens": 0,
            "kv_bytes_per_token": 0.0,
            "kv_dtype": "stub",
            "migrated_out": 0,
            "migrated_in": 0,
            "migrated_in_tokens": 0,
            "migration_fallbacks": 0,
            "cancelled_requests": 0,
        }

    def _pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def run(self, requests, *, results: bool = False):
        """Serve a batch; same contract as ``ContinuousEngine.run``.
        Accepts engine ``Request`` objects or ``(prompt, gen_len)``
        tuples. ``decode_steps`` counts emitted tokens (the stub has no
        batched decode, so steps == tokens). The batch delay is spread
        over its tokens, and each token updates the per-ticket snapshot
        buffer — so a mid-batch SIGKILL leaves resumable progress and a
        handoff request (:meth:`request_handoff`) exports mid-request."""
        stats = self._zero_stats()
        parsed = []
        for req in requests:
            prompt = getattr(req, "prompt", None)
            if prompt is None:
                prompt, gen_len = req
                req = None
            else:
                gen_len = req.gen_len
            parsed.append((req, prompt, int(gen_len)))
        # Capacity model: each `max_batch`-sized round costs one
        # delay_s (spread over ITS tokens), so an over-cap batch's
        # tail requests wait whole rounds for a slot — the queueing a
        # saturated replica really exhibits, visible wire-side as
        # first-token latency. max_batch=0 keeps the one-round shape.
        round_size = self.max_batch or max(len(parsed), 1)
        outs: list[RequestResult] = []
        for lo in range(0, max(len(parsed), 1), round_size):
            chunk = parsed[lo:lo + round_size]
            chunk_toks = sum(max(g, 1) for _, _, g in chunk)
            sleep = self.delay_s / max(chunk_toks, 1)
            for req, prompt, gen_len in chunk:
                if self._handoff.is_set():
                    # Not-yet-started requests hand back un-run. NOT
                    # counted as migrated_out — nothing was exported;
                    # the real engine's sweep makes the same
                    # distinction, so stub and ContinuousEngine fleets
                    # report one schema.
                    outs.append(RequestResult(
                        np.zeros(0, np.int32), "migrated",
                        "handoff drain before admission",
                        getattr(req, "snapshot", None),
                    ))
                    continue
                outs.append(
                    self._serve_one(req, prompt, gen_len, stats, sleep)
                )
        with self._snap_lock:
            self._snapshots = {}
        self._handoff.clear()  # one-shot, like the engine's _handoff_at
        # Cancels that raced past their request must not leak into
        # future batches reusing the same ticket id (the
        # ContinuousEngine's batch-scoped prune + cap, mirrored).
        batch_tids = {
            getattr(req, "ticket_id", None) for req, _, _ in parsed
        }
        batch_tids.discard(None)
        with self._cancel_lock:
            self._cancelled -= batch_tids
            if len(self._cancelled) > 4096:
                self._cancelled.clear()
        self.last_stats = stats
        stats["prefix_cache"] = dict(self.prefix.stats)
        stats["prefix_hit_rate"] = self.prefix.hit_rate
        stats["tree_pages"] = self.prefix.node_count
        stats["free_pages"] = len(self.pool.free)
        if results:
            return outs
        return [np.asarray(r.tokens, np.int32) for r in outs]

    def _serve_one(self, req, prompt, gen_len: int, stats: dict,
                   sleep: float) -> RequestResult:
        toks = [int(t) for t in prompt]
        s = len(toks)
        if s == 0 or gen_len <= 0:
            return RequestResult(
                np.zeros(0, np.int32), "unservable",
                "stub needs a non-empty prompt and gen_len >= 1",
            )
        # Snapshot resume (docs/scale-out.md "Slot migration &
        # handoff"): a valid snapshot's generated tokens are restored,
        # not re-generated; anything malformed/stale falls back to a
        # full replay from the prompt — the same contract as the real
        # engine's import path.
        tid = getattr(req, "ticket_id", None)
        if tid is not None and self._cancel_hit(tid):
            stats["cancelled_requests"] += 1
            return RequestResult(
                np.zeros(0, np.int32), "cancelled",
                "cancelled by client before admission",
            )
        out: list[int] = []
        snap = getattr(req, "snapshot", None)
        if snap is not None:
            restored = self._resume_tokens(snap, toks, gen_len)
            if restored is None:
                stats["migration_fallbacks"] += 1
            else:
                out = restored
                stats["migrated_in"] += 1
                stats["migrated_in_tokens"] += len(out)
                self._m_mig_saved.inc(len(out))
        total = self._pages_for(s + gen_len)
        # The production admission protocol: match (pins + hit
        # accounting), allocate the uncovered pages (LRU-evicting the
        # tree when the free list runs short), COW-finish, retire.
        m = self.prefix.match(toks)
        new = self.prefix.allocate(total - len(m.nodes))
        if new is None:
            self.prefix.release_match(m)
            return RequestResult(
                np.zeros(0, np.int32), "overloaded",
                f"stub pool cannot cover {total} pages",
            )
        matched = m.matched_len
        shared = list(m.nodes)
        self.prefix.finish_cow(m)
        pages = m.pages + new
        # A resumed request's KV is "shipped" (the hash model carries
        # none) — only a cold start pays the prefill.
        stats["prefill_tokens"] += 0 if out else s - matched
        stats["prefix_hit_tokens"] += matched
        cold = 0 if out else s - matched
        if self.prefill_delay_per_ktok and cold:
            time.sleep(self.prefill_delay_per_ktok * cold / 1024.0)
        ctx = toks + out
        # prefill→decode handoff: emit only the admission token, then
        # export (the engine's prefill_only contract). Never re-armed
        # on a resumed request — its prefill already happened.
        prefill_only = bool(getattr(req, "prefill_only", False)) and not out
        on_token = getattr(req, "on_token", None)
        migrated = None
        cancelled = False
        while len(out) < gen_len:
            if sleep:
                time.sleep(sleep)
            if self._handoff.is_set():
                migrated = "drain"
                break
            if tid is not None and self._cancel_hit(tid):
                cancelled = True
                break
            nxt = stub_next_token(ctx, self.vocab)
            out.append(nxt)
            ctx.append(nxt)
            if on_token is not None:
                # Streaming hook (docs/serving.md "Streaming &
                # cancellation"): the ContinuousEngine contract —
                # restored tokens never re-fire, a raising sink
                # detaches instead of failing the request.
                try:
                    on_token(len(out) - 1, int(nxt))
                except Exception:  # noqa: BLE001 — sink isolation
                    on_token = None
            stats["generated_tokens"] += 1
            stats["decode_steps"] += 1
            if tid is not None:
                with self._snap_lock:
                    self._snapshots[tid] = self._snapshot_of(
                        toks, out, gen_len, req
                    )
            if prefill_only and len(out) < gen_len:
                migrated = "prefill_handoff"
                break
        if cancelled:
            # Mid-stream cancel: partial tokens back to the caller,
            # every page back to the pool (nothing retires — a
            # cancelled chain must not poison later matches any more
            # than a failed one would).
            for node in shared:
                self.prefix.release_node(node)
            self.pool.release(pages[len(shared):])
            stats["cancelled_requests"] += 1
            return RequestResult(
                np.asarray(out, np.int32), "cancelled",
                f"cancelled by client after {len(out)} generated tokens",
            )
        if migrated:
            # Mid-request handoff: export the progress, release the
            # pages (nothing retires — the tree only caches completed
            # chains in the stub), hand the snapshot back.
            for node in shared:
                self.prefix.release_node(node)
            self.pool.release(pages[len(shared):])
            stats["migrated_out"] += 1
            self._m_migrations.inc(reason=migrated)
            return RequestResult(
                np.asarray(out, np.int32), "migrated",
                f"slot exported ({migrated})",
                self._snapshot_of(toks, out, gen_len, req),
            )
        # Cache prompt + fed-back generations, positions [0, s+gen-1)
        # — the same chain a real engine retires.
        chain = ctx[: s + gen_len - 1]
        nchain = self._pages_for(len(chain))
        self.prefix.retire_sequence(chain, pages[:nchain], shared)
        self.pool.release(pages[nchain:])
        return RequestResult(np.asarray(out, np.int32))

    @staticmethod
    def _snapshot_of(toks, out, gen_len, req) -> dict:
        return {
            "stub": True,
            "prompt": list(toks),
            "out": list(out),
            "gen_len": int(gen_len),
            "trace_id": getattr(req, "trace_id", None),
            "exported_at": time.time(),
        }

    def _resume_tokens(self, snap, toks, gen_len) -> list[int] | None:
        """Validate a snapshot against the request; None → replay."""
        try:
            if [int(t) for t in snap["prompt"]] != toks:
                return None
            out = [int(t) for t in snap["out"]]
        except (KeyError, TypeError, ValueError):
            return None
        if len(out) >= int(gen_len):
            return None
        return out

    # -- replica/server surface -------------------------------------------

    def cancel(self, ticket_ids) -> None:
        """Arm cancellation for the given ticket ids — thread-safe;
        the server's engine-lock-free cancel verb calls this mid-batch
        and the in-flight request stops at its next token boundary
        (the ContinuousEngine contract, docs/serving.md)."""
        ids = {str(t) for t in ticket_ids}
        if ids:
            with self._cancel_lock:
                self._cancelled |= ids

    def _cancel_hit(self, tid: str) -> bool:
        """Consume a pending cancellation for ``tid`` (ids are
        one-shot, like the engine's per-batch prune)."""
        with self._cancel_lock:
            if tid in self._cancelled:
                self._cancelled.discard(tid)
                return True
        return False

    def request_handoff(self, after_rounds: int = 0) -> None:
        """Arm the lossless-drain export (docs/scale-out.md "Slot
        migration & handoff"): the in-flight batch stops at the next
        token, exporting each request's progress as a snapshot.
        ``after_rounds`` is accepted for engine-surface parity (the
        stub has no scheduling rounds — it always stops at the next
        token boundary)."""
        del after_rounds
        self._handoff.set()

    def export_slots(self) -> dict:
        """Per-ticket progress snapshots of the in-flight batch — what
        the server's ``export_slots`` verb returns and the supervisor's
        crash recovery resumes from. Lock-guarded; safe mid-batch."""
        with self._snap_lock:
            return dict(self._snapshots)

    def prefix_digest(self) -> list:
        return self.prefix.prefix_digest()

    def drain(self) -> int:
        """Flush the radix tree back to the pool (replica drain)."""
        return self.prefix.flush()

    def audit(self, *, raise_on_violation: bool = False) -> list[str]:
        """Tree invariants + exact pool partition (no in-flight state
        survives a synchronous ``run``, so free ∪ tree must cover the
        pool whenever the engine lock is held)."""
        problems = list(self.prefix.audit())
        held = len(self.pool.free) + self.prefix.node_count
        if held != self.pool.num_pages:
            problems.append(
                f"pool partition broken: {len(self.pool.free)} free + "
                f"{self.prefix.node_count} tree != {self.pool.num_pages}"
            )
        if problems and raise_on_violation:
            from triton_distributed_tpu.models.paged_kv_cache import (
                PoolAuditError,
            )

            raise PoolAuditError("; ".join(problems))
        return problems
