"""Continuous batching over the paged KV pool.

Beyond-parity subsystem: the reference Engine (``models/engine.py:113``)
serves fixed batches; modern serving interleaves requests — admit a new
sequence the moment pool pages free up, evict on completion, and step
the union every iteration. Its paged cache
(``mega_triton_kernel/models/paged_kv_cache.py``) is the natural
substrate, and this module is the TPU build's admission/eviction loop on
top of ours.

Design: the decode step stays ONE jitted program over a fixed
``max_batch`` of slots (static shapes — XLA's requirement). Slot state
(page table rows, kv_len, free list) lives host-side; admission writes a
slot's table row + kv_len and prefises the prompt into its pages,
eviction releases the pages. Inactive slots keep table row 0 and point
at a reserved trash page, so their (masked-out) appends land harmlessly.

With ``prefix_cache=True`` two serving-path upgrades switch on
(docs/serving.md):

- **Radix prefix reuse**: finished sequences retire their pages into a
  :class:`~triton_distributed_tpu.models.prefix_cache.PrefixCache`
  instead of the free list; admission maps the longest cached prefix
  into the new slot's table row (refcounted, COW for partially matched
  tail pages) and prefills ONLY the suffix.
- **Chunked prefill**: the suffix runs through
  ``Qwen3.prefill_paged_chunk`` in fixed-width chunks with a decode
  step of the running batch between chunks, so a long cold prompt never
  stalls in-flight decodes for its whole prefill (``prefill_chunk=0``
  keeps one chunk per admission).

**Fault tolerance** (docs/serving.md "Fault tolerance"): requests fail
*individually*. An unservable, deadline-expired, shed, or crashed
request tears down only its own slot — private pages back to the pool,
prefix pins back to the tree, table row back to the trash page — and
surfaces a structured :class:`RequestResult` (status, reason, partial
tokens) via ``run(results=True)`` while every other request completes.
``run()`` ends with a pool/radix invariant audit (:meth:`audit`) so a
leak is caught at the batch that caused it, not three batches later.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
import weakref
from collections import Counter, deque

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import sampling
from triton_distributed_tpu.models.engine import (
    MegaDispatch,
    prefill_suffix_chunks,
)
from triton_distributed_tpu.models.stats import (
    STAT_METRIC_ALIASES,
    STAT_METRICS,
)
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs.timeline import Timeline, observe_request
from triton_distributed_tpu.models.paged_kv_cache import (
    PoolAuditError,
    audit_pool,
    copy_page,
    gather_pages,
    init_paged_cache,
    truncate_pages,
    write_page,
    write_prefill,
)
from triton_distributed_tpu.models.prefix_cache import (
    PrefixCache,
    PrefixMatch,
    node_chain,
    round_chunk,
)
from triton_distributed_tpu.models.qwen import Mode, Qwen3
from triton_distributed_tpu.runtime.faults import (
    FaultError,
    fault_point,
    mutate_point,
)
from triton_distributed_tpu.runtime.profiling import trace_span


@dataclasses.dataclass
class RequestError:
    """Structured failure: a machine-readable ``status`` plus a human
    ``reason``. Statuses: ``unservable`` (can never fit), ``overloaded``
    (shed by the bounded admission queue — retry with backoff),
    ``deadline_exceeded``, ``nan_logits`` (non-finite model output),
    ``failed`` (crash isolated to this request), ``aborted`` (the
    engine loop itself died)."""

    status: str
    reason: str


@dataclasses.dataclass
class RequestResult:
    """One request's outcome: generated tokens (PARTIAL when the
    request failed mid-decode — everything emitted before the failure)
    and its status. ``status == "migrated"`` means the slot was
    exported instead of finished (handoff drain, prefill→decode
    handoff): ``snapshot`` then carries the portable slot state
    (``models/slot_state.py`` wire form) a different engine resumes
    from — the serving tier re-dispatches it, so a migrated result
    never reaches a client."""

    tokens: np.ndarray
    status: str = "ok"
    reason: str = ""
    snapshot: dict | None = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    @property
    def error(self) -> RequestError | None:
        return None if self.ok else RequestError(self.status, self.reason)


# Process-wide trace-id allocator: ids must stay unique across engines
# and replicas (the router fans one batch over several), or the
# device-task tagging would alias two requests into one thread of the
# merged timeline.
_TRACE_IDS = itertools.count(1)


def _model_fingerprint(model) -> str:
    """Identity of the weights a durable tier entry was produced
    under: class name, every param leaf's shape/dtype (architecture),
    and a value sample spread across the whole tree — leaves taken at
    an even stride (the last leaf, typically the LM head, always
    included) and, within each, elements strided across the FULL
    flattened tensor, so a scan-stacked ``[L, ...]`` leaf samples
    every layer band, not just layer 0. A fine-tune's gradients are
    dense, so a partial update (later layers only, head only) still
    moves sampled bytes. Tier entries carry this so a ``tier_dir``
    reused across a weight update faults back NOTHING instead of
    stale KV — cached attention state from old weights under new
    weights is silently wrong bits, exactly what the tier promises
    never to serve. A few tiny host fetches, computed once per engine
    when a tier is attached."""
    h = hashlib.sha1(type(model).__name__.encode())
    leaves = jax.tree_util.tree_leaves(getattr(model, "params", None))
    for leaf in leaves:
        h.update(str(getattr(leaf, "shape", ())).encode())
        h.update(str(getattr(leaf, "dtype", "")).encode())
    sampled = leaves[::max(1, len(leaves) // 8)][:8]
    if leaves and leaves[-1] is not sampled[-1]:
        sampled.append(leaves[-1])
    for leaf in sampled:
        try:
            flat = jnp.ravel(leaf)
            stride = max(1, int(flat.shape[0]) // 64)
            sample = np.asarray(jax.device_get(flat[::stride][:64]))
        except Exception:  # noqa: BLE001 — identity is best-effort;
            continue  # shapes/dtypes alone still gate architecture
        h.update(sample.tobytes())
    return h.hexdigest()


class RequestFailedError(RuntimeError):
    """Raised by ``run(results=False)`` when requests failed: the
    legacy list-of-arrays interface has no failure channel, so the
    engine completes what it can, tears the failures down cleanly, and
    raises this with every per-request failure attached."""

    def __init__(self, failures):
        self.failures = failures  # list[(index, Request)]
        msgs = "; ".join(
            f"request {i}: [{r.status}] {r.reason}" for i, r in failures
        )
        super().__init__(f"{len(failures)} request(s) failed: {msgs}")


# NaN/Inf logits raised by the admission sampler and the speculative
# verify guard; both map to a structured `nan_logits` request failure.
_NonFiniteLogits = sampling.NonFiniteLogitsError

# Finite mask + greedy argmax of a decode step's logits in ONE device
# program: the NaN guard rides the token fetch the loop already pays.
_finite_greedy = jax.jit(
    lambda logits: (
        jnp.isfinite(logits).all(axis=-1), sampling.greedy(logits)
    )
)

# Serving counters mirrored live into the process metrics registry
# (docs/observability.md): same numbers as ``last_stats``, but
# scrapeable through ``{"cmd": "metrics"}`` MID-generation instead of
# only after a run() returns. Keys match ``_zero_stats``; the (name,
# help) table lives in models/stats.py so Engine.serve shares the
# exact declarations.

# Event-ring kind for each terminal failure status (PR 3 taxonomy);
# statuses not listed emit a generic ``request_failed`` event.
_FAIL_EVENT_KIND = {
    "overloaded": "shed",
    "deadline_exceeded": "deadline",
    "nan_logits": "nan_guard",
    "cancelled": "cancel",
}


@dataclasses.dataclass
class Request:
    """One generation request and its accumulated output.

    ``temperature``/``top_p``/``top_k`` override the engine's defaults
    for THIS request (None → engine default) — mixed greedy/sampled
    batches decode together, each slot sampled under its own knobs.
    ``deadline_s`` is a per-request wall-clock budget measured from
    ``run()`` entry; an expired request fails with
    ``deadline_exceeded`` and its partial tokens.
    """

    prompt: np.ndarray  # [S] int32
    gen_len: int
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    deadline_s: float | None = None
    out: list[int] = dataclasses.field(default_factory=list)
    slot: int | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    # Prefix-cache bookkeeping: tree nodes whose pages lead this
    # request's page list (refcounted for the request's lifetime).
    shared_nodes: list = dataclasses.field(default_factory=list)
    # Speculative-decoding state (``SpecState``), attached at admission
    # when the engine runs with ``speculative=K``.
    spec: object | None = None
    # Failure channel (``ok`` until something fails this request).
    status: str = "ok"
    reason: str = ""
    # Telemetry (docs/observability.md): lifecycle stamps yielding the
    # queue-wait/TTFT/TPOT/e2e histograms. The server stamps enqueue at
    # payload decode; ``run()`` backfills for direct callers.
    timeline: Timeline | None = dataclasses.field(default=None, repr=False)
    deadline_at: float | None = dataclasses.field(default=None, repr=False)
    # Request trace id (docs/observability.md "Device task tracer"):
    # follows the request server → router → replica → engine →
    # individual device tasks. Clients may supply one in the payload
    # (``trace_ids``); ``run()`` assigns ``req-<n>`` when absent.
    trace_id: str | None = None
    # Slot migration (docs/scale-out.md "Slot migration & handoff"):
    # ``snapshot`` holds portable slot state (slot_state.py wire form)
    # — set on INPUT to resume a migrated request (admission imports it
    # instead of prefilling), and set on OUTPUT by a handoff export
    # (``status`` flips to "migrated" and ``result()`` ships it).
    # ``prefill_only`` makes the engine export the slot right after
    # admission — the prefill→decode handoff's first half.
    # ``ticket_id`` keys the engine's incremental snapshot buffer so
    # the supervisor's crash recovery can match snapshots to tickets.
    snapshot: dict | None = dataclasses.field(default=None, repr=False)
    prefill_only: bool = False
    ticket_id: str | None = None
    # SLO class (obs/slo.py, serving/pools.py): admission class the
    # goodput yardstick and the pool scheduler judge this request
    # under. The engine itself never branches on it — it rides along
    # so telemetry and router-side scheduling see one name end to end.
    slo_class: str | None = None
    # Per-request PRNG state: sampled requests draw from their OWN key
    # via fold_in(key, key_step) — never the engine-global key — so a
    # migrated slot's seeded-sampled continuation replays the exact
    # draws the un-migrated run would have made. Assigned lazily from
    # the engine key on first sampled draw (greedy requests never pay).
    key: object | None = dataclasses.field(default=None, repr=False)
    key_step: int = 0
    # Streaming (docs/serving.md "Streaming & cancellation"): called
    # ``on_token(index, token_id)`` on the engine thread the moment a
    # token is EMITTED (appended to ``out``) — the server's streaming
    # path writes one wire frame per call. Tokens RESTORED from a
    # migration snapshot never fire it (they were already delivered);
    # the callback must not raise — a broken sink detaches itself
    # instead of failing the request (see ``_emit_token``).
    on_token: object | None = dataclasses.field(default=None, repr=False)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.gen_len

    def result(self) -> RequestResult:
        return RequestResult(
            np.asarray(self.out, np.int32), self.status, self.reason,
            self.snapshot if self.status == "migrated" else None,
        )


# Tier-key namespace for sharded long-context slots: every demoted page
# of one sharded admission keys as "<uid>:<page-index>", so a slot
# re-admitted into the same engine (or a second sharded slot) can never
# collide with a predecessor's leftovers.
_LONG_UIDS = itertools.count(1)


@dataclasses.dataclass
class _LongSlot:
    """Sharded-slot bookkeeping (docs/serving.md "Long-context
    serving"): a slot whose KV exceeds ``rank_page_budget`` keeps a
    RESIDENT paged window (``req.pages``, local positions) plus
    ``cold`` pages demoted to the KV tier. Host ``_kv_len[slot]`` stays
    the ABSOLUTE sequence length; the resident region holds
    ``_kv_len[slot] - cold * page_size`` tokens. The DEVICE kv_len /
    table row for the slot are forced to zero (``_sync_tables``) —
    batched decode must treat a sharded slot as empty (its append lands
    on the trash page; its batched logits are overwritten by the
    per-slot sharded program's)."""

    uid: int
    cold: int = 0           # pages demoted (tokens [0, cold*page) cold)
    # Cached cold window: (k, v, ks, vs, bucket_pages) device arrays
    # [L, Hkv, bucket_pages*page, hd] (scales [L, Hkv, bucket_pages]);
    # invalidated (None) whenever another page demotes.
    view: tuple | None = None


@dataclasses.dataclass
class _MegaPlan:
    """One composed megakernel launch: the row mapping (launch row →
    engine slot), the batch-bucket width, and the operand set. Built
    from host truth by ``_mega_plan``; a resident chain reuses the
    pending launch's plan with only ``n_valid`` re-projected (the slot
    set cannot change between issue and drain — every slot-state
    mutation site drains first)."""

    rows: list
    B: int
    compact: bool       # B < max_batch: compacted table/kv_len/tok
    sampled: bool
    filtered: bool      # in-kernel top-k/top-p (single-rank only)
    eos: bool           # device stop-token test + halt chaining
    temps: np.ndarray   # [B] per-row temperature (0 = greedy row)
    n_valid: np.ndarray  # [B] kept-row counts fed to append_n
    sampcfg: np.ndarray | None  # [B, 4] when filtered
    stop_tok: np.ndarray | None  # [B] when eos


@dataclasses.dataclass
class _MegaLaunch:
    """An issued — possibly still in-flight — NS-step launch: what the
    resident pipeline holds between issue and drain. ``toks``/``ss``/
    ``halt``/``cache``/``ring`` are device arrays nothing has synced
    on; ``cache`` is the launch's (bucket-shaped) output cache the
    NEXT chained launch donates."""

    plan: _MegaPlan
    toks: object        # [NS, B] device
    cache: object       # PagedKVCache (bucket-shaped)
    ss: object | None   # [B] first stop-token step (NS = never)
    halt: object | None  # [B] halt bits chained into the next launch
    ring: object | None  # device trace ring (kernel_trace only)
    t0: float
    doorbell: int | None
    trace_ids: dict


class ContinuousEngine(MegaDispatch):
    """Admission/eviction serving loop over the paged pool.

    ``max_batch`` decode slots share ``num_pages`` pool pages; a request
    is admitted when a slot AND enough pages for its prompt+gen_len are
    free (cached prefix pages count as free coverage — they are mapped,
    not allocated). Page 0 is reserved as the trash page for inactive
    slots.

    ``max_queue`` bounds the admission queue: requests beyond it are
    shed with a structured ``overloaded`` error instead of wedging the
    batch (None → unbounded).
    """

    NS = 8  # megakernel multi-step launch width

    # Live engines, auditable by the shared pytest fixture
    # (tests/conftest.py) after every test.
    _live: "weakref.WeakSet[ContinuousEngine]" = weakref.WeakSet()

    def __init__(
        self,
        model: Qwen3,
        *,
        max_batch: int = 4,
        page_size: int = 128,
        max_length: int | None = None,
        num_pages: int | None = None,
        mode: str = "xla",  # Mode or "mega" (megakernel decode)
        temperature: float = 0.0,
        top_p: float = 1.0,
        top_k: int = 0,
        eos_id: int | None = None,
        seed: int = 0,
        mega_cfg=None,
        prefix_cache: bool = False,
        prefill_chunk: int = 0,
        speculative: int = 0,
        spec_width: int = 4,
        max_queue: int | None = None,
        kv_dtype: str | None = None,
        kernel_trace: bool = False,
        snapshot_every: int = 0,
        tier_bytes: int = 0,
        tier_dir: str | None = None,
        tier=None,
        fabric=None,
        handoff_batch: bool = True,
        ns: int = 8,
        mega_buckets: bool = True,
        resident: bool = False,
        cp: int = 1,
        rank_page_budget: int = 0,
    ):
        self.model = model
        self.mode = mode
        self.mega_cfg = mega_cfg
        # Resident decode (docs/megakernel.md "Resident decode"): the
        # NS launch width becomes a knob (perf/mega_serve_bench.py
        # sweeps it), batch buckets give a 2-slot round a 2-wide launch
        # program instead of the max_batch-wide one, and
        # ``resident=True`` pipelines launches through a host work
        # ring — the host pushes admit/retire/cancel items + one
        # doorbell per round, issues launch i+1 off launch i's device
        # outputs, and syncs only to drain emitted tokens.
        if int(ns) < 1:
            raise ValueError(f"ns must be >= 1, got {ns}")
        self.NS = int(ns)
        self.mega_buckets = bool(mega_buckets)
        self.resident = bool(resident)
        if resident and mode != "mega":
            raise ValueError(
                "resident=True requires mode='mega' (the resident loop "
                "pipelines megakernel NS-step launches through the host "
                "work ring; the xla/pallas decode paths have no device "
                "loop to keep resident)"
            )
        if resident:
            from triton_distributed_tpu.megakernel.ring import WorkRing

            self._ring = WorkRing()
            self._ring_gauge = obs_metrics.gauge(
                "tdt_mega_ring_occupancy",
                "Host work-ring occupancy at the last doorbell publish.",
            )
        else:
            self._ring = None
            self._ring_gauge = None
        self._pend = None  # in-flight resident launch (depth-1 pipeline)
        # Device task tracer (docs/observability.md "Device task
        # tracer"): mega launches carry an in-kernel trace ring; every
        # launch's ring is folded into tdt_mega_task_seconds /
        # tdt_mega_overlap_exposure and kept (bounded) for the
        # server's {"cmd": "kernel_trace"} verb and the merged chrome
        # timeline (plumbing shared with Engine via MegaDispatch). Off
        # by default: the untraced build is bit-identical to PR 7's.
        self._init_kernel_trace(kernel_trace, mode)
        self.temperature = temperature
        self.top_p = top_p
        self.top_k = top_k
        # Speculative decoding (docs/serving.md): per-slot n-gram
        # drafts verified through the chunk-prefill path; rounds with
        # no draft anywhere fall back to the batched decode step. The
        # ONE remaining mega exclusion (docs/megakernel.md "Serving
        # fast path" composition matrix): speculation's chunked
        # verify+rollback steps slots at different paces, while a mega
        # launch advances every slot NS tokens in lockstep — and the
        # NS-amortized launch already buys the dispatch saving
        # speculation would chase.
        if speculative and mode == "mega":
            raise ValueError(
                "speculative=K does not compose with mode='mega': the "
                "NS-step fused launch advances all slots in lockstep "
                "and already amortizes per-step dispatch — the resident "
                "work ring splices whole slots between rounds, never "
                "a mid-launch verify/rollback; run speculative with "
                "mode='xla'/'pallas', or drop speculative to serve "
                "through the megakernel (docs/megakernel.md)"
            )
        self.speculative = int(speculative)
        # Quantized KV storage (docs/serving.md "Quantized KV cache"):
        # int8 pool + per-page-per-head scales — halves the bytes every
        # decode step streams AND doubles how many tokens the same pool
        # HBM holds, so the radix tree retains more prefixes and more
        # slots admit before shedding. Explicit knob wins over
        # ``cfg.kv_dtype``. Composes with mode='mega': the fused decode
        # dequantizes the int8 pool in-kernel through the per-page
        # scales (PR 7 lifted the old full-width exclusion).
        self.kv_dtype = kv_dtype if kv_dtype is not None else (
            model.cfg.kv_dtype
        )
        # Tree speculation (docs/serving.md "Speculative decoding"):
        # with ``spec_width > 1`` a slot whose radix tree / KV tier
        # remembers several continuations of its suffix drafts them as
        # a token TRIE and verifies every branch in the one chunk
        # forward (the pad rows a linear draft wastes carry the extra
        # branches). Full-width pools only: the commit is a KV
        # row-move, and ``quantized_row_scatter``'s reset-scales-at-
        # offset-0 semantics make moved int8 rows unrepresentable —
        # quantized pools keep width-1 chains (today's linear path,
        # bit-for-bit).
        self.spec_width = max(int(spec_width), 1)
        self._spec_tree = (
            bool(speculative) and self.spec_width > 1
            and self.kv_dtype is None
        )
        self.eos_id = eos_id
        self.key = jax.random.key(seed)
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_length = max_length or model.cfg.max_length
        if self.max_length % page_size:
            raise ValueError(
                f"max_length {self.max_length} is not a multiple of "
                f"page_size {page_size}: pages_per_seq would silently "
                f"truncate to {self.max_length // page_size} and the "
                f"tail tokens would have no page — pick an aligned pair"
            )
        self.pps = self.max_length // page_size
        self.max_queue = max_queue
        # Long-context serving (docs/serving.md "Long-context
        # serving"): ``cp`` shards one request's prefill over cp
        # virtual ranks with the block-KV exchange fired split-phase
        # under the next block's attention; ``rank_page_budget``
        # (TOKENS per rank) turns over-budget slots into SHARDED slots
        # — a resident paged window plus tier-demoted cold pages,
        # decoded through the lse_combine partial merge.
        self.cp = int(cp)
        if self.cp < 1:
            raise ValueError(f"cp must be >= 1, got {cp}")
        if self.cp > 1 and (mode == "mega" or resident or speculative):
            raise ValueError(
                "cp > 1 composes with the chunked xla/pallas prefill "
                "path only: mode='mega', resident=True and "
                "speculative=K all drive the slot through programs "
                "that bypass the per-chunk exchange schedule"
            )
        if self.cp > 1 and not prefix_cache:
            raise ValueError(
                "cp > 1 requires prefix_cache=True: context-parallel "
                "prefill rides the chunked suffix-prefill path, and "
                "without the radix tree admission prefills dense in "
                "one batched program (no per-chunk exchange to "
                "overlap)"
            )
        self.cp_tracer = None  # CPTracer of the LAST cp>1 prefill
        self.rank_page_budget = int(rank_page_budget)
        if self.rank_page_budget:
            if self.rank_page_budget % page_size:
                raise ValueError(
                    f"rank_page_budget {self.rank_page_budget} is not "
                    f"a multiple of page_size {page_size}: the budget "
                    f"is demoted page-by-page, so a ragged budget "
                    f"would strand a partial page — pick an aligned "
                    f"pair"
                )
            if self.rank_page_budget < 2 * page_size:
                raise ValueError(
                    f"rank_page_budget {self.rank_page_budget} must "
                    f"cover >= 2 pages of page_size {page_size} (one "
                    f"write page + one full page to demote)"
                )
            if tier is None and not (tier_bytes or tier_dir):
                raise ValueError(
                    "rank_page_budget requires a KV tier (tier=, "
                    "tier_bytes= or tier_dir=): demoted cold pages "
                    "must land somewhere a fault can bring them back "
                    "from"
                )
            if mode == "mega" or resident or speculative:
                raise ValueError(
                    "rank_page_budget composes with the xla/pallas "
                    "decode paths only: sharded slots decode through "
                    "a per-slot partial-merge program the mega/"
                    "resident/speculative launchers do not run"
                )
            if round_chunk(page_size) != page_size:
                raise ValueError(
                    f"rank_page_budget needs a chunk-alignable "
                    f"page_size (multiple of 16, or of 128 past 128); "
                    f"got {page_size}"
                )
        self.budget_pages = self.rank_page_budget // page_size
        # slot -> _LongSlot for slots decoding in sharded mode.
        self._longctx: dict[int, "_LongSlot"] = {}
        # Handoff-burst batching (docs/scale-out.md "Disaggregated
        # pools & autoscaling"): an armed drain sweep exports every
        # active slot through ONE concatenated page gather
        # (slot_state.export_slots_batch) instead of per-slot serial
        # round trips. Bit-identical either way; the flag exists so
        # perf/pools_bench.py can measure the wall delta.
        self.handoff_batch = bool(handoff_batch)

        # +1: page 0 is reserved as the trash page every inactive slot's
        # table points at, and must not shave serviceable capacity.
        n_pages = (num_pages or max_batch * self.pps) + 1
        self.cache, self.pool = init_paged_cache(
            model.cfg, max_batch, model.ctx, model.axis,
            max_length=self.max_length, page_size=page_size,
            num_pages=n_pages, assign_pages=False,
            kv_dtype=self.kv_dtype,
        )
        self.pool.free = [p for p in self.pool.free if p != 0]
        self._capacity = len(self.pool.free)
        self._table = np.zeros((max_batch, self.pps), np.int32)
        self._kv_len = np.zeros((max_batch,), np.int32)
        self._tok = np.zeros((max_batch,), np.int32)
        self._slots: list[Request | None] = [None] * max_batch
        self.prefix = PrefixCache(self.pool, page_size) if prefix_cache else None
        # Durable KV tier (docs/serving.md "Tiered KV"): a host-RAM
        # (and optionally disk) PageStore behind the radix tree —
        # evicted prefix pages spill into it instead of dropping to
        # nothing, admission faults tier-hit pages back cheaper than
        # re-prefill, and the incremental snapshot buffer persists
        # through the same store. ``tier=`` accepts a pre-built (or
        # shared) store; otherwise ``tier_bytes``/``tier_dir`` build
        # one. Off (None) keeps every pre-tier code path untouched.
        # Owned = built from this engine's knobs, so every snap entry
        # in it is this engine's (incl. leftovers a crashed previous
        # process wrote under the same dir — see run()'s start-clear).
        # A ``tier=`` store may be shared: only our own keys are ours.
        self._tier_owned = tier is None and bool(tier_bytes or tier_dir)
        if tier is None and (tier_bytes or tier_dir):
            from triton_distributed_tpu.models.kv_tier import PageStore

            # fsync=False: spills and snapshot write-throughs run ON
            # the scheduling loop — the atomic rename alone gives
            # process-crash durability (what restart resume needs),
            # and an OS crash can only tear an entry the CRC drops.
            # The supervisor's resume store keeps fsync (its writes
            # ride the monitor thread, off any decode path).
            tier = PageStore(capacity_bytes=tier_bytes or (64 << 20),
                             dir=tier_dir, fsync=False)
        self.tier = tier
        # KV fabric (docs/scale-out.md "KV fabric"): a
        # ``kv_tier.FabricClient`` consulted by ``_tier_fill`` on a
        # LOCAL tier miss — peers' tier entries are pulled over the
        # wire (or in-process), validated through the same codec +
        # geometry/fingerprint checks as local entries, and grafted
        # identically. None (default) keeps every single-replica path
        # untouched; a fabric without a tier is ignored (nowhere to
        # graft through).
        self.fabric = fabric if tier is not None else None
        self._tier_snap_keys: set[str] = set()
        # Weight identity for durable entries (computed only when a
        # tier is attached — one small host fetch): spilled pages and
        # durable snapshots are valid under THESE weights only.
        self._tier_fp = (
            _model_fingerprint(model) if self.tier is not None else None
        )
        # One-shot leftover sweep latch — see run()'s start-clear.
        self._tier_swept = False
        if self.prefix is not None and self.tier is not None:
            self.prefix.spill_fn = self._spill_page
        self.prefill_chunk = round_chunk(prefill_chunk) if prefill_chunk else 0
        # Dense batch-1 prefill scratch — only the legacy (non-prefix)
        # admission path scatters through it; the chunked path writes
        # pages directly.
        self._dense1 = None if prefix_cache else model.new_cache(
            1, self.max_length
        )
        # Lazy megakernel multi-step programs, keyed by (sampled,
        # filtered, eos, ring, bucket_B) — greedy rounds must not
        # consume PRNG keys, or temperature=0 runs would lose their
        # seeded determinism, and each batch bucket compiles its own
        # (narrower) program.
        self._multi_fns: dict = {}
        self.stats = self._zero_stats()
        # Metric handles resolved ONCE: the hot decode loop pays a dict
        # lookup + inc per _bump, not a registry get-or-create.
        # Registry.clear zeroes series in place, so the handles stay
        # valid across test resets.
        self._metric_handles = {
            key: [obs_metrics.counter(name, help)]
            for key, (name, help) in STAT_METRICS.items()
        }
        # Fleet-dashboard aliases: extra registry names incrementing in
        # lockstep with their primary (stats.py STAT_METRIC_ALIASES).
        for key, aliases in STAT_METRIC_ALIASES.items():
            self._metric_handles[key].extend(
                obs_metrics.counter(name, help) for name, help in aliases
            )
        # Touch every unlabeled series at 0 so the full catalog renders
        # from the first scrape — a counter that never fired (say,
        # tree branch-accepts on a cold engine) must read 0 on the
        # dashboard, not be indistinguishable from "not exported".
        for handles in self._metric_handles.values():
            for handle in handles:
                handle.inc(0)
        # Cumulative accept rate as a scrape-friendly gauge (the ratio
        # of two counters is a dashboard recording rule away, but spec
        # health is the first thing a tree-speculation rollout watches).
        # Last-write-wins and UNLABELED like the gauges below.
        self._spec_accept_gauge = obs_metrics.gauge(
            "tdt_spec_accept_rate",
            "Cumulative speculative accept rate (accepted / drafted) "
            "of this process's serving engine.",
        )
        # Last-write-wins and UNLABELED by design: a serving process
        # hosts one engine (ModelServer owns exactly one), so one
        # series is the truth there; with several engines in-process
        # (the test suite) the gauge flaps to whichever synced last —
        # a per-engine label would instead leak a series per engine
        # into the never-GC'd process registry (docs/observability.md).
        self._free_pages_gauge = obs_metrics.gauge(
            "tdt_engine_free_pages", "Pool pages on the free list."
        )
        # NS-amortization gauge: decode steps emitted per mega launch
        # over the current run — NS while every round launches fused,
        # sagging toward 1 as tail/filter fallbacks mix in.
        # Last-write-wins and UNLABELED like _free_pages_gauge above
        # (same rationale): one engine per serving process is the
        # deployment shape; an in-process replica fleet's scrape shows
        # the last replica to launch, and the per-replica truth rides
        # the stats verb's per-replica `mega_launches` counters.
        self._ns_gauge = obs_metrics.gauge(
            "tdt_mega_ns_amortization",
            "Decode steps per megakernel launch (current run).",
        )
        # Slot migration (docs/scale-out.md "Slot migration & handoff"):
        # ``snapshot_every=N`` (rounds, 0 = off) keeps an incremental
        # per-ticket snapshot buffer the server's ``export_slots`` verb
        # reads — the supervisor's crash-recovery feed. ``_handoff_at``
        # arms the lossless-drain sweep: at the first scheduling round
        # >= it, every active slot exports instead of finishing here.
        # MoE serving (docs/serving.md "MoE serving"): top_k per routed
        # token position — 0 for dense models; gates the
        # moe_routed_tokens bumps and the last_stats expert keys.
        self._moe_k = (
            model.cfg.num_experts_per_tok
            if getattr(model.cfg, "num_experts", 0) else 0
        )
        self.snapshot_every = int(snapshot_every)
        self._handoff_at: int | None = None
        self._round = 0
        self._snap_lock = threading.Lock()
        self._snapshots: dict[str, dict] = {}
        # Client-driven cancellation (docs/serving.md "Streaming &
        # cancellation"): ticket ids whose requests should tear down
        # at the next scheduling round. Written from ANY thread via
        # :meth:`cancel` (the server's cancel verb and the streaming
        # disconnect path land here mid-batch); consumed on the engine
        # thread by ``_apply_cancels``.
        self._cancel_lock = threading.Lock()
        self._cancelled: set[str] = set()
        self._m_migrations = obs_metrics.counter(
            "tdt_migrations_total",
            "Slots exported for migration, by reason.",
            labels=("reason",),
        )
        self._m_mig_bytes = obs_metrics.histogram(
            "tdt_migration_bytes",
            "KV payload bytes shipped per exported slot.",
            buckets=obs_metrics.SIZE_BUCKETS,
        )
        self._m_mig_handoff = obs_metrics.histogram(
            "tdt_migration_handoff_seconds",
            "Wall time from slot export to its import elsewhere.",
        )
        self._m_mig_saved = obs_metrics.counter(
            "tdt_migration_tokens_saved_total",
            "Generated tokens restored from a snapshot instead of "
            "re-generated (work a replay recovery would repeat).",
        )
        self._m_mig_fallbacks = obs_metrics.counter(
            "tdt_migration_fallbacks_total",
            "Snapshot imports that fell back to replay-from-prompt.",
        )
        ContinuousEngine._live.add(self)

    @staticmethod
    def _zero_stats() -> dict:
        return {
            "admitted": 0,
            "decode_steps": 0,
            "prefill_tokens": 0,
            "generated_tokens": 0,
            "prefill_chunks": 0,
            "prefix_hit_tokens": 0,
            "pages_cow_copied": 0,
            "admission_stalls": 0,
            "spec_verify_steps": 0,
            "spec_draft_tokens": 0,
            "spec_accepted_tokens": 0,
            "spec_rollback_tokens": 0,
            # Tree-speculation ledger (docs/serving.md "Speculative
            # decoding"): multi-branch rounds, drafted trie nodes,
            # cumulative drafted depth, and rounds whose accepted path
            # left the primary branch (the row-move commits).
            "spec_tree_rounds": 0,
            "spec_tree_nodes": 0,
            "spec_tree_depth": 0,
            "spec_tree_branch_accepts": 0,
            # Fault-tolerance ledger (docs/serving.md "Fault tolerance").
            "failed_requests": 0,
            "cancelled_requests": 0,
            "shed_requests": 0,
            "deadline_expired": 0,
            "nonfinite_logits": 0,
            "decode_faults": 0,
            # Megakernel fast-path ledger (mode="mega" only): fused
            # NS-step launches vs single-step fallback rounds.
            "mega_launches": 0,
            "mega_fallback_steps": 0,
            # Device task tracer: launches whose ring was decoded.
            "mega_trace_launches": 0,
            # Resident-decode ledger (docs/megakernel.md "Resident
            # decode"): work-ring items/doorbells, in-kernel filtered
            # rounds, device stop-token retires, pipelined resident
            # rounds, and bucket-program launches.
            "mega_ring_items": 0,
            "mega_ring_doorbells": 0,
            "mega_ring_host_drains": 0,
            "mega_device_retires": 0,
            "mega_resident_rounds": 0,
            "mega_bucket_launches": 0,
            "mega_filtered_rounds": 0,
            # Slot-migration ledger (docs/scale-out.md "Slot migration
            # & handoff"): exports, imports, generated tokens restored
            # without re-generation, and imports that fell back to a
            # full replay from the prompt.
            "migrated_out": 0,
            "migrated_in": 0,
            "migrated_in_tokens": 0,
            "migration_fallbacks": 0,
            # MoE serving ledger (docs/serving.md "MoE serving"):
            # routed expert assignments (token positions through the
            # MoE FFN × top_k) and EP a2a drops — always 0 on the
            # lossless serving paths, surfaced so a capacity-mode EP
            # experiment can never hide overflow.
            "moe_routed_tokens": 0,
            "a2a_dropped": 0,
            # Durable KV tier ledger (docs/serving.md "Tiered KV"):
            # evictions demoted to the tier, and admissions extended by
            # faulting those pages back instead of re-prefilling.
            "tier_spilled_pages": 0,
            "tier_hits": 0,
            "tier_faults": 0,
            "tier_bytes": 0,
            # KV fabric (docs/scale-out.md "KV fabric"): the subset of
            # tier_faults whose entry came from a PEER replica's tier.
            "tier_remote_pages": 0,
            # Long-context ledger (docs/serving.md "Long-context
            # serving"): cp>1 prefills and their split-phase exchange
            # accounting, plus sharded-slot admissions, cold-page
            # demotes/faults and per-slot decode programs.
            "cp_prefills": 0,
            "cp_blocks": 0,
            "cp_exchange_bytes": 0,
            "cp_exchange_us": 0,
            "cp_hidden_us": 0,
            "longctx_sharded_slots": 0,
            "longctx_demoted_pages": 0,
            "longctx_tier_faults": 0,
            "longctx_tier_bytes": 0,
            "longctx_decode_steps": 0,
        }

    @property
    def last_stats(self) -> dict:
        """Serving counters (parity: ``Engine.last_stats``): admission /
        prefill work done, prefix-cache reuse, COW copies, stalls, the
        speculative accept/rollback ledger, and the fault-tolerance
        ledger (failed/shed/expired requests, non-finite logits,
        isolated decode faults)."""
        stats = dict(self.stats)
        stats["free_pages"] = len(self.pool.free)
        from triton_distributed_tpu.models.paged_kv_cache import (
            kv_bytes_per_token,
        )

        stats["kv_bytes_per_token"] = kv_bytes_per_token(self.cache)
        stats["kv_dtype"] = (
            self.kv_dtype or str(jnp.dtype(self.cache.k_pages.dtype))
        )
        if self.prefix is not None:
            stats["prefix_cache"] = dict(self.prefix.stats)
            stats["prefix_hit_rate"] = self.prefix.hit_rate
            stats["tree_pages"] = self.prefix.node_count
        if self.speculative:
            stats["spec_accept_rate"] = (
                stats["spec_accepted_tokens"]
                / max(stats["spec_draft_tokens"], 1)
            )
            # Target forwards actually paid for decode: batched steps
            # plus per-slot verify chunks (mega's NS-per-launch counting
            # never mixes in — speculative excludes mega at the ctor).
            stats["target_steps"] = (
                stats["decode_steps"] + stats["spec_verify_steps"]
            )
        if self._moe_k:
            stats["num_experts"] = self.model.cfg.num_experts
            stats["experts_per_tok"] = self._moe_k
        if self.tier is not None:
            stats["tier"] = self.tier.snapshot()
        if self.fabric is not None:
            stats["fabric"] = self.fabric.snapshot()
        return stats

    # -- telemetry ---------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        """Increment a serving counter in ``stats`` AND its mirrored
        registry metric, so the same number is visible per-run
        (``last_stats``) and fleet-wide (``{"cmd": "metrics"}``).
        ``inc`` no-ops when telemetry is disabled."""
        self.stats[key] += n
        for handle in self._metric_handles[key]:
            handle.inc(n)

    def _finish_obs(self, req: Request) -> None:
        """Latch a request's terminal timeline stamp and fold it into
        the latency histograms — exactly once per request, whichever
        teardown path gets here first."""
        tl = req.timeline
        if tl is None:
            return
        tl.tokens_in = len(req.prompt)
        tl.tokens_out = len(req.out)
        if tl.finish(req.status):
            observe_request(tl)

    # -- slot management -------------------------------------------------

    def _ring_push(self, kind: str, slot: int, arg: int = 0) -> None:
        """Queue one work item for the resident device loop — no-op
        without a ring. ``kind``: "admit" | "retire" | "cancel". The
        next launch's doorbell publish covers it (megakernel/ring.py)."""
        if self._ring is None:
            return
        from triton_distributed_tpu.megakernel import ring as _ring_mod

        kinds = {"admit": _ring_mod.RING_ADMIT,
                 "retire": _ring_mod.RING_RETIRE,
                 "cancel": _ring_mod.RING_CANCEL}
        self._ring.push(kinds[kind], slot, arg)
        self._bump("mega_ring_items")

    def _sync_tables(self) -> None:
        self._free_pages_gauge.set(len(self.pool.free))
        # COPIES, not views: ``jnp.asarray`` on the CPU backend may
        # zero-copy an aligned numpy array, so the device "buffer"
        # aliases the live host array — and this engine mutates
        # ``_table``/``_kv_len`` in place while async-dispatched
        # launches still read them (the decode then races host
        # bookkeeping; observed as run-to-run token flips whose
        # probability scaled with host work between dispatch and the
        # first output fetch). Explicit copies give the device arrays
        # their own storage.
        table = self._table.copy()
        kv_len = self._kv_len.copy()
        for slot in self._longctx:
            # Sharded slots are INVISIBLE to the batched decode step:
            # host truth keeps the resident row + absolute length (the
            # audit and the per-slot sharded program read those), but
            # the device copies go to zero so the batched append lands
            # on the trash page and the batched attention sees an empty
            # sequence (its logits are spliced over anyway).
            table[slot] = 0
            kv_len[slot] = 0
        self.cache = dataclasses.replace(
            self.cache,
            page_table=jnp.asarray(table),
            kv_len=jnp.asarray(kv_len),
        )

    def _admit(
        self, req: Request, slot: int, m: PrefixMatch | None = None
    ):
        """Prefill ``req`` into ``slot``; returns the first sampled
        token — or None when ``req`` carried a migration snapshot and
        was RESUMED instead (its pending token is already in ``out``;
        the next scheduling round continues decoding it)."""
        fault_point("engine.admit", slot=slot)
        if req.timeline is not None:
            req.timeline.stamp_admit()
        if req.snapshot is not None:
            return self._admit_import(req, slot)
        if self._sharded_eligible(req):
            if m is not None:
                # Sharded slots never map tree pages (their pages cycle
                # through the tier); a match computed before routing
                # here (e.g. the import-fallback replay) releases its
                # pins instead of leaking them.
                self.prefix.release_match(m)
            return self._admit_sharded(req, slot)
        if self.prefix is not None:
            return self._admit_prefix(req, slot, m)
        s = len(req.prompt)
        n = self.model.ctx.axis_size(self.model.axis)
        pad = (-s) % n
        row = np.concatenate([req.prompt, np.zeros(pad, np.int32)])
        need = self._needed_pages(s, req.gen_len)
        req.slot = slot  # before any allocation: teardown keys off it
        req.pages = self.pool.allocate(need)
        self._table[slot] = 0
        self._table[slot, : len(req.pages)] = req.pages
        self._kv_len[slot] = s
        self._sync_tables()

        if req.timeline is not None:
            req.timeline.stamp_first_chunk()
        logits, self._dense1 = self.model.prefill_batched(
            jnp.asarray(row[None]), self._dense1, self._prefill_mode,
            jnp.asarray([s], jnp.int32),
        )
        self.cache = write_prefill(
            self.cache, slot, self._dense1.k, self._dense1.v, s
        )
        self._bump("admitted")
        self._bump("prefill_tokens", s)
        if self._moe_k:
            self._bump("moe_routed_tokens", s * self._moe_k)
        # Emitted HERE, aligned with the `admitted` counter — a failed
        # allocation/prefill must not leave a phantom admit event for
        # consumers correlating admits against counters or evicts.
        obs_events.emit("admit", slot=slot, prompt_len=s, matched=0,
                        trace_id=req.trace_id)
        self._slots[slot] = req
        return self._sample_req(req, logits[0])

    def _admit_prefix(
        self, req: Request, slot: int, m: PrefixMatch
    ) -> jax.Array:
        """Prefix-cache admission: map the matched prefix pages into the
        slot's table row, COW-clone a partially matched tail, then
        chunk-prefill only the suffix."""
        s = len(req.prompt)
        total = self._needed_pages(s, req.gen_len)
        req.slot = slot  # before any allocation: teardown keys off it
        new_pages = self.prefix.allocate(total - len(m.nodes))
        assert new_pages is not None, "try_admit availability check failed"
        matched = m.matched_len
        req.pages = m.pages + new_pages
        req.shared_nodes = list(m.nodes)
        # Pins now ride on the request: the admission failure handler
        # releases m's REMAINING pins, the slot teardown releases the
        # request's — moving them here keeps each pin owned exactly once.
        m.nodes = []
        self._table[slot] = 0
        self._table[slot, : len(req.pages)] = req.pages
        if m.cow_len:
            # The partially matched page becomes this request's first
            # private page: clone it, count only the matched positions.
            self.cache = copy_page(self.cache, m.cow_node.page, new_pages[0])
            self._bump("pages_cow_copied")
            obs_events.emit("cow", slot=slot, matched=m.cow_len)
        self.prefix.finish_cow(m)
        self._kv_len[slot] = matched
        self._sync_tables()
        if req.timeline is not None:
            req.timeline.stamp_first_chunk()
        with trace_span(
            "prefix_cache:admit", slot=slot, prompt_len=s, matched=matched
        ):
            logits = self._prefill_suffix(slot, req.prompt, matched)
        # Counted/emitted only AFTER the suffix prefill: a chunk that
        # fails must not leave a phantom admit event or `admitted`
        # count (the same contract the non-prefix path states above).
        self._bump("admitted")
        self._bump("prefix_hit_tokens", matched)
        obs_events.emit("admit", slot=slot, prompt_len=s, matched=matched,
                        trace_id=req.trace_id)
        self._slots[slot] = req
        return self._sample_req(req, logits)

    def _admit_import(self, req: Request, slot: int):
        """Resume a migrated request from its snapshot: import the
        portable slot state (``models/slot_state.py``) into ``slot``
        instead of re-prefilling. Returns None (the pending token is
        already the tail of ``out``). Import failures — geometry or
        dtype mismatch, a stale prefix delta, an injected
        ``migrate.import`` fault — fall back to a FULL REPLAY from the
        prompt: correct (the engine is deterministic), just without the
        saved work. A failure after pages were claimed unwinds through
        the standard crash-safe teardown first."""
        from triton_distributed_tpu.models import slot_state

        snap_wire = req.snapshot
        snap = None
        try:
            if (isinstance(snap_wire, dict)
                    and self._tier_fp is not None
                    and "model_fp" in snap_wire
                    and snap_wire["model_fp"] != self._tier_fp):
                # Produced under different weights (a resume store or
                # tier_dir that outlived a checkpoint swap): old-weight
                # KV continued under new weights is wrong bits — take
                # the replay fallback below instead.
                raise slot_state.SnapshotStaleError(
                    "snapshot was produced under different model weights"
                )
            snap = (
                slot_state.SlotSnapshot.from_wire(snap_wire)
                if isinstance(snap_wire, dict) else snap_wire
            )
            slot_state.import_slot(self, req, snap, slot)
        except Exception as e:  # noqa: BLE001 — fallback boundary
            if req.slot is not None:
                # Pages/pins were claimed before the failure: release
                # them the same way any crashed slot does.
                self._teardown_slot(req)
            req.snapshot = None
            req.out = []
            # Replay under the snapshot's OWN key from draw 0: the
            # replay then makes exactly the draws the original run
            # made, keeping even the fallback bit-exact for seeded
            # sampling (a fresh engine-split key would not).
            if snap is not None and snap.key_data is not None:
                req.key = jax.random.wrap_key_data(
                    jnp.asarray(snap.key_data)
                )
            req.key_step = 0
            self.stats["migration_fallbacks"] += 1
            self._m_mig_fallbacks.inc()
            obs_events.emit(
                "migrate_fallback", slot=slot,
                reason=f"{type(e).__name__}: {str(e)[:160]}",
                trace_id=req.trace_id,
            )
            m = self.prefix.match(req.prompt) if self.prefix else None
            try:
                return self._admit(req, slot, m)
            except Exception as e2:  # noqa: BLE001 — isolation boundary
                # The replay admission failed too: release the match
                # pins and fail ONLY this request, exactly as a direct
                # admission failure would (the caller reads the status).
                self._admit_failure(req, m, e2)
                return None
        req.snapshot = None
        self._sync_tables()
        self._bump("admitted")
        self.stats["migrated_in"] += 1
        self.stats["migrated_in_tokens"] += len(req.out)
        self._m_mig_saved.inc(len(req.out))
        if snap.exported_at:
            self._m_mig_handoff.observe(
                max(time.time() - snap.exported_at, 0.0)
            )
        obs_events.emit(
            "migrate_in", slot=slot, tokens_out=len(req.out),
            kv_tokens=int(self._kv_len[slot]),
            from_prefix_pages=int(snap.from_prefix_pages),
            trace_id=req.trace_id,
        )
        return None

    def _prefill_suffix(self, slot: int, prompt: np.ndarray, start: int):
        """Chunk-prefill ``prompt[start:]`` into ``slot``'s pages,
        stepping the running batch between chunks (chunked prefill:
        admission never stalls in-flight decodes for a full prefill).
        Returns the last real token's logits ``[V]``."""

        def between_chunks(cache, new_len):
            # Device kv_len is set absolutely by the chunk program, so
            # host and device agree even after interleaved decode steps
            # bumped the in-flight slot's device counter.
            self.cache = cache
            self._kv_len[slot] = new_len
            if self._step_guard(self._decode_once):
                # An interleaved decode finished (or failed) a request:
                # its pages retired/released, and the device table must
                # drop them BEFORE the next chunk, or the stale row's
                # append would corrupt a cached page.
                self._sync_tables()
            return self.cache

        if self.cp > 1:
            logits, chunks = self._prefill_suffix_cp(
                slot, prompt, start, between_chunks
            )
        else:
            logits, self.cache, chunks = prefill_suffix_chunks(
                self.model, self.cache, slot, prompt, start,
                self.prefill_chunk, self._prefill_mode, between_chunks,
            )
        self._kv_len[slot] = len(prompt)
        self._bump("prefill_tokens", len(prompt) - start)
        self._bump("prefill_chunks", chunks)
        if self._moe_k:
            self._bump("moe_routed_tokens",
                       (len(prompt) - start) * self._moe_k)
        return logits

    def _prefill_suffix_cp(self, slot: int, prompt: np.ndarray,
                           start: int, between_chunks):
        """Context-parallel chunked prefill (docs/serving.md
        "Long-context serving"): the suffix runs through the SAME
        ``prefill_suffix_chunks`` call sequence as cp=1 — cp>1 logits
        are bit-exact by construction — with block ``i`` owned by
        virtual rank ``i % cp`` and block i's freshly written KV pages
        staged toward rank ``(i+1) % cp`` on a background thread WHILE
        the main thread blocks on block i+1's attention compute (the
        split-phase AR_SEND/AR_WAIT discipline at serving granularity;
        ``models/long_context.py``). The tracer lands in
        ``self.cp_tracer`` for ``cp_overlap_report``/
        ``validate_cp_ring``. Returns ``(logits, chunks)``."""
        from triton_distributed_tpu.models import long_context as lc

        suffix = len(prompt) - start
        # No explicit chunk width → one block per rank (round_chunk
        # keeps the width a legal chunk shape; the last block absorbs
        # the rounding remainder).
        width = self.prefill_chunk or round_chunk(-(-suffix // self.cp))
        tracer = lc.CPTracer()
        exch = lc.SplitPhaseExchange(tracer, self.cp)
        page = self.page_size
        state = {"blk": 0, "off": start, "t0": time.perf_counter_ns()}

        def stamp_attn(blk: int, t1: int) -> None:
            r = lc.cp_block_rank(blk, self.cp)
            tracer.record(lc.CP_ATTN, blk, r, r, state["t0"], t1)

        def cp_between(cache, new_len):
            # Block on the chunk program just dispatched — that is
            # block ``blk``'s attention window; the PREVIOUS block's
            # staging thread has been running underneath it.
            jax.block_until_ready(cache.k_pages)
            t1 = time.perf_counter_ns()
            blk = state["blk"]
            stamp_attn(blk, t1)
            # Receive barrier for the oldest in-flight exchange (the
            # pipeline is one block deep: join i-1 before staging i).
            exch.join_oldest()
            # Stage block ``blk``'s pages toward its successor rank.
            # The jnp.take gathers are enqueued HERE — before the
            # decode interleave and the next chunk program donate the
            # cache — and materialize on the staging thread.
            first = state["off"] // page
            last = -(-new_len // page) - 1
            ids = jnp.asarray(
                self._table[slot, first:last + 1], jnp.int32
            )
            arrays = [
                jnp.take(cache.k_pages, ids, axis=1),
                jnp.take(cache.v_pages, ids, axis=1),
            ]
            if cache.quantized:
                arrays += [
                    jnp.take(cache.k_scale, ids, axis=1),
                    jnp.take(cache.v_scale, ids, axis=1),
                ]
            exch.dispatch(blk, arrays)
            out = between_chunks(cache, new_len)
            state["blk"] = blk + 1
            state["off"] = new_len
            state["t0"] = time.perf_counter_ns()
            return out

        logits, self.cache, chunks = prefill_suffix_chunks(
            self.model, self.cache, slot, prompt, start,
            width, self._prefill_mode, cp_between,
        )
        jax.block_until_ready(logits)
        stamp_attn(state["blk"], time.perf_counter_ns())
        # The final block is the ring's tail — nothing consumes its KV
        # during prefill, so it is not exchanged (validate_cp_ring
        # expects exchanges for blocks 0..n-2 only).
        exch.join_all()
        self.cp_tracer = tracer
        rep = lc.cp_overlap_report(tracer)
        self._bump("cp_prefills")
        self._bump("cp_blocks", chunks)
        self._bump("cp_exchange_bytes", rep["exchange_bytes"])
        self._bump("cp_exchange_us", rep["send_ns"] // 1000)
        self._bump("cp_hidden_us", rep["hidden_ns"] // 1000)
        return logits, chunks

    # -- sharded long-context slots ---------------------------------------
    #
    # docs/serving.md "Long-context serving": with ``rank_page_budget``
    # set, a request whose KV needs more pages than the budget admits in
    # SHARDED mode — a resident paged window of at most ``budget_pages``
    # pages (local positions, the slot's own explicit table row) plus
    # cold pages demoted to the KV tier, faulted back on demand as a
    # read-only dense window. Prefill and decode both run per-slot
    # programs that merge the (cold, resident) attention partials with
    # ``lse_combine`` — the distributed-flash-decode combine — so the
    # logits are what one giant resident slot would compute.

    def _sharded_eligible(self, req: Request) -> bool:
        """Whether ``req`` must admit in sharded long-context mode:
        budgeted engine, not a snapshot resume, and a KV footprint the
        budget cannot hold resident."""
        return (
            self.budget_pages > 0
            and req.snapshot is None
            and self._needed_pages(len(req.prompt), req.gen_len)
            > self.budget_pages
        )

    def _alloc_pages(self, n: int) -> list:
        """Allocate ``n`` pool pages for a sharded slot — through the
        radix tree's reclaim path when a prefix cache is on (cold tree
        pages yield, exactly as admission allocation does), straight
        from the pool otherwise."""
        if self.prefix is not None:
            pages = self.prefix.allocate(n)
            if pages is None:
                raise RuntimeError(
                    f"page pool exhausted ({n} pages for a sharded slot)"
                )
            return pages
        return self.pool.allocate(n)

    def _admit_sharded(self, req: Request, slot: int):
        """Admit an over-budget request as a SHARDED slot: chunk-prefill
        one page at a time through ``prefill_paged_chunk_cold``,
        demoting the oldest resident page to the KV tier whenever the
        resident window hits the budget. Host ``_kv_len``/``_table``
        keep absolute-length/resident-row truth; the device copies stay
        zero (``_sync_tables``) so the batched decode never touches the
        slot. Returns the first sampled token."""
        s = len(req.prompt)
        page = self.page_size
        ls = _LongSlot(uid=next(_LONG_UIDS))
        req.slot = slot  # before any allocation: teardown keys off it
        self._longctx[slot] = ls
        self._table[slot] = 0
        self._kv_len[slot] = 0
        self._sync_tables()
        if req.timeline is not None:
            req.timeline.stamp_first_chunk()
        logits = None
        off = 0
        while off < s:
            take = min(page, s - off)
            kv_loc = off - ls.cold * page
            if kv_loc == self.budget_pages * page:
                self._demote_front(slot, ls, req)
                kv_loc -= page
            if kv_loc == len(req.pages) * page:
                req.pages = req.pages + self._alloc_pages(1)
                self._table[slot, len(req.pages) - 1] = req.pages[-1]
            row = np.zeros(self.budget_pages, np.int32)
            row[: len(req.pages)] = req.pages
            k_c, v_c, ks_c, vs_c, _bucket = self._cold_view(ls)
            buf = np.zeros(page, np.int32)
            buf[:take] = req.prompt[off: off + take]
            with trace_span("longctx:chunk", slot=slot, offset=off,
                            cold=ls.cold):
                logits, self.cache = self.model.prefill_paged_chunk_cold(
                    buf, row, off, off + take, take - 1, self.cache,
                    k_c, v_c, ks_c, vs_c, s_cold=ls.cold * page,
                    mode=self._prefill_mode,
                )
            off += take
            self._kv_len[slot] = off
            if off < s and self._step_guard(self._decode_once):
                # Chunked-prefill contract: the running batch keeps
                # decoding between this slot's chunks.
                self._sync_tables()
        self._bump("admitted")
        self._bump("prefill_tokens", s)
        self._bump("prefill_chunks", -(-s // page))
        self._bump("longctx_sharded_slots")
        if self._moe_k:
            self._bump("moe_routed_tokens", s * self._moe_k)
        obs_events.emit("admit", slot=slot, prompt_len=s, matched=0,
                        trace_id=req.trace_id)
        self._slots[slot] = req
        return self._sample_req(req, logits)

    def _demote_front(self, slot: int, ls: _LongSlot, req: Request) -> None:
        """Demote the slot's oldest (full) resident page to the KV
        tier: the page's KV + scales ship as a ``prefix_payload`` keyed
        ``<uid>:<cold-index>`` under ``LONGCTX_KIND``, the pool page
        frees, and the cold window grows by one page. The payload's
        chain is the page's OWN ``page_size`` tokens, so fault-back and
        the audit can cross-check content against the sequence."""
        from triton_distributed_tpu.models import kv_tier

        page = self.page_size
        pid = int(req.pages[0])
        start = ls.cold * page
        seq = [int(t) for t in req.prompt] + [int(t) for t in req.out]
        k, v, ks, vs = gather_pages(self.cache, [pid])
        payload = kv_tier.prefix_payload(
            seq[start: start + page], page, self.kv_dtype,
            k[:, 0], v[:, 0],
            None if ks is None else ks[:, 0],
            None if vs is None else vs[:, 0],
        )
        if self._tier_fp is not None:
            payload["model_fp"] = self._tier_fp
        key = f"{ls.uid}:{ls.cold}"
        if not self.tier.put(kv_tier.LONGCTX_KIND, key, payload):
            raise RuntimeError(
                f"KV tier refused cold page {key} of sharded slot {slot}"
            )
        self.pool.release([pid])
        req.pages = req.pages[1:]
        self._table[slot] = 0
        self._table[slot, : len(req.pages)] = req.pages
        ls.cold += 1
        ls.view = None
        self._bump("longctx_demoted_pages")
        obs_events.emit("longctx_demote", slot=slot, page=pid,
                        cold=ls.cold)

    def _cold_view(self, ls: _LongSlot):
        """The slot's cold window as device arrays: every demoted page
        faulted back from the tier (``tdt_longctx_tier_faults_total``
        counts each page read) and stitched — in absolute order — into
        a power-of-two page bucket (log-many compiled programs over a
        slot's life; the tail past ``cold`` pages is zero and masked by
        the kernels' ``s_cold``). Cached until the next demote. Returns
        ``(k, v, ks, vs, bucket_pages)``."""
        from triton_distributed_tpu.models import kv_tier

        page = self.page_size
        n = ls.cold
        bucket = 1
        while bucket < n:
            bucket *= 2
        if ls.view is not None and ls.view[4] == bucket:
            return ls.view
        kp = self.cache.k_pages  # [L, P, Hkv, page, hd]
        n_layers, _p, hkv, _page, hd = kp.shape
        dt = np.dtype(kp.dtype)
        k_np = np.zeros((n_layers, hkv, bucket * page, hd), dt)
        v_np = np.zeros((n_layers, hkv, bucket * page, hd), dt)
        quant = self.cache.quantized
        ks_np = np.zeros((n_layers, hkv, bucket), np.float32) if quant \
            else None
        vs_np = np.zeros((n_layers, hkv, bucket), np.float32) if quant \
            else None
        for i in range(n):
            key = f"{ls.uid}:{i}"
            payload = self.tier.get(kv_tier.LONGCTX_KIND, key)
            if payload is None:
                raise RuntimeError(
                    f"cold page {key} missing from the KV tier (a "
                    "sharded slot's cold window cannot be rebuilt)"
                )
            if (self._tier_fp is not None
                    and payload.get("model_fp") != self._tier_fp):
                raise RuntimeError(
                    f"cold page {key} was produced under different "
                    "model weights"
                )
            _chain, _ps, _dt, k1, v1, ks1, vs1 = (
                kv_tier.decode_prefix_payload(payload)
            )
            k_np[:, :, i * page:(i + 1) * page, :] = k1
            v_np[:, :, i * page:(i + 1) * page, :] = v1
            if quant:
                ks_np[:, :, i] = ks1
                vs_np[:, :, i] = vs1
            self._bump("longctx_tier_faults")
            self._bump("longctx_tier_bytes",
                       kv_tier.payload_nbytes(payload))
        view = (
            jnp.asarray(k_np), jnp.asarray(v_np),
            None if ks_np is None else jnp.asarray(ks_np),
            None if vs_np is None else jnp.asarray(vs_np),
            bucket,
        )
        ls.view = view
        return view

    def _longctx_decode(self, logits):
        """One sharded decode step per sharded slot, run after the
        batched step (which saw the slot as empty): append the slot's
        pending token at its local resident position — demoting /
        allocating a page when the append needs room — and splice the
        per-slot partial-merge logits over the batched row BEFORE the
        NaN guard and sampling read them. Returns
        ``(logits, changed)``."""
        page = self.page_size
        changed = False
        for slot in sorted(self._longctx):
            ls = self._longctx.get(slot)
            req = self._slots[slot]
            if ls is None or req is None:
                continue
            try:
                # _kv_len was already bumped for this step: rows cached
                # before the append = _kv_len - 1, all absolute.
                kv_loc = int(self._kv_len[slot]) - 1 - ls.cold * page
                if kv_loc == self.budget_pages * page:
                    self._demote_front(slot, ls, req)
                    kv_loc -= page
                if kv_loc == len(req.pages) * page:
                    req.pages = req.pages + self._alloc_pages(1)
                    self._table[slot, len(req.pages) - 1] = req.pages[-1]
                row = np.zeros(self.budget_pages, np.int32)
                row[: len(req.pages)] = req.pages
                k_c, v_c, ks_c, vs_c, _bucket = self._cold_view(ls)
                lg, self.cache = self.model.decode_step_sharded(
                    np.asarray([self._tok[slot]], np.int32), self.cache,
                    row, kv_loc, k_c, v_c, ks_c, vs_c,
                    s_cold=ls.cold * page, mode=self.mode,
                )
                self._bump("longctx_decode_steps")
            except Exception as e:  # noqa: BLE001 — per-slot isolation
                self._fail(
                    req, "failed",
                    f"sharded decode: {type(e).__name__}: {e}",
                )
                changed = True
                continue
            logits = logits.at[slot].set(lg[0])
        return logits, changed

    def _drop_longctx(self, slot: int) -> None:
        """Forget a sharded slot's bookkeeping and delete its tier
        entries (cold pages belong to exactly ONE live request — they
        are not a cache; leftovers would leak tier capacity)."""
        ls = self._longctx.pop(slot, None)
        if ls is None:
            return
        if self.tier is not None:
            from triton_distributed_tpu.models import kv_tier

            for i in range(ls.cold):
                self.tier.delete(kv_tier.LONGCTX_KIND, f"{ls.uid}:{i}")

    def _audit_longctx(self) -> list[str]:
        """Sharded-slot invariants, folded into :meth:`audit`: every
        sharded entry has a live request, resident pages within budget,
        local length within resident capacity, and every cold page
        present in the tier."""
        from triton_distributed_tpu.models import kv_tier

        problems: list[str] = []
        page = self.page_size
        for slot, ls in self._longctx.items():
            req = self._slots[slot]
            if req is None:
                problems.append(
                    f"longctx: slot {slot} sharded but has no request"
                )
                continue
            if len(req.pages) > self.budget_pages:
                problems.append(
                    f"longctx: slot {slot} holds {len(req.pages)} "
                    f"resident pages > budget {self.budget_pages}"
                )
            kv_loc = int(self._kv_len[slot]) - ls.cold * page
            if not 0 <= kv_loc <= len(req.pages) * page:
                problems.append(
                    f"longctx: slot {slot} local kv {kv_loc} outside "
                    f"resident capacity {len(req.pages) * page}"
                )
            if self.tier is not None:
                for i in range(ls.cold):
                    key = f"{ls.uid}:{i}"
                    if not self.tier.contains(kv_tier.LONGCTX_KIND, key):
                        problems.append(
                            f"longctx: slot {slot} cold page {key} "
                            "missing from the KV tier"
                        )
        if self.tier is not None and self._tier_owned:
            live = {
                str(ls.uid) for ls in self._longctx.values()
            }
            for key in self.tier.keys(kv_tier.LONGCTX_KIND):
                if key.split(":", 1)[0] not in live:
                    problems.append(
                        f"longctx: stale tier entry {key} (no live "
                        "sharded slot owns it)"
                    )
        return problems

    def _decode_once(self) -> bool:
        """One single-step decode of every active slot; appends sampled
        tokens and evicts finished requests. Returns whether slot state
        changed (caller decides when to re-admit/sync)."""
        if self._ring is not None:
            # Single-step fallback under a resident session: this round
            # applies slot state directly on the host, so no device
            # loop will ever observe the queued admit/retire/cancel
            # items — drain them here (no doorbell) or a workload that
            # persistently falls back (e.g. filtered sampling at
            # tp > 1, or ns=1) overflows the ring and wedges every
            # subsequent round.
            flushed = self._ring.flush()
            if flushed:
                self._bump("mega_ring_host_drains", len(flushed))
        active = np.asarray([r is not None for r in self._slots], np.int32)
        if not active.any():
            return False
        fault_point("engine.decode", step=self.stats["decode_steps"])
        logits, self.cache = self._decode_step(
            jnp.asarray(self._tok), self.cache
        )
        logits = mutate_point(
            "engine.logits", logits, step=self.stats["decode_steps"]
        )
        # Rebind, never ``+=`` — the zero-copy-alias discipline of
        # ``_sync_tables`` (an in-place add between an async dispatch
        # and its first fetch raced the device's kv_len read).
        self._kv_len = self._kv_len + active
        self._bump("decode_steps")
        if self._moe_k:
            self._bump("moe_routed_tokens",
                       int(active.sum()) * self._moe_k)
        # Sharded long-context slots were invisible to the batched step
        # (device table/kv_len masked to the trash page): run their
        # per-slot partial-merge decode now and splice the real logits
        # over the batched rows BEFORE the NaN guard and sampling.
        if self._longctx:
            logits, lc_changed = self._longctx_decode(logits)
        else:
            lc_changed = False
        # One device program computes the finite mask AND the greedy
        # base tokens, so the NaN guard adds no extra host-sync round
        # trip to the hot decode loop.
        finite, greedy_base = _finite_greedy(logits)
        failed = self._guard_logits(np.asarray(finite))
        nxt = self._sample_slots(logits, np.array(greedy_base))
        changed = self._process(lambda slot: [nxt[slot]])
        return changed or bool(failed) or lc_changed

    def _guard_logits(self, finite: np.ndarray) -> list[int]:
        """Per-slot NaN/Inf guard on a batched decode output: fail ONLY
        the slots whose logits went non-finite (structured
        ``nan_logits`` error, counted in ``last_stats``) instead of
        silently sampling garbage. ``finite`` is the per-slot
        all-finite mask. Returns the failed slot indices."""
        failed = []
        for slot, req in enumerate(self._slots):
            if req is None or bool(finite[slot]):
                continue
            self._bump("nonfinite_logits")
            self._fail(
                req, "nan_logits",
                f"non-finite logits at decode step "
                f"{self.stats['decode_steps']} after {len(req.out)} tokens",
            )
            failed.append(slot)
        return failed

    def _process(self, slot_tokens) -> bool:
        """Append per-slot tokens; evict on gen_len/eos. Returns whether
        slot state changed."""
        changed = False
        emitted = 0
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            for t in slot_tokens(slot):
                req.out.append(int(t))
                self._emit_token(req)
                emitted += 1
                self._tok[slot] = int(t)
                if req.spec is not None:
                    req.spec.observe((int(t),))
                if self._maybe_finish(req, int(t)):
                    changed = True
                    break
        if emitted:
            self._bump("generated_tokens", emitted)
        return changed

    def _evict(self, req: Request) -> None:
        slot = req.slot
        self._finish_obs(req)  # status "ok": _evict only runs on success
        obs_events.emit("evict", slot=slot, tokens_out=len(req.out))
        self._ring_push("retire", slot, len(req.out))
        if slot in self._longctx:
            # A sharded slot's resident pages hold a LOCAL window (the
            # cold prefix lives in the tier) — useless as a prefix
            # chain, so they go straight back to the pool and the tier
            # entries are deleted with the slot.
            req.pages = truncate_pages(
                self.pool, req.pages, 0, self.page_size
            )
            self._drop_longctx(slot)
        elif self.prefix is not None:
            self._retire_to_prefix(req)
        else:
            # Full truncation: every private page goes back to the pool
            # (the 0-token case of the speculative rollback helper).
            req.pages = truncate_pages(
                self.pool, req.pages, 0, self.page_size
            )
        self._table[slot] = 0  # back to the trash page
        self._kv_len[slot] = 0
        req.pages, req.slot = [], None
        self._slots[slot] = None

    # -- failure isolation -----------------------------------------------

    def _fail(self, req: Request, status: str, reason) -> None:
        """Fail ONE request: record the structured error and, if it
        holds a slot, tear that slot down. Everything else keeps
        serving. A client-initiated ``cancelled`` rides the same
        teardown but its own counter — a cancellation is not a server
        failure."""
        req.status, req.reason = status, str(reason)
        if status == "cancelled":
            self._bump("cancelled_requests")
        else:
            self._bump("failed_requests")
        if status == "deadline_exceeded":
            self._bump("deadline_expired")
        elif status == "overloaded":
            self._bump("shed_requests")
        if req.slot is not None:
            self._teardown_slot(req)
        obs_events.emit(
            _FAIL_EVENT_KIND.get(status, "request_failed"),
            status=status, tokens_out=len(req.out),
            reason=str(reason)[:200],
        )
        self._finish_obs(req)

    def _teardown_slot(self, req: Request) -> None:
        """Crash-safe slot release: private pages to the pool, shared
        prefix pins back to the tree (the TREE owns those pages — they
        must not be freed here), table row back to the trash page.

        Unlike ``_evict`` nothing is donated to the prefix tree: a
        failed request's KV is suspect (non-finite logits, a partial
        verify chunk) and caching it would poison later matches."""
        slot = req.slot
        self._ring_push("retire", slot, len(req.out))
        self._drop_longctx(slot)
        truncate_pages(
            self.pool, req.pages, 0, self.page_size,
            shared=len(req.shared_nodes),
        )
        if self.prefix is not None:
            for node in req.shared_nodes:
                self.prefix.release_node(node)
        req.shared_nodes = []
        req.pages = []
        self._table[slot] = 0
        self._kv_len[slot] = 0
        self._slots[slot] = None
        req.slot = None

    def _admit_failure(self, req: Request, m: PrefixMatch | None, e) -> None:
        """Clean up a failed admission: release whatever prefix pins
        were NOT yet transferred to the request, tear down any slot
        state it acquired, mark it failed, and resync the device
        table — the engine stays serviceable."""
        if self.prefix is not None and m is not None:
            if m.cow_node is not None:
                self.prefix.release_node(m.cow_node)
                m.cow_node = None
            for node in m.nodes:
                self.prefix.release_node(node)
            m.nodes = []
        status = "failed"
        if isinstance(e, _NonFiniteLogits):
            status = "nan_logits"
            self._bump("nonfinite_logits")
        self._fail(req, status, f"{type(e).__name__}: {e}")
        self._sync_tables()

    def _step_guard(self, fn) -> bool:
        """Run one decode-phase step with per-request error isolation:
        an exception carrying a ``slot`` attribute (injected faults,
        slot-attributable guards) fails exactly that request; anything
        else poisons the whole in-flight set — every active request
        gets a structured error and a clean teardown, and the engine
        (slots, pool, tree, device table) remains reusable. Returns
        whether slot state changed."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — isolation boundary
            self._bump("decode_faults")
            # A fault mid-resident-round may leave a launch in flight;
            # block on it before the teardown below reuses its state.
            self._abort_pend()
            slot = getattr(e, "slot", None)
            if (isinstance(slot, int) and 0 <= slot < self.max_batch
                    and self._slots[slot] is not None):
                victims = [self._slots[slot]]
            else:
                victims = [r for r in self._slots if r is not None]
            for r in victims:
                self._fail(r, "failed", f"{type(e).__name__}: {e}")
            self._sync_tables()
            return True

    def _emit_token(self, req: Request) -> None:
        """Streaming hook: hand the just-appended token to the
        request's ``on_token`` sink. A raising sink detaches itself —
        a client that vanished mid-stream must never fail the request
        through its own callback (the server's disconnect path cancels
        it explicitly instead)."""
        cb = req.on_token
        if cb is None:
            return
        try:
            cb(len(req.out) - 1, int(req.out[-1]))
        except Exception:  # noqa: BLE001 — sink isolation boundary
            req.on_token = None

    # -- client-driven cancellation (docs/serving.md) ----------------------

    def cancel(self, ticket_ids) -> None:
        """Request cancellation of the given ticket ids — thread-safe
        (a set add under its own lock; the server's cancel verb and
        the streaming disconnect path call this MID-batch). Applied at
        the next scheduling round: queued requests fail before
        admission, in-flight slots tear down through the standard
        crash-safe path with status ``cancelled`` and their partial
        tokens. Ids matching nothing in the current batch are pruned
        when the batch ends — a cancel racing a slot's natural finish
        simply loses (the tokens were already emitted). Ids stay
        ARMED until consumed or batch-pruned, deliberately: a cancel
        may legitimately beat its request here. The flip side is the
        contract that ticket ids are request IDENTITIES — a client
        that cancels ``job1`` and then submits a NEW request reusing
        ``job1`` may see the armed cancel apply to it; never reuse
        ids across requests (docs/serving.md)."""
        ids = {str(t) for t in ticket_ids}
        if not ids:
            return
        with self._cancel_lock:
            self._cancelled |= ids
        obs_events.emit("cancel", requested=len(ids))

    def _apply_cancels(self, queue: deque) -> bool:
        """Consume pending cancellations against the queue and the
        active slots. Returns whether slot state changed. The
        ``engine.cancel`` fault seam sits between the snapshot and the
        application so chaos tests can sequence a cancel deterministically
        against a finishing slot."""
        with self._cancel_lock:
            if not self._cancelled:
                return False
            pending = set(self._cancelled)
        fault_point("engine.cancel", pending=len(pending))
        if self._pend is not None:
            # Cancellation tears slots down — the in-flight resident
            # launch still reads their table rows; sync first.
            self._drain_pend()
        consumed: set[str] = set()
        changed = False
        for r in list(queue):
            if r.ticket_id is not None and r.ticket_id in pending:
                queue.remove(r)
                consumed.add(r.ticket_id)
                self._fail(
                    r, "cancelled", "cancelled by client before admission"
                )
        for req in list(self._slots):
            if req is None or req.ticket_id is None:
                continue
            if req.ticket_id in pending:
                consumed.add(req.ticket_id)
                self._ring_push("cancel", req.slot)
                self._fail(
                    req, "cancelled",
                    f"cancelled by client after {len(req.out)} generated "
                    "tokens",
                )
                changed = True
        if consumed:
            with self._cancel_lock:
                self._cancelled -= consumed
        return changed

    def _expire_deadlines(self) -> bool:
        """Fail every active request whose wall-clock deadline passed
        (structured ``deadline_exceeded`` + partial tokens). Returns
        whether slot state changed."""
        now = time.monotonic()
        if self._pend is not None and any(
                r is not None and r.deadline_at is not None
                and now > r.deadline_at for r in self._slots):
            # The expiry is about to tear a slot down mid-pipeline;
            # sync first (the drain may even finish it naturally).
            self._drain_pend()
        changed = False
        for req in list(self._slots):
            if req is None or req.deadline_at is None:
                continue
            if now > req.deadline_at:
                self._fail(
                    req, "deadline_exceeded",
                    f"deadline_s={req.deadline_s} exceeded after "
                    f"{len(req.out)} generated tokens",
                )
                changed = True
        return changed

    # -- sampling ---------------------------------------------------------

    def _retire_to_prefix(self, req: Request) -> None:
        """Donate the finished request's KV pages to the radix tree.

        Valid KV covers positions ``[0, s + len(out) - 1)`` — the last
        sampled token was never fed back, so its KV was never appended
        (and multi-step overshoot rows beyond it hold discarded-token
        garbage the retire chunking never references)."""
        gen_cached = max(len(req.out) - 1, 0)
        toks = np.concatenate(
            [req.prompt, np.asarray(req.out[:gen_cached], np.int32)]
        )
        with trace_span("prefix_cache:retire", tokens=len(toks)):
            self.prefix.retire_sequence(toks, req.pages, req.shared_nodes)
        req.shared_nodes = []

    # -- durable KV tier (docs/serving.md "Tiered KV") --------------------

    def _spill_page(self, chain: list, page: int) -> None:
        """``PrefixCache.spill_fn``: export one evicted full page to
        the tier, keyed by its token-chain digest — byte-exact via
        ``gather_pages`` (int8 codes + per-page scales travel as a
        pair). Raising is fine: eviction treats any spill failure as
        the pre-tier drop."""
        from triton_distributed_tpu.models import kv_tier

        k, v, ks, vs = gather_pages(self.cache, [page])
        payload = kv_tier.prefix_payload(
            chain, self.page_size, self.kv_dtype,
            k[:, 0], v[:, 0],
            None if ks is None else ks[:, 0],
            None if vs is None else vs[:, 0],
        )
        payload["model_fp"] = self._tier_fp
        if self.tier.put(kv_tier.PREFIX_KIND, kv_tier.chain_digest(chain),
                         payload):
            self._bump("tier_spilled_pages")
            obs_events.emit("tier_spill", tokens=len(chain), page=int(page))

    def tier_digest(self) -> dict | None:
        """The tier's compact content summary wrapped with this
        engine's page size — what replicas publish next to
        ``prefix_digest()`` at batch boundaries so the router can score
        tier affinity and peers can gate fabric probes. None without a
        tier. Memoized inside the store (mutation-counter keyed), so a
        per-batch call is a dict copy, not a scan."""
        if self.tier is None:
            return None
        return {"ps": int(self.page_size), **self.tier.digest()}

    def _tier_fill(self, tokens) -> None:
        """Fault-back half of the tier: extend the radix tree's
        coverage of ``tokens`` from the tier BEFORE admission matches —
        each hit page is re-allocated, written verbatim via
        ``write_page`` (cheaper than re-prefilling it), and grafted
        back into the tree where ``match()`` then pins it exactly like
        any cached page. Stops at the first miss, divergence, or
        allocation failure; every failure path degrades to the
        ordinary suffix prefill — never wrong bits."""
        if self.tier is None or self.prefix is None:
            return
        from triton_distributed_tpu.models import kv_tier

        fabric = self.fabric
        if fabric is not None and not fabric.peers:
            fabric = None
        if not self.tier.may_contain(kv_tier.PREFIX_KIND) and fabric is None:
            # Nothing has ever spilled (the steady state before the
            # first eviction) and no fabric peer to ask: skip the
            # per-round tree walk + SHA-1 over the uncovered prefix —
            # guaranteed misses. The queue head re-runs this every
            # scheduling round it waits. With peers attached the walk
            # must run: a cold LOCAL tier is exactly when a neighbor's
            # entries are worth pulling (the warm-boot path).
            return
        ps = self.page_size
        toks = [int(t) for t in tokens]
        limit = len(toks) - 1  # match()'s cap: one suffix token prefills
        node = self.prefix.root
        i = 0
        faulted = bytes_in = remote = 0
        # The walked path is refcount-PINNED for the fill's duration:
        # each faulted page's allocation may itself run the LRU
        # eviction sweep, which would otherwise happily evict (and
        # re-spill) the very nodes this prompt is about to match —
        # observed as a fill that fed its own allocations. Pins release
        # before match() takes its own.
        pinned: list = []
        try:
            while i + ps <= limit:
                chunk = toks[i:i + ps]
                child = node.children.get(chunk[0])
                if child is not None:
                    if tuple(chunk) == child.chunk:
                        node = child
                        node.refcount += 1
                        pinned.append(node)
                        i += ps
                        continue
                    break  # divergent/partial sibling: the tree wins
                digest = kv_tier.chain_digest(toks[: i + ps])
                payload = self.tier.get(kv_tier.PREFIX_KIND, digest)
                from_fabric = False
                if payload is None and fabric is not None:
                    # Local miss → peer fault-back: the fabric returns
                    # a payload already CRC/header-validated through
                    # the SAME codec a local read crosses; the
                    # chain/geometry/fingerprint checks below then run
                    # UNCHANGED — a remote entry can fail them exactly
                    # like a local one, and fails to re-prefill.
                    payload = fabric.fetch(kv_tier.PREFIX_KIND, digest)
                    from_fabric = payload is not None
                if payload is None:
                    break
                try:
                    chain, page_size, kv_dtype, k, v, ks, vs = (
                        kv_tier.decode_prefix_payload(payload)
                    )
                except kv_tier.TierIntegrityError:
                    if not from_fabric:  # nothing local to delete
                        self.tier.delete(kv_tier.PREFIX_KIND, digest)
                    break
                if (chain != toks[: i + ps] or page_size != ps
                        or kv_dtype != self.kv_dtype
                        or payload.get("model_fp") != self._tier_fp):
                    # Digest collision, a foreign-geometry entry, or a
                    # page produced under DIFFERENT weights (a reused
                    # tier_dir across a checkpoint swap): never fault
                    # it back — that would map wrong KV under this
                    # chain. The admission re-prefills. Deleting is
                    # owner-only: on a SHARED store (``tier=``) the
                    # entry may be perfectly valid for the engine that
                    # spilled it, and a fabric-pulled entry lives on
                    # the PEER — nothing local to delete.
                    if self._tier_owned and not from_fabric:
                        self.tier.delete(kv_tier.PREFIX_KIND, digest)
                    obs_events.emit(
                        "tier_drop", tier_kind=kv_tier.PREFIX_KIND,
                        key=digest[:64],
                        reason="chain/geometry/weights mismatch",
                    )
                    break
                pages = self.prefix.allocate(1)
                if pages is None:
                    break
                try:
                    self.cache = write_page(
                        self.cache, pages[0], k, v, ks, vs
                    )
                except Exception:  # noqa: BLE001 — degrade to re-prefill
                    self.pool.release(pages)
                    if self._tier_owned and not from_fabric:
                        self.tier.delete(kv_tier.PREFIX_KIND, digest)
                    break
                self.prefix.insert_chain(node, chunk, pages)
                child = node.children.get(chunk[0])
                if child is None or child.page != pages[0]:
                    break  # insert declined (raced sibling) — released
                node = child
                node.refcount += 1
                pinned.append(node)
                i += ps
                faulted += 1
                bytes_in += kv_tier.payload_nbytes(payload)
                if from_fabric:
                    # Adopt the validated entry into the LOCAL tier:
                    # the next evict/re-admit cycle (and peers probing
                    # us) hit here instead of re-crossing the wire.
                    self.tier.put(kv_tier.PREFIX_KIND, digest, payload)
                    remote += 1
        finally:
            for n in pinned:
                self.prefix.release_node(n)
        if faulted:
            self._bump("tier_hits")
            self._bump("tier_faults", faulted)
            self._bump("tier_bytes", bytes_in)
            if remote:
                self._bump("tier_remote_pages", remote)
            obs_events.emit(
                "tier_fault", pages=faulted, bytes=bytes_in,
                matched_tokens=i, remote_pages=remote,
            )

    def _request_sampling(self, req: Request) -> tuple[float, float, int]:
        """Resolve a request's effective (temperature, top_p, top_k):
        per-request overrides beat the engine defaults."""
        t = self.temperature if req.temperature is None else req.temperature
        p = self.top_p if req.top_p is None else req.top_p
        k = self.top_k if req.top_k is None else req.top_k
        return float(t), float(p), int(k)

    def _req_key(self, req: Request) -> jax.Array:
        """One sampling subkey for ``req`` — the per-request PRNG
        protocol slot migration relies on: every draw is
        ``fold_in(request key, draw counter)``, so a migrated slot
        (which carries key + counter in its snapshot) replays the exact
        draw sequence the un-migrated run would have made, independent
        of what other slots share its batch. The request key itself is
        split off the engine key lazily on the first sampled draw."""
        if req.key is None:
            self.key, req.key = jax.random.split(self.key)
        sub = jax.random.fold_in(req.key, req.key_step)
        req.key_step += 1
        return sub

    def _sample_req(self, req: Request, logits: jax.Array) -> int:
        """Sample one token for ``req`` from ``logits [V]`` under its
        effective knobs."""
        if not bool(jnp.isfinite(logits).all()):
            raise _NonFiniteLogits(
                "non-finite logits from the admission prefill",
                slot=req.slot,
            )
        t, p, k = self._request_sampling(req)
        if t <= 0.0:
            return int(sampling.greedy(logits))
        return int(sampling.sample(logits, self._req_key(req), t, p, k))

    def _sample_slots(
        self, logits: jax.Array, toks: np.ndarray | None = None
    ) -> np.ndarray:
        """Per-slot sampling of a batched ``[max_batch, V]`` decode
        output. All-greedy batches stay one batched argmax (``toks``,
        when given, is that argmax already fetched by the caller);
        slots with ``temperature > 0`` each draw under their own knobs
        and their own per-request key (see :meth:`_req_key`)."""
        if toks is None:
            toks = np.array(sampling.greedy(logits))
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            t, p, k = self._request_sampling(req)
            if t <= 0.0:
                continue
            toks[slot] = int(
                sampling.sample(logits[slot], self._req_key(req), t, p, k)
            )
        return toks

    def _needed_pages(self, prompt_len: int, gen_len: int) -> int:
        return -(-(prompt_len + gen_len) // self.page_size)

    # -- speculative decoding ---------------------------------------------

    def _new_spec_state(self):
        """A fresh per-request SpecState under this engine's knobs —
        admission and snapshot import build through here so both get
        the same width ceiling (1 when tree speculation is off or the
        pool is quantized)."""
        from triton_distributed_tpu.models.speculative import SpecState

        return SpecState(
            self.speculative,
            w_max=self.spec_width if self._spec_tree else 1,
        )

    def _plan_drafts(self):
        """Propose a draft for every active slot — a ``TreeDraft`` when
        tree speculation is on and the slot has a genuinely branching
        candidate set, else today's linear token list. Returns
        ``(drafts, ok)``; ``ok=False`` when some slot is too close to
        ``max_length`` for even a zero-draft verify chunk (its pad rows
        would run past the page table) — that round must use the
        batched single-step decode instead."""
        from triton_distributed_tpu.models.speculative import cap_draft

        drafts: dict = {}
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            budget = req.gen_len - len(req.out)
            k = cap_draft(
                req.spec.k, int(self._kv_len[slot]), budget, self.max_length
            )
            if k < 0:
                return {}, False
            if k <= 0:
                drafts[slot] = []
                continue
            if self._spec_tree and req.spec.width > 1:
                tree = self._plan_tree(req, slot, k)
                if tree is not None:
                    drafts[slot] = tree
                    continue
            drafts[slot] = req.spec.propose(k)
        return drafts, True

    def _plan_tree(self, req, slot: int, k: int):
        """Build ``slot``'s draft trie for a ``k``-token budget, or
        None when the candidates don't actually branch (a single path
        keeps the linear verify — identical chunk shape, identical
        per-verify PRNG consumption, no mask program).

        Branch sources, merged by the trie (shared prefixes dedup):
        the radix tree's continuations of the slot's FULL history
        (other finished requests that shared this prefix and then
        diverged), the durable KV tier's RAM-resident chains (spilled
        continuations whose token identity survives in the headers),
        and the slot's own n-gram proposal as the fallback branch.

        Budgets: per-branch depth ≤ ``k`` (so emitted ≤ accepted+1
        stays within the generation budget ``cap_draft`` enforced) and
        total nodes ≤ ``round_chunk(k+1)`` — the branches beyond the
        linear draft ride ONLY in rows the chunk would have padded
        anyway, so a tree verify is never a bigger program than the
        linear verify it replaces."""
        from triton_distributed_tpu.models.speculative import TreeDraft

        from triton_distributed_tpu.models import kv_tier

        if self.prefix is None:
            return None
        hist = [int(t) for t in req.prompt] + [int(t) for t in req.out]
        paths = self.prefix.propose_continuations(
            hist, width=req.spec.width, depth=k,
            tier_chains=(
                self.tier.resident_chains()
                if self.tier is not None
                and self.tier.may_contain(kv_tier.PREFIX_KIND)
                else None
            ),
        )
        ngram = req.spec.propose(k)
        if ngram:
            paths.append(ngram)
        if not paths:
            return None
        tree = TreeDraft(int(self._tok[slot]))
        node_budget = round_chunk(k + 1)
        for p in paths:
            tree.add_path(p[:k], budget=node_budget)
        if tree.is_chain:
            return None
        return tree

    def _spec_round(self, drafts: dict[int, list[int]]) -> bool:
        """One speculative round: every slot in ``drafts`` verifies its
        draft in a single chunked forward, rolls rejected KV back (the
        host-authoritative kv_len resync IS the rollback), and appends
        ``accepted + 1`` tokens. Slots WITHOUT an entry are untouched —
        on a mixed round the caller advances them (and the verified
        slots, one more token) through the ordinary batched decode
        step. A verify that raises fails ONLY its own slot (structured
        error, clean teardown); the other slots' round proceeds.
        Returns whether slot state changed."""
        from triton_distributed_tpu.models.speculative import (
            TreeDraft,
            spec_verify_slot,
        )

        bursts: dict[int, list[int]] = {}
        rolled_total = drafted_total = accepted_total = 0
        any_failed = False
        for slot, req in enumerate(self._slots):
            if req is None or slot not in drafts:
                continue
            kv = int(self._kv_len[slot])
            draft = drafts[slot]
            t, p, k = self._request_sampling(req)
            if isinstance(draft, TreeDraft):
                if self._spec_tree_slot(req, slot, draft, kv, t, p, k,
                                        bursts):
                    any_failed = True
                else:
                    drafted_total += draft.num_drafted
                    accepted_total += len(bursts[slot]) - 1
                    rolled_total += (
                        draft.num_drafted - (len(bursts[slot]) - 1)
                    )
                continue
            # One per-request subkey per verify (the internal
            # accept/resample splits derive from it) — the draw
            # sequence stays the request's own across a migration.
            sub = self._req_key(req) if t > 0.0 else None
            try:
                emitted, self.cache, a, _ = spec_verify_slot(
                    self.model, self.cache, slot, int(self._tok[slot]),
                    draft, kv, self._prefill_mode, key=sub,
                    temperature=t, top_p=p, top_k=k,
                )
            except FaultError as e:
                # Injected faults fire at the seam BEFORE the chunk
                # program consumed (donated) the cache — per-slot
                # isolation is safe.
                self._bump("decode_faults")
                self._fail(req, "failed", f"{type(e).__name__}: {e}")
                any_failed = True
                continue
            except Exception:
                # A real mid-chunk failure may have raised AFTER the
                # chunk donated self.cache's buffers: continuing the
                # round on deleted arrays would cascade crashes across
                # the surviving slots. Re-raise to _step_guard, which
                # fails the whole in-flight set and leaves the engine
                # reusable — the honest policy when the cache can't be
                # trusted.
                raise
            if emitted is None:
                # Non-finite verify logits (the cache was still
                # threaded through — only this request is poisoned).
                self._bump("nonfinite_logits")
                self._fail(
                    req, "nan_logits",
                    f"non-finite logits in speculative verify chunk "
                    f"after {len(req.out)} tokens",
                )
                any_failed = True
                continue
            req.spec.record(len(draft), a)
            self._bump("spec_verify_steps")
            if self._moe_k:
                # The verify chunk routes draft+1 positions per slot.
                self._bump("moe_routed_tokens",
                           (len(draft) + 1) * self._moe_k)
            self._bump("spec_draft_tokens", len(draft))
            self._bump("spec_accepted_tokens", a)
            self._bump("spec_rollback_tokens", len(draft) - a)
            drafted_total += len(draft)
            accepted_total += a
            rolled_total += len(draft) - a
            self._kv_len[slot] = kv + a + 1
            bursts[slot] = emitted
        changed = self._process(lambda slot: bursts.get(slot, []))
        # Every verify left the device kv_len at the chunk's end
        # (accepted + rejected rows); resyncing the host table rolls the
        # rejected tail back and drops any evicted/failed slot's pages
        # in one write. The round's accept rate rides the span as a
        # NATIVE float (trace_span keeps numbers numeric in the event
        # ring; only the profiler's metadata may stringify).
        with trace_span(
            "spec:rollback", tokens=rolled_total,
            accept_rate=accepted_total / max(drafted_total, 1),
        ):
            self._sync_tables()
        self._spec_accept_gauge.set(
            self.stats["spec_accepted_tokens"]
            / max(self.stats["spec_draft_tokens"], 1)
        )
        return changed or any_failed

    def _spec_tree_slot(
        self, req, slot: int, tree, kv: int,
        t: float, p: float, k: int, bursts: dict,
    ) -> bool:
        """One TREE verify of ``slot`` inside a speculative round:
        single multi-branch chunk forward, sample/argmax-then-match
        walk, row-move commit of the accepted branch. On success
        ``bursts[slot]`` holds the emitted tokens and the host kv_len
        is advanced (the round's ``_sync_tables`` is the rollback, as
        in the linear path); returns True when the slot FAILED (fault
        seam or non-finite logits — same isolation contract as the
        linear arm)."""
        from triton_distributed_tpu.models.speculative import (
            commit_tree_path,
            spec_verify_tree,
        )

        nk = (lambda: self._req_key(req)) if t > 0.0 else None
        try:
            emitted, self.cache, path = spec_verify_tree(
                self.model, self.cache, slot, tree, kv,
                self._prefill_mode, next_key=nk,
                temperature=t, top_p=p, top_k=k,
            )
        except FaultError as e:
            # The seam fires before the chunk donated the cache —
            # per-slot isolation is safe (see the linear arm).
            self._bump("decode_faults")
            self._fail(req, "failed", f"{type(e).__name__}: {e}")
            return True
        except Exception:
            # Post-donation failure: re-raise to _step_guard (the
            # cache can no longer be trusted — same as the linear arm).
            raise
        if emitted is None:
            self._bump("nonfinite_logits")
            self._fail(
                req, "nan_logits",
                f"non-finite logits in speculative tree-verify chunk "
                f"after {len(req.out)} tokens",
            )
            return True
        a = len(path)
        # Commit: the accepted branch's rows move from their DFS
        # storage slots to the contiguous positions linear decode
        # would have written; a primary-branch accept is a no-op.
        moved = any(int(n) != j + 1 for j, n in enumerate(path))
        self.cache = commit_tree_path(self.cache, slot, kv, path)
        req.spec.record_tree(tree.num_drafted, tree.max_depth, a)
        self._bump("spec_verify_steps")
        self._bump("spec_tree_rounds")
        self._bump("spec_tree_nodes", tree.num_drafted)
        self._bump("spec_tree_depth", tree.max_depth)
        if moved:
            self._bump("spec_tree_branch_accepts")
        if self._moe_k:
            # The verify chunk routes every trie node's position.
            self._bump("moe_routed_tokens", len(tree) * self._moe_k)
        self._bump("spec_draft_tokens", tree.num_drafted)
        self._bump("spec_accepted_tokens", a)
        self._bump("spec_rollback_tokens", tree.num_drafted - a)
        self._kv_len[slot] = kv + a + 1
        bursts[slot] = emitted
        return False

    def _maybe_finish(self, req: Request, t: int) -> bool:
        """Evict ``req`` if token ``t`` completed it (gen_len or eos)."""
        if req.done or (self.eos_id is not None and t == self.eos_id):
            self._evict(req)  # free pages NOW
            return True
        return False

    # -- the loop --------------------------------------------------------

    def _try_admit(self, queue: deque) -> bool:
        """Admit queue heads into free slots while pages allow. Failed
        admissions (injected faults, pool exhaustion races, non-finite
        prefill logits, expired deadlines) fail ONLY their request and
        the scan continues. Returns whether anything was admitted."""
        if queue and self._pend is not None:
            # Admission mutates slot/table/pool state the in-flight
            # resident launch still reads — the pipeline syncs here
            # first. An empty queue mutates nothing and keeps the
            # pipeline unbroken (the steady resident state).
            self._drain_pend()
        admitted = False
        progress = True
        while progress:  # re-scan: a first-token eviction frees its
            progress = False          # slot for the next request
            for slot in range(self.max_batch):
                if self._slots[slot] is not None or not queue:
                    continue
                head = queue[0]
                if (head.deadline_at is not None
                        and time.monotonic() > head.deadline_at):
                    queue.popleft()
                    self._fail(
                        head, "deadline_exceeded",
                        f"deadline_s={head.deadline_s} expired before "
                        "admission",
                    )
                    progress = True
                    break
                need = self._needed_pages(len(head.prompt), head.gen_len)
                sharded = self._sharded_eligible(head)
                if sharded:
                    # A sharded slot holds at most the resident budget;
                    # the rest of its KV lives in the tier.
                    need = self.budget_pages
                m = None
                if head.snapshot is not None:
                    # Migration import does its own (prefix-delta)
                    # matching; here only a conservative availability
                    # check — full need against free + reclaimable.
                    avail = len(self.pool.free) + (
                        self.prefix.reclaimable_pages()
                        if self.prefix is not None else 0
                    )
                    if need > avail:
                        self._bump("admission_stalls")
                        progress = False
                        break
                elif sharded:
                    avail = len(self.pool.free) + (
                        self.prefix.reclaimable_pages()
                        if self.prefix is not None else 0
                    )
                    if need > avail:
                        self._bump("admission_stalls")
                        progress = False
                        break  # head-of-line waits for budget pages
                elif self.prefix is not None:
                    if self.tier is not None:
                        # Durable-tier fault-back (docs/serving.md
                        # "Tiered KV"): pull tier-resident pages of
                        # this prompt back into the tree BEFORE the
                        # match, so a spilled-then-revisited prefix
                        # re-maps instead of re-prefilling.
                        self._tier_fill(head.prompt)
                    m = self.prefix.match(head.prompt)
                    avail = (
                        len(self.pool.free)
                        + self.prefix.reclaimable_pages()
                    )
                    if need - len(m.nodes) > avail:
                        self.prefix.release_match(m)
                        self._bump("admission_stalls")
                        progress = False  # end the scan: a rescan would
                        break             # just re-stall the same head
                elif need > len(self.pool.free):
                    progress = False
                    break  # head-of-line waits for pages
                req = queue.popleft()
                try:
                    first = self._admit(req, slot, m)
                except Exception as e:  # noqa: BLE001 — isolation
                    self._admit_failure(req, m, e)
                    progress = True
                    break
                if req.status == "ok":
                    self._ring_push("admit", slot, len(req.prompt))
                if first is None:
                    # Snapshot path: either resumed mid-generation (its
                    # pending token is already out[-1]) or failed inside
                    # the import fallback — the status tells which.
                    if req.status != "ok":
                        progress = True
                        break
                    if req.timeline is not None:
                        req.timeline.stamp_first_token()
                    admitted = progress = True
                    continue
                if req.timeline is not None:
                    req.timeline.stamp_first_token()
                if self.speculative and req.spec is None:
                    req.spec = self._new_spec_state()
                    req.spec.observe(req.prompt)
                    req.spec.observe((int(first),))
                req.out.append(int(first))
                self._emit_token(req)
                # The admission-sampled token is emitted output too —
                # without this, generated_tokens undercounts by one per
                # request vs tokens_out and Engine.serve's b*gen_len.
                self._bump("generated_tokens")
                self._tok[slot] = int(first)
                admitted = progress = True
                # The admission token itself can finish the request
                # (gen_len=1, or eos as first token)...
                if not self._maybe_finish(req, int(first)) \
                        and req.prefill_only:
                    # ...and an unfinished prefill_only request exports
                    # HERE — the prefill→decode handoff's first half:
                    # the slot (prefill KV + the admission token) ships
                    # to a decode replica instead of occupying this one.
                    self._migrate_out(req, "prefill_handoff")
        if admitted:
            # A trailing first-token eviction leaves the device table
            # pointing at released pages until synced — and every exit
            # path must reach this sync (an early return here once left
            # a zombie slot decoding into freed pages).
            self._sync_tables()
        return admitted

    def _step(self) -> bool:
        """One scheduling round of the in-flight batch: a speculative
        verify + batched-decode mix, a megakernel multi-step launch, or
        one batched decode step. Returns whether slot state changed."""
        active = np.asarray([r is not None for r in self._slots], np.int32)
        kv_high = int((self._kv_len * active).max())
        if self.speculative:
            # Per-slot verify chunks ONLY for slots that drafted;
            # undraftable slots (or an all-empty plan, or a slot too
            # near max_length for a padded chunk) ride the ONE batched
            # decode step — a mixed round costs 1 + |drafted| forwards,
            # never per-slot chunks for the no-match majority, so
            # speculation never makes the no-match case slower than
            # plain serving.
            drafts, ok = self._plan_drafts()
            drafted = {s: d for s, d in drafts.items() if d} if ok else {}
            n_active = int(active.sum())
            changed = False
            if drafted:
                changed = self._spec_round(drafted)
            if not ok or len(drafted) < n_active:
                changed = self._decode_once() or changed
            return changed
        # Megakernel serving decodes in NS-step chunks: one launch
        # emits NS tokens per slot (in-kernel argmax — Gumbel-perturbed
        # per slot when sampling), then the host checks eos/gen_len. A
        # finished row's overshoot tokens are discarded; its overshoot
        # KV rows land beyond its allocated pages, where the zeroed
        # table entries route them to the trash page. Top-k/top-p
        # slots sample IN-KERNEL through the bisection filter on
        # single-rank builds; rounds that don't compose (rows near
        # max_length, filtered slots at tp > 1) fall back to single
        # steps.
        if self.mode == "mega":
            changed = self._mega_round(active, kv_high)
            if changed is not None:
                return changed
            self._bump("mega_fallback_steps")
        return self._decode_once()

    def _mega_round(self, active: np.ndarray, kv_high: int):
        """One NS-step megakernel launch — pipelined behind the
        in-flight resident launch when one is pending — or None when
        this round must use the single-step fallback: a row within NS
        of ``max_length`` (the append would overwrite cached rows past
        capacity), or — at tp > 1 only — an active slot sampling with
        top-k/top-p (the single-rank build filters IN-KERNEL through
        the bisection filter; the sharded LM head streams vocab shards
        whose running filter state does not yet cross ranks). Mixed
        greedy/sampled batches launch fused: per-slot temperatures
        scale the noise, a zero temperature zeroes it — exactly the
        greedy argmax."""
        if self._pend is not None:
            # Resident pipeline: issue the NEXT launch off the pending
            # one's device outputs FIRST (tok = its last token row,
            # halt chained, cache threaded — no host sync anywhere on
            # that path), THEN drain the pending round's tokens. The
            # next launch is parked in ``_pend`` BEFORE the drain runs:
            # a drain that raises (injected fault, non-finite logits,
            # per-slot failure) reaches the step guard with the
            # in-flight launch still owned, so ``_abort_pend`` blocks
            # on it before teardown frees pages it still reads.
            pend, self._pend = self._pend, None
            nxt = self._issue_resident(pend)
            if nxt is not None:
                self._pend = nxt
                self._bump("mega_resident_rounds")
            return self._drain_launch(pend)
        plan = self._mega_plan(active, kv_high)
        if plan is None:
            return None
        pend = self._launch_mega(plan)
        if self.resident:
            # Tokens land at the next drain site; the round made
            # progress (the launch is in flight).
            self._pend = pend
            return True
        return self._drain_launch(pend)

    def _mega_plan(self, active: np.ndarray, kv_high: int):
        """Compose the next launch from host truth: per-slot sampling
        knobs (the filtered gate), kept-row counts, the batch bucket,
        and the eos operand set. None → this round cannot launch fused
        and falls back to single steps."""
        if kv_high + self.NS > self.max_length:
            return None
        tp1 = self.model.ctx.axis_size(self.model.axis) == 1
        act = [s for s in range(self.max_batch)
               if self._slots[s] is not None]
        V = self.model.cfg.vocab_size
        filtered = False
        for slot in act:
            t, p, k = self._request_sampling(self._slots[slot])
            # Same predicate as the per-row enable below: top-k >= V
            # with top-p 1 is a no-op filter, not a filtered round —
            # treating it as one forced a permanent single-step
            # fallback at tp > 1 and a needless filtered program at
            # tp == 1.
            if t > 0.0 and (0 < k < V or p < 1.0):
                # In-kernel top-k/top-p (kernels._filtered_winner's
                # bisection) needs the full vocab row on one rank and
                # a multi-step build; otherwise single-step fallback.
                if not tp1 or self.NS <= 1:
                    return None
                filtered = True
        # Batch bucket: the smallest power-of-two program covering the
        # active slots, so a 2-slot round stops paying the
        # max_batch-wide program. Full-width rounds keep the identity
        # layout (and the exact pre-bucket launch program).
        B = self.max_batch
        if self.mega_buckets and act:
            b = 1
            while b < len(act):
                b *= 2
            B = min(b, self.max_batch)
        compact = B < self.max_batch
        rows = act + [-1] * (B - len(act)) if compact \
            else list(range(self.max_batch))
        temps = np.zeros(B, np.float32)
        # Kept-row counts: a slot finishing mid-launch (gen_len bound,
        # known NOW) emits guaranteed-overshoot rows — routed to the
        # trash page by the append so a retiring page's int8 scale
        # never covers garbage (append_n docstring).
        n_valid = np.zeros(B, np.int32)
        # Inert rows: inv_t 1, top-k window V, top-p 1, filter off.
        sampcfg = np.tile(
            np.asarray([[1.0, float(V), 1.0, 0.0]], np.float32), (B, 1)
        )
        stop_tok = np.full(B, -1, np.int32)
        for i, slot in enumerate(rows):
            req = self._slots[slot] if slot >= 0 else None
            if req is None:
                continue
            t, p, k = self._request_sampling(req)
            temps[i] = max(t, 0.0)
            n_valid[i] = min(req.gen_len - len(req.out), self.NS)
            # Mirrors sampling.filter_logits applicability exactly:
            # top-k only when 0 < k < V, top-p only when p < 1 — rows
            # with neither keep the unfiltered Gumbel argmax (enable
            # 0), bit-identical to the pre-filter sampled launch.
            en = t > 0.0 and (0 < k < V or p < 1.0)
            sampcfg[i] = [1.0 / t if t > 0.0 else 1.0,
                          float(k) if 0 < k < V else float(V),
                          min(max(p, 1e-6), 1.0),
                          1.0 if en else 0.0]
            if self.eos_id is not None:
                stop_tok[i] = self.eos_id
        sampled = bool((temps > 0.0).any())
        # Device stop-token test needs the multi-step tail (the
        # stop_step output is per-sub-step bookkeeping).
        eos = self.eos_id is not None and self.NS > 1
        return _MegaPlan(
            rows=rows, B=B, compact=compact, sampled=sampled,
            filtered=filtered, eos=eos, temps=temps, n_valid=n_valid,
            sampcfg=sampcfg if filtered else None,
            stop_tok=stop_tok if eos else None,
        )

    def _issue_resident(self, chain: _MegaLaunch):
        """Issue the next resident launch chained off ``chain``'s
        device outputs — no host sync. None when the projected state
        cannot compose another launch (the pipeline breaks; the caller
        drains and the next round replans from host truth). The slot
        set is ``chain``'s by construction: every slot-state mutation
        site drains the pipeline first, so only retires-at-drain can
        differ — those rows ride along with ``n_valid`` 0 (every KV
        write trash-routed, every token discarded at drain)."""
        n_valid = np.zeros(chain.plan.B, np.int32)
        live = False
        for i, slot in enumerate(chain.plan.rows):
            req = self._slots[slot] if slot >= 0 else None
            if req is None:
                continue
            # Projected remaining: the pending launch will emit (at
            # most) its n_valid tokens for this row before this launch
            # drains. An eos hit inside the pending launch emits fewer
            # — but then the row retires at its drain and THIS
            # launch's tokens are discarded (halt chaining already
            # stopped its KV writes in-kernel).
            rem = req.gen_len - len(req.out) - int(chain.plan.n_valid[i])
            n_valid[i] = min(max(rem, 0), self.NS)
            if n_valid[i] > 0:
                live = True
        if not live:
            return None
        # Host _kv_len is already projected past the pending launch
        # (advanced at issue); one more launch must fit under it.
        active = np.asarray(
            [r is not None for r in self._slots], np.int32
        )
        if int((self._kv_len * active).max()) + self.NS > self.max_length:
            return None
        plan = dataclasses.replace(chain.plan, n_valid=n_valid)
        return self._launch_mega(plan, chain=chain)

    def _launch_mega(self, plan: _MegaPlan,
                     chain: _MegaLaunch | None = None) -> _MegaLaunch:
        """Dispatch one NS-step launch for ``plan`` and do the
        issue-time host bookkeeping (projected ``_kv_len``, counters,
        the launch event). Nothing here syncs on device results — the
        returned record's outputs drain later (immediately for
        non-resident rounds, at the next drain site for resident)."""
        NS = self.NS
        params = self._mega_model()._step_params()  # Q8Params under wq8
        if chain is not None:
            tok = chain.toks[NS - 1]  # device gather, async
            cache_in = chain.cache
            halt_in = chain.halt
        else:
            rows = np.asarray([max(s, 0) for s in plan.rows], np.int32)
            tok = jnp.asarray(self._tok[rows].copy())
            if plan.compact:
                # Bucket launch: compacted table/kv_len views share
                # the pool buffers (the pools are page-indexed, not
                # slot-indexed). Inert filler rows keep zeroed table
                # rows (the trash page) and kv_len 0.
                tbl = self._table[rows].copy()
                kvl = self._kv_len[rows].copy()
                for i, slot in enumerate(plan.rows):
                    if slot < 0 or self._slots[slot] is None:
                        tbl[i] = 0
                        kvl[i] = 0
                cache_in = dataclasses.replace(
                    self.cache,
                    page_table=jnp.asarray(tbl),
                    kv_len=jnp.asarray(kvl),
                )
            else:
                cache_in = self.cache
            halt_in = (jnp.zeros((plan.B,), jnp.int32)
                       if plan.eos else None)
        extra = []
        if plan.eos:
            extra.append(jnp.asarray(plan.stop_tok))
            extra.append(halt_in)
        doorbell = None
        if self._ring is not None:
            # One doorbell per round; everything pushed since the last
            # publish is this round's splice (ring.py documents the
            # hardware spin this stands in for).
            state = self._ring.publish()
            doorbell = int(state[0])
            self._ring_gauge.set(int(state[3]))
            self._bump("mega_ring_doorbells")
            self._ring.consume()
            extra.append(jnp.asarray(state))
        fn = self._mega_multi_fn(
            plan.sampled, filtered=plan.filtered, eos=plan.eos,
            ring=self._ring is not None, B=plan.B,
        )
        nv = jnp.asarray(plan.n_valid)
        t0 = time.monotonic()
        if plan.sampled:
            self.key, sub = jax.random.split(self.key)
            outs = fn(params, tok, cache_in, nv, tuple(extra), sub,
                      jnp.asarray(plan.temps),
                      jnp.asarray(plan.sampcfg) if plan.filtered
                      else None)
        elif extra:
            outs = fn(params, tok, cache_in, nv, tuple(extra))
        else:
            outs = fn(params, tok, cache_in, nv)
        outs = list(outs)
        toks, _logits, new_cache = outs[:3]
        idx = 3
        ss = halt = None
        if plan.eos:
            ss, halt = outs[idx], outs[idx + 1]
            idx += 2
        ring_arr = outs[idx] if self.kernel_trace else None
        # Rebind, never ``+=``: the in-place add mutated the numpy
        # array a zero-copy ``jnp.asarray`` may have aliased into the
        # STILL-RUNNING launch's cache.kv_len (see _sync_tables).
        adv = np.zeros(self.max_batch, np.int32)
        n_active = 0
        for i, slot in enumerate(plan.rows):
            if slot >= 0 and self._slots[slot] is not None:
                n_active += 1
                if plan.n_valid[i] > 0:
                    adv[slot] = 1
        self._kv_len = self._kv_len + NS * adv
        if plan.compact:
            # Restore the full-width table view over the launch's
            # output pools; the record keeps the bucket-shaped cache
            # for chaining.
            self.cache = dataclasses.replace(
                new_cache,
                page_table=jnp.asarray(self._table.copy()),
                kv_len=jnp.asarray(self._kv_len.copy()),
            )
            self._bump("mega_bucket_launches")
        else:
            self.cache = new_cache
        if plan.filtered:
            self._bump("mega_filtered_rounds")
        self._bump("decode_steps", NS)
        if self._moe_k:
            self._bump("moe_routed_tokens", NS * n_active * self._moe_k)
        self._bump("mega_launches")
        self._ns_gauge.set(
            self.stats["decode_steps"] / max(self.stats["mega_launches"], 1)
        )
        # Active slots' request trace ids ride the launch event (and
        # the decoded ring's launch metadata), so one request can be
        # followed server → router → replica → engine → device tasks.
        # Keys are LAUNCH rows (what the device stamps into TR_SLOT) —
        # identical to engine slots for full-width launches.
        trace_ids = {
            i: self._slots[slot].trace_id
            for i, slot in enumerate(plan.rows)
            if slot >= 0 and self._slots[slot] is not None
            and self._slots[slot].trace_id
        }
        obs_events.emit(
            "mega:launch", ns=NS, active=n_active,
            sampled=int(plan.sampled),
            trace_ids=",".join(trace_ids[k] for k in sorted(trace_ids)),
        )
        return _MegaLaunch(
            plan=plan, toks=toks, cache=new_cache, ss=ss, halt=halt,
            ring=ring_arr, t0=t0, doorbell=doorbell,
            trace_ids=trace_ids,
        )

    def _drain_pend(self) -> bool:
        """THE sync point of the resident pipeline: fetch the pending
        launch's emitted tokens and run the normal emit/retire paths.
        Every slot-state mutation site (_try_admit, _apply_cancels,
        _expire_deadlines, _handoff_sweep, _update_snapshot_buffer,
        run()'s teardown) comes through here before touching state an
        in-flight launch still reads."""
        pend, self._pend = self._pend, None
        if pend is None:
            return False
        return self._drain_launch(pend)

    def _abort_pend(self) -> None:
        """Teardown-path drain: block on (then discard) the in-flight
        resident launch so no exit path leaves a launch reading slot
        state the teardown is about to reuse."""
        pend, self._pend = self._pend, None
        if pend is None:
            return
        try:
            jax.block_until_ready(pend.toks)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass

    def _drain_launch(self, pend: _MegaLaunch) -> bool:
        """Fetch one launch's outputs and emit/retire through the
        normal paths. A device stop-token hit (``ss < n_valid``)
        truncates the row's stream at the stop token — the host never
        re-tests tokens the kernel already tested — and the retire
        flows through ``_maybe_finish``'s standard ``_evict`` (pages →
        radix tree/pool exactly as before)."""
        # Chaos seam: a drain that raises must reach the step guard
        # with any just-issued resident launch parked in ``_pend``
        # (tests/test_resident.py pins the no-orphan invariant).
        fault_point("engine.mega_drain",
                    launches=self.stats["mega_launches"])
        plan = pend.plan
        toks_np = np.asarray(pend.toks)  # [NS, B] — THE host sync
        ss_np = np.asarray(pend.ss) if pend.ss is not None else None
        wall_s = time.monotonic() - pend.t0
        if pend.ring is not None:
            # Shared MegaDispatch plumbing records the launch; the
            # per-run stats ledger + registry mirror ride _bump.
            self._record_kernel_trace(
                pend.ring, pend.t0, wall_s, self.NS, pend.trace_ids,
                doorbell=pend.doorbell,
            )
            self._bump("mega_trace_launches")
        col = {slot: i for i, slot in enumerate(plan.rows) if slot >= 0}
        if ss_np is not None:
            for slot, i in col.items():
                if (self._slots[slot] is not None
                        and ss_np[i] < plan.n_valid[i]):
                    self._bump("mega_device_retires")

        def slot_tokens(slot):
            i = col.get(slot)
            if i is None:
                return ()
            n = int(plan.n_valid[i])
            if ss_np is not None:
                n = min(n, int(ss_np[i]) + 1)
            return toks_np[:n, i]

        return self._process(slot_tokens)

    def _mega_multi_fn(self, sampled: bool, *, filtered: bool = False,
                       eos: bool = False, ring: bool = False,
                       B: int | None = None):
        """The NS-step launch program (built lazily, cached per full
        option tuple — the batch bucket B is part of the key). The
        sampled wrapper draws the Gumbel noise INSIDE the jit —
        per-sub-step key splits, per-slot temperature scaling — so
        each rank materializes only its vocab shard and the kernel's
        argmax over ``logits + T_b·gumbel`` IS per-slot temperature
        sampling (the Gumbel-max trick, distribution-equal to
        ``sampling.sample`` at ``top_p=1, top_k=0``). With
        ``filtered``, the per-row ``sampcfg`` rides along and the
        in-kernel bisection filter restricts that argmax to the host
        ``filter_logits`` keep-set. ``eos``/``ring`` append the device
        stop-token operands and the work-ring snapshot. Unified call
        shape past the base four args: ``fn(params, tok, cache,
        n_valid[, extra_tuple][, key, temps, sampcfg])``."""
        key = (sampled, filtered, eos, ring, B or self.max_batch)
        fn = self._multi_fns.get(key)
        if fn is not None:
            return fn
        Bk = key[-1]
        mega = self._mega_model()
        base = mega.decode_multi_fn(
            Bk, self.max_length, self.NS, sampled=sampled,
            page=self.page_size, kv_quant=self.kv_dtype is not None,
            num_pages=int(self.cache.k_pages.shape[1]),
            valid_arg=True, trace=self.kernel_trace,
            filtered=filtered, eos=eos, ring=ring,
        )
        if sampled:
            NS = self.NS
            n = self.model.ctx.axis_size(self.model.axis)
            v_pad = mega._dims(Bk, self.max_length).v_loc * n

            def wrapped(params, tok, cache, n_valid, extra, key, temps,
                        sampcfg):
                keys = jax.random.split(key, NS)
                noise = jax.vmap(
                    lambda k: jax.random.gumbel(
                        k, (Bk, v_pad), jnp.float32
                    )
                )(keys) * temps[None, :, None]
                tail = (noise, sampcfg) if filtered else (noise,)
                return base(params, tok, cache, n_valid, *extra, *tail)

            fn = jax.jit(wrapped, donate_argnums=(2,))
        elif eos or ring:
            def wrapped_g(params, tok, cache, n_valid, extra):
                return base(params, tok, cache, n_valid, *extra)

            fn = jax.jit(wrapped_g, donate_argnums=(2,))
        else:
            fn = base
        self._multi_fns[key] = fn
        return fn

    def run(self, requests, *, results: bool = False):
        """Serve requests to completion with per-request error
        isolation. Each entry is a ``(prompt, gen_len)`` tuple or a
        :class:`Request` (the server builds Requests to carry
        per-request sampling knobs and deadlines).

        ``results=False`` (legacy): returns each request's generated
        tokens (prompt excluded), in order. Unservable requests raise
        ``ValueError`` up front (nothing runs); runtime failures finish
        the surviving requests, tear the failures down cleanly, and
        raise :class:`RequestFailedError`.

        ``results=True``: never raises for per-request failures —
        returns one :class:`RequestResult` per request (partial tokens
        + structured status/reason), the contract the model server
        speaks.

        Every run ends with the pool/radix invariant audit
        (:meth:`audit`); a bookkeeping leak raises
        :class:`PoolAuditError` at the batch that caused it.
        """
        reqs = [
            r if isinstance(r, Request)
            else Request(np.asarray(r[0], np.int32), int(r[1]))
            for r in requests
        ]
        self.stats = self._zero_stats()
        t0 = time.monotonic()
        self._round = 0
        # A fresh batch invalidates the previous one's crash-recovery
        # snapshots (their tickets latched when run() returned) — the
        # durable copies too: leftovers on disk are only meaningful
        # after a crash, and this engine did not crash.
        with self._snap_lock:
            self._snapshots = {}
        if self.tier is not None:
            from triton_distributed_tpu.models.kv_tier import SNAP_KIND

            if self._tier_owned and not self._tier_swept:
                # First run over an OWNED store: sweep every snap
                # entry, not just this object's keys — a RESPAWNED
                # process starts with empty _tier_snap_keys, and its
                # crashed predecessor's leftovers would otherwise
                # accumulate forever (recovery consumed them before
                # resubmitting; "entries mean crash", never history).
                # One-shot: later runs track their own keys, so the
                # per-batch cost stays a handful of deletes, not a
                # directory sweep.
                if self.tier.may_contain(SNAP_KIND):
                    self.tier.clear(SNAP_KIND)
            else:
                for tid in self._tier_snap_keys:
                    self.tier.delete(SNAP_KIND, tid)
            self._tier_swept = True
            self._tier_snap_keys = set()
        # Telemetry: every request gets a lifecycle timeline; the
        # server stamps enqueue at payload decode, direct callers get
        # it backfilled here (docs/observability.md).
        for r in reqs:
            if r.timeline is None:
                r.timeline = Timeline()
            r.timeline.stamp_enqueue()
            # Trace id: client-supplied or assigned here; tags admit
            # events, mega:launch events, and device-task ring records.
            # A migrated request keeps its snapshot's id, so one id
            # follows the request across engines.
            if r.trace_id is None:
                snap_tid = (
                    r.snapshot.get("trace_id")
                    if isinstance(r.snapshot, dict) else None
                )
                r.trace_id = snap_tid or f"req-{next(_TRACE_IDS)}"
        # Load shedding: the admission queue is bounded — excess
        # requests get a structured `overloaded` error immediately
        # instead of wedging the batch (clients retry with backoff).
        if self.max_queue is not None and len(reqs) > self.max_queue:
            for r in reqs[self.max_queue:]:
                self._fail(
                    r, "overloaded",
                    f"admission queue bounded at {self.max_queue} "
                    f"requests ({len(reqs)} submitted); retry with backoff",
                )
        for r in reqs:
            if r.status != "ok":
                continue
            total = len(r.prompt) + r.gen_len
            if total > self.max_length:
                msg = (
                    f"prompt+gen_len = {total} exceeds max_length "
                    f"{self.max_length}"
                )
                if not results:
                    raise ValueError(msg)
                self._fail(r, "unservable", msg)
                continue
            need = self._needed_pages(len(r.prompt), r.gen_len)
            if self._sharded_eligible(r):
                # Sharded admission only ever holds the resident
                # budget; the cold remainder lives in the KV tier.
                need = self.budget_pages
            if need > self._capacity:
                msg = (
                    f"request needs {need} pages; "
                    f"pool capacity is {self._capacity} (unservable)"
                )
                if not results:
                    raise ValueError(msg)
                self._fail(r, "unservable", msg)
                continue
            if r.deadline_s is not None:
                r.deadline_at = t0 + float(r.deadline_s)
        queue = deque(r for r in reqs if r.status == "ok")

        try:
            # Cancellations that landed before the batch (the server's
            # cancel verb is engine-lock-free, so one can beat run()
            # here) drain their requests before any admission work.
            self._apply_cancels(queue)
            self._try_admit(queue)
            while True:
                self._round += 1
                if (self._handoff_at is not None
                        and self._round > self._handoff_at):
                    # Lossless drain: export the active slots, hand the
                    # queue back; slots whose export failed keep
                    # decoding and are retried next round.
                    self._handoff_sweep(queue)
                if self._apply_cancels(queue):
                    # A cancellation freed a slot AND its pages: same
                    # admit-now rule as deadline expiry below.
                    self._sync_tables()
                    self._try_admit(queue)
                if self._expire_deadlines():
                    # An expiry freed a slot AND its pages: admit from
                    # the queue NOW — waiting for the next slot-state
                    # change would starve queued requests on a free
                    # slot for the remainder of a long decode.
                    self._sync_tables()
                    self._try_admit(queue)
                if not any(r is not None for r in self._slots):
                    if not queue:
                        break
                    if not self._try_admit(queue) and queue:
                        # Nothing in flight and the head still can't
                        # admit: capacity was validated, so this is a
                        # bookkeeping leak — fail the head rather than
                        # spin forever (the audit below will name it).
                        # The re-check matters: _try_admit itself drains
                        # expired/failed heads, so the queue may already
                        # be empty even though nothing was admitted.
                        head = queue.popleft()
                        if head.status == "ok":
                            self._fail(
                                head, "failed",
                                "admission made no progress on an idle "
                                "engine (page accounting leak?)",
                            )
                    continue
                if self._step_guard(self._step):
                    # Slot state changed: the device cache threads k/v
                    # pages, but table + kv_len are host-authoritative.
                    self._try_admit(queue)
                    self._sync_tables()
                if (self.snapshot_every
                        and self._round % self.snapshot_every == 0):
                    # Incremental crash-recovery snapshots at round
                    # boundaries — host state is consistent here.
                    self._update_snapshot_buffer()
        finally:
            self._handoff_at = None
            self._round = 0
            # Block on (and discard) any in-flight resident launch
            # BEFORE teardown reuses the state it reads.
            self._abort_pend()
            if self._ring is not None:
                # The resident session ends with the batch: items still
                # queued (the final retires, teardown cancels) have no
                # future doorbell to ride — drain them host-side so the
                # ring is empty at rest.
                flushed = self._ring.flush()
                if flushed:
                    self._bump("mega_ring_host_drains", len(flushed))
            # Crash-safe teardown: NO exit path — injected fault,
            # engine bug, KeyboardInterrupt — leaves a slot holding
            # pages, a dangling tree pin, or a stale device table; the
            # engine object stays reusable.
            leftover = [r for r in self._slots if r is not None]
            for r in leftover:
                self._fail(r, "aborted", "engine loop aborted mid-flight")
            while queue:
                r = queue.popleft()
                if r.status == "ok":
                    self._fail(
                        r, "aborted", "engine loop aborted before admission"
                    )
            if leftover:
                self._sync_tables()
            # Cancellations that raced past their request (the slot
            # finished first, or the id never matched) must not leak
            # into future batches: prune THIS batch's ids; foreign ids
            # stay armed for the batch that carries them, bounded so a
            # client spraying garbage ids can't grow the set forever.
            batch_ids = {r.ticket_id for r in reqs
                         if r.ticket_id is not None}
            with self._cancel_lock:
                self._cancelled -= batch_ids
                if len(self._cancelled) > 4096:
                    self._cancelled.clear()

        self.audit(raise_on_violation=True)
        if results:
            return [r.result() for r in reqs]
        failures = [(i, r) for i, r in enumerate(reqs) if r.status != "ok"]
        if failures:
            raise RequestFailedError(failures)
        return [np.asarray(r.out, np.int32) for r in reqs]

    # -- serving-tier hooks ------------------------------------------------

    def prefix_digest(self) -> list | None:
        """Router-side mirror export (docs/scale-out.md): the radix
        tree's cached token chains as a serializable forest
        (:meth:`PrefixCache.prefix_digest`), or None when
        ``prefix_cache`` is off. The multi-replica router scores
        prefix affinity against each replica's latest digest instead
        of touching live engine state from another thread."""
        return None if self.prefix is None else self.prefix.prefix_digest()

    def drain(self) -> int:
        """Graceful-drain hook for the serving tier: release every
        unreferenced radix page back to the pool, so a replica being
        taken out of rotation returns its cache HBM instead of
        stranding it behind a dead worker. In-use (refcounted) chains
        are never dropped — call with no requests in flight for a full
        flush. Returns the number of pages released."""
        if self.prefix is None:
            return 0
        return self.prefix.flush()

    # -- slot migration (docs/scale-out.md "Slot migration & handoff") ----

    def request_handoff(self, after_rounds: int = 0) -> None:
        """Arm the lossless-drain sweep: at the first scheduling round
        past ``after_rounds`` more rounds, every active slot is
        EXPORTED (status ``migrated`` + portable snapshot) instead of
        finishing here, and queued requests return un-run for
        re-dispatch. Thread-safe (an int write); the replica tier calls
        this from ``begin_drain(handoff=True)`` while a batch is in
        flight. Tests arm it before ``run()`` for a deterministic
        mid-generation export point."""
        self._handoff_at = self._round + int(after_rounds)

    def export_slot(self, slot: int, *, target_digest=None):
        """Snapshot one active slot (``models/slot_state.py``) — a pure
        read; the slot keeps decoding. ``target_digest`` enables the
        prefix delta: payload for pages the target's radix digest
        already covers is omitted. Call between runs or from the
        engine's own thread at a round boundary."""
        from triton_distributed_tpu.models import slot_state

        return slot_state.export_slot(
            self, slot, target_digest=target_digest
        )

    def export_slots(self) -> dict:
        """The incremental snapshot buffer (``snapshot_every`` rounds),
        keyed by ticket id — what the server's ``export_slots`` verb
        returns and the supervisor's crash recovery resumes from.
        Lock-guarded and engine-lock-free: safe to read mid-batch."""
        with self._snap_lock:
            return dict(self._snapshots)

    def _update_snapshot_buffer(self) -> None:
        """Refresh the per-ticket snapshot buffer from every active
        slot that carries a ticket id. Wholesale replacement IS the
        pruning: finished tickets drop out on the next refresh, and a
        resumed stale snapshot can only latch-lose."""
        from triton_distributed_tpu.models import slot_state

        if self._pend is not None:
            # Snapshots read slot KV the in-flight resident launch is
            # still appending to; sync the pipeline first.
            self._drain_pend()
        snaps: dict[str, dict] = {}
        for slot, req in enumerate(self._slots):
            if req is None or req.ticket_id is None:
                continue
            try:
                wire = slot_state.export_slot(self, slot).to_wire()
                if self._tier_fp is not None:
                    # Weight identity rides every buffered snapshot
                    # (and through it the supervisor's resume store):
                    # a tier-enabled engine refuses to IMPORT a
                    # snapshot carrying a different fingerprint —
                    # continuing old-weight KV under new weights is
                    # wrong bits, so it replays instead.
                    wire["model_fp"] = self._tier_fp
                snaps[req.ticket_id] = wire
            except Exception:  # noqa: BLE001 — snapshotting is best-effort
                continue
        with self._snap_lock:
            self._snapshots = snaps
        if self.tier is not None:
            # Durable snapshots (docs/scale-out.md "Durable
            # snapshots"): the buffer persists through the tier, so a
            # crashed process's LAST snapshots outlive it — a fresh
            # process over the same tier dir reads them back and
            # resumes mid-generation instead of replaying.
            from triton_distributed_tpu.models.kv_tier import SNAP_KIND

            for tid, snap in snaps.items():
                self.tier.put(SNAP_KIND, tid, snap)
            for tid in self._tier_snap_keys - set(snaps):
                self.tier.delete(SNAP_KIND, tid)
            self._tier_snap_keys = set(snaps)

    def _migrate_out(self, req: Request, reason: str,
                     snap=None) -> bool:
        """Export ``req``'s slot and tear it down with status
        ``migrated`` (the serving tier re-dispatches the snapshot
        elsewhere). Returns False — and leaves the request RUNNING —
        when the export itself fails (e.g. an injected
        ``migrate.export`` fault): the slot then simply finishes here,
        which keeps a handoff drain lossless either way. ``snap``
        short-circuits the export with a snapshot the caller already
        holds (the batched handoff sweep)."""
        from triton_distributed_tpu.models import slot_state

        slot = req.slot
        try:
            if snap is None:
                snap = slot_state.export_slot(self, slot)
            req.snapshot = snap.to_wire()
        except Exception as e:  # noqa: BLE001 — export is best-effort
            obs_events.emit(
                "migrate_failed", slot=slot,
                reason=f"{type(e).__name__}: {str(e)[:160]}",
                trace_id=req.trace_id,
            )
            return False
        req.status, req.reason = "migrated", f"slot exported ({reason})"
        self.stats["migrated_out"] += 1
        self._m_migrations.inc(reason=reason)
        self._m_mig_bytes.observe(float(snap.payload_bytes()))
        self._teardown_slot(req)
        self._finish_obs(req)
        obs_events.emit(
            "migrate_out", slot=slot, tokens_out=len(req.out),
            reason=reason, bytes=snap.payload_bytes(),
            trace_id=req.trace_id,
        )
        return True

    def _handoff_sweep(self, queue: deque) -> None:
        """The armed handoff fires: export every active slot (a slot
        whose export fails keeps decoding — retried next round) and
        mark everything still queued ``migrated`` with no snapshot
        (nothing computed yet; it re-dispatches as a plain request).

        With ``handoff_batch`` (the default) the sweep gathers every
        active slot's pages in ONE device round trip
        (``slot_state.export_slots_batch`` — bit-identical to the
        serial path); any batch failure (e.g. an injected
        ``migrate.export`` fault) degrades to the per-slot exports, so
        a single bad slot never blocks the others' handoff."""
        from triton_distributed_tpu.models import slot_state

        if self._pend is not None:
            # Exports read slot KV the in-flight resident launch is
            # still appending to; sync the pipeline first.
            self._drain_pend()
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None]
        snaps: dict = {}
        if self.handoff_batch and len(active) > 1:
            try:
                snaps = slot_state.export_slots_batch(self, active)
            except Exception:  # noqa: BLE001 — degrade to serial
                snaps = {}
        changed = False
        for slot in active:
            req = self._slots[slot]
            if req is not None and self._migrate_out(
                    req, "drain", snap=snaps.get(slot)):
                changed = True
        while queue:
            r = queue.popleft()
            if r.status == "ok":
                r.status = "migrated"
                r.reason = "handoff drain before admission"
                self._finish_obs(r)
        if changed:
            self._sync_tables()

    # -- auditing ---------------------------------------------------------

    def _audit_tier(self) -> list[str]:
        """Tier-residency cross-checks (docs/serving.md "Tiered KV"),
        run by :meth:`audit` when a tier is attached: a tier entry
        holds payload COPIES never pool page ids, so the pool partition
        is tier-independent — what CAN go wrong is identity drift
        between the tree and the store. For every full tree page whose
        chain also has a tier entry, that entry's payload chain must
        equal the node's chain (a mismatch means a later fault-back of
        the evicted node would map wrong KV under this prompt); the
        store-level key↔digest check rides ``PageStore.audit``."""
        from triton_distributed_tpu.models import kv_tier

        problems: list[str] = []
        for node in self.prefix.walk() if self.prefix is not None else ():
            if len(node.chunk) != self.page_size:
                continue
            chain = [int(t) for t in node_chain(node)]
            entry = self.tier.peek(
                kv_tier.PREFIX_KIND, kv_tier.chain_digest(chain)
            )
            if entry is None:
                continue
            if [int(t) for t in entry.get("chain", [])] != chain:
                problems.append(
                    f"tier entry for tree page {node.page} carries a "
                    "different token chain than the node"
                )
        return problems

    def audit(self, *, raise_on_violation: bool = False) -> list[str]:
        """Pool/radix invariant audit (docs/serving.md): free list ∪
        slot-private pages ∪ tree pages ∪ trash page partition the pool
        exactly; shared mappings target live tree pages; tree refcounts
        equal live slot references; host table rows mirror each
        request's page list. Host-side and cheap — ``run()`` calls it
        after every batch, tests after every case. Returns violation
        strings; raises :class:`PoolAuditError` instead when asked."""
        problems: list[str] = []
        owners: dict[str, list[int]] = {}
        shared: dict[str, list[int]] = {}
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            n_sh = len(req.shared_nodes)
            owners[f"slot{slot}"] = [int(p) for p in req.pages[n_sh:]]
            shared[f"slot{slot}"] = [int(p) for p in req.pages[:n_sh]]
        if self.prefix is not None:
            problems += self.prefix.audit()
            owners["tree"] = [n.page for n in self.prefix.walk()]
            pin_counts: Counter = Counter()
            for req in self._slots:
                if req is None:
                    continue
                for node in req.shared_nodes:
                    pin_counts[id(node)] += 1
            for node in self.prefix.walk():
                live = pin_counts.get(id(node), 0)
                if node.refcount != live:
                    problems.append(
                        f"tree node page {node.page}: refcount "
                        f"{node.refcount} != {live} live slot references"
                    )
        if self.tier is not None:
            problems += [f"tier: {p}" for p in self.tier.audit()]
            problems += self._audit_tier()
        problems += self._audit_longctx()
        problems += audit_pool(
            self.pool, self.pool.num_pages, owners, shared=shared,
            reserved=(0,),
        )
        for slot in range(self.max_batch):
            req = self._slots[slot]
            row = self._table[slot]
            if req is None:
                if row.any():
                    problems.append(
                        f"inactive slot {slot} still has a nonzero "
                        "page-table row"
                    )
            else:
                want = np.zeros(self.pps, np.int32)
                want[: len(req.pages)] = req.pages
                if not np.array_equal(row, want):
                    problems.append(
                        f"slot {slot} table row disagrees with its "
                        "request's page list"
                    )
        if problems and raise_on_violation:
            raise PoolAuditError("; ".join(problems))
        return problems
