"""Continuous batching over the paged KV pool.

Beyond-parity subsystem: the reference Engine (``models/engine.py:113``)
serves fixed batches; modern serving interleaves requests — admit a new
sequence the moment pool pages free up, evict on completion, and step
the union every iteration. Its paged cache
(``mega_triton_kernel/models/paged_kv_cache.py``) is the natural
substrate, and this module is the TPU build's admission/eviction loop on
top of ours.

Design: the decode step stays ONE jitted program over a fixed
``max_batch`` of slots (static shapes — XLA's requirement). Slot state
(page table rows, kv_len, free list) lives host-side; admission writes a
slot's table row + kv_len and prefises the prompt into its pages,
eviction releases the pages. Inactive slots keep table row 0 and point
at a reserved trash page, so their (masked-out) appends land harmlessly.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import sampling
from triton_distributed_tpu.models.engine import MegaDispatch
from triton_distributed_tpu.models.paged_kv_cache import (
    PagedKVCache,
    PagePool,
    init_paged_cache,
    write_prefill,
)
from triton_distributed_tpu.models.qwen import Mode, Qwen3


@dataclasses.dataclass
class Request:
    """One generation request and its accumulated output."""

    prompt: np.ndarray  # [S] int32
    gen_len: int
    out: list[int] = dataclasses.field(default_factory=list)
    slot: int | None = None
    pages: list[int] = dataclasses.field(default_factory=list)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.gen_len


class ContinuousEngine(MegaDispatch):
    """Admission/eviction serving loop over the paged pool.

    ``max_batch`` decode slots share ``num_pages`` pool pages; a request
    is admitted when a slot AND enough pages for its prompt+gen_len are
    free. Page 0 is reserved as the trash page for inactive slots.
    """

    def __init__(
        self,
        model: Qwen3,
        *,
        max_batch: int = 4,
        page_size: int = 128,
        max_length: int | None = None,
        num_pages: int | None = None,
        mode: str = "xla",  # Mode or "mega" (megakernel decode)
        temperature: float = 0.0,
        eos_id: int | None = None,
        seed: int = 0,
        mega_cfg=None,
    ):
        self.model = model
        self.mode = mode
        self.mega_cfg = mega_cfg
        self.temperature = temperature
        self.eos_id = eos_id
        self.key = jax.random.key(seed)
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_length = max_length or model.cfg.max_length
        self.pps = self.max_length // page_size

        # +1: page 0 is reserved as the trash page every inactive slot's
        # table points at, and must not shave serviceable capacity.
        n_pages = (num_pages or max_batch * self.pps) + 1
        self.cache, self.pool = init_paged_cache(
            model.cfg, max_batch, model.ctx, model.axis,
            max_length=self.max_length, page_size=page_size,
            num_pages=n_pages, assign_pages=False,
        )
        self.pool.free = [p for p in self.pool.free if p != 0]
        self._capacity = len(self.pool.free)
        self._table = np.zeros((max_batch, self.pps), np.int32)
        self._kv_len = np.zeros((max_batch,), np.int32)
        self._dense1 = model.new_cache(1, self.max_length)
        self._slots: list[Request | None] = [None] * max_batch

    # -- slot management -------------------------------------------------

    def _sync_tables(self) -> None:
        self.cache = dataclasses.replace(
            self.cache,
            page_table=jnp.asarray(self._table),
            kv_len=jnp.asarray(self._kv_len),
        )

    def _admit(self, req: Request, slot: int) -> jax.Array:
        """Prefill ``req`` into ``slot``; returns the first sampled token."""
        s = len(req.prompt)
        n = self.model.ctx.axis_size(self.model.axis)
        pad = (-s) % n
        row = np.concatenate([req.prompt, np.zeros(pad, np.int32)])
        need = -(-(s + req.gen_len) // self.page_size)
        req.pages = self.pool.allocate(need)
        req.slot = slot
        self._table[slot] = 0
        self._table[slot, : len(req.pages)] = req.pages
        self._kv_len[slot] = s
        self._sync_tables()

        logits, self._dense1 = self.model.prefill_batched(
            jnp.asarray(row[None]), self._dense1, self._prefill_mode,
            jnp.asarray([s], jnp.int32),
        )
        self.cache = write_prefill(
            self.cache, slot, self._dense1.k, self._dense1.v, s
        )
        self._slots[slot] = req
        return self._sample(logits)[0]

    def _evict(self, req: Request) -> None:
        slot = req.slot
        self.pool.release(req.pages)
        self._table[slot] = 0  # back to the trash page
        self._kv_len[slot] = 0
        req.pages, req.slot = [], None
        self._slots[slot] = None

    def _sample(self, logits: jax.Array) -> jax.Array:
        if self.temperature <= 0.0:
            return sampling.greedy(logits)
        self.key, sub = jax.random.split(self.key)
        return sampling.sample(logits, sub, self.temperature, 1.0)

    def _needed_pages(self, prompt_len: int, gen_len: int) -> int:
        return -(-(prompt_len + gen_len) // self.page_size)

    def _maybe_finish(self, req: Request, t: int) -> bool:
        """Evict ``req`` if token ``t`` completed it (gen_len or eos)."""
        if req.done or (self.eos_id is not None and t == self.eos_id):
            self._evict(req)  # free pages NOW
            return True
        return False

    # -- the loop --------------------------------------------------------

    def run(self, requests: list[tuple[np.ndarray, int]]) -> list[np.ndarray]:
        """Serve ``(prompt, gen_len)`` requests to completion; returns
        each request's generated tokens (prompt excluded), in order."""
        reqs = [Request(np.asarray(p, np.int32), g) for p, g in requests]
        for r in reqs:
            total = len(r.prompt) + r.gen_len
            if total > self.max_length:
                raise ValueError(
                    f"prompt+gen_len = {total} exceeds max_length "
                    f"{self.max_length}"
                )
            need = self._needed_pages(len(r.prompt), r.gen_len)
            if need > self._capacity:
                raise ValueError(
                    f"request needs {need} pages; "
                    f"pool capacity is {self._capacity} (unservable)"
                )
        queue = deque(reqs)
        tok = np.zeros((self.max_batch,), np.int32)

        def try_admit() -> bool:
            admitted = False
            progress = True
            while progress:  # re-scan: a first-token eviction frees its
                progress = False          # slot for the next request
                for slot in range(self.max_batch):
                    if self._slots[slot] is None and queue:
                        need = self._needed_pages(
                            len(queue[0].prompt), queue[0].gen_len
                        )
                        if need > len(self.pool.free):
                            progress = False
                            break  # head-of-line waits for pages
                        req = queue.popleft()
                        first = self._admit(req, slot)
                        req.out.append(int(first))
                        tok[slot] = int(first)
                        admitted = progress = True
                        # The admission token itself can finish the
                        # request (gen_len=1, or eos as first token).
                        self._maybe_finish(req, int(first))
            if admitted:
                # A trailing first-token eviction leaves the device
                # table pointing at released pages until synced — and
                # every exit path must reach this sync (an early return
                # here once left a zombie slot decoding into freed
                # pages).
                self._sync_tables()
            return admitted

        # Megakernel greedy serving decodes in NS-step chunks: one
        # launch emits NS tokens per slot (in-kernel argmax), then the
        # host checks eos/gen_len. A finished row's overshoot tokens
        # are discarded; its overshoot KV rows land beyond its
        # allocated pages, where the zeroed table entries route them to
        # the trash page. Rows near max_length fall back to single
        # steps for the tail.
        NS = 8
        use_multi = self.mode == "mega" and self.temperature <= 0.0
        multi_fn = None

        def process(slot_tokens) -> bool:
            """Append per-slot tokens; evict on gen_len/eos. Returns
            whether slot state changed."""
            changed = False
            for slot, req in enumerate(self._slots):
                if req is None:
                    continue
                for t in slot_tokens(slot):
                    req.out.append(int(t))
                    tok[slot] = int(t)
                    if self._maybe_finish(req, int(t)):
                        changed = True
                        break
            return changed

        try_admit()
        while any(r is not None for r in self._slots):
            active = np.asarray(
                [r is not None for r in self._slots], np.int32
            )
            kv_high = int((self._kv_len * active).max())
            if use_multi and kv_high + NS <= self.max_length:
                if multi_fn is None:
                    multi_fn = self._mega_model().decode_multi_fn(
                        self.max_batch, self.max_length, NS,
                        page=self.page_size,
                    )
                toks, _logits, self.cache = multi_fn(
                    # Q8Params under MegaConfig(wq8=True), else params.
                    self._mega_model()._step_params(),
                    jnp.asarray(tok), self.cache,
                )
                self._kv_len += NS * active
                toks_np = np.asarray(toks)  # [NS, max_batch]
                changed = process(lambda slot: toks_np[:, slot])
            else:
                logits, self.cache = self._decode_step(
                    jnp.asarray(tok), self.cache
                )
                self._kv_len += active
                nxt = np.asarray(self._sample(logits))
                changed = process(lambda slot: [nxt[slot]])
            if changed:
                # Slot state changed: the device cache threads k/v
                # pages, but table + kv_len are host-authoritative.
                try_admit()
                self._sync_tables()

        return [np.asarray(r.out, np.int32) for r in reqs]
