"""Serving engine: prefill + jitted decode loop.

Parity: reference ``models/engine.py`` — ``Engine.serve``:113 (prefill →
switch to dist kernels → CUDA-graph capture :75-105 → decode loop
:164-169 with per-step sampling and KV offset bump).

TPU translation: the CUDA graph is ``jax.jit`` of the whole decode step
(trace once, replay per token); the cache is donated so decode is
in-place at the XLA level. The decode loop stays a host loop (the
reference replays its graph from host too), keeping sampling/stopping
logic in Python while each step is a single device program.
"""

from __future__ import annotations

import time
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import sampling
from triton_distributed_tpu.models.kv_cache import KVCache
from triton_distributed_tpu.models.qwen import Mode, Qwen3

# Engine modes: the model's xla/pallas decode paths plus the megakernel
# ("mega"): whole-step single-kernel decode, with a multi-step fast
# path (several steps per launch, in-kernel argmax — cross-rank
# exchanged under TP, Gumbel-perturbed for temperature sampling) when
# the cache is dense and no top-p filter truncates the distribution.
EngineMode = Literal["xla", "pallas", "mega"]


class MegaDispatch:
    """Shared megakernel-mode dispatch (Engine + ContinuousEngine):
    lazy MegaQwen3 construction, xla prefill fallback, and mega-vs-model
    decode routing. Expects ``self.model`` and ``self.mode``;
    ``self.mega_cfg`` (an optional ``MegaConfig``, e.g. a sweep-tuned
    one — ``MegaConfig.from_spec(...)`` parses the
    ``perf/MEGA_TUNED.json`` config strings) customizes the kernel."""

    _mega = None
    mega_cfg = None

    @property
    def _prefill_mode(self) -> Mode:
        # The mega prefill path is single-sequence; batched serving
        # prefills through the model's own path.
        return "xla" if self.mode == "mega" else self.mode

    def _mega_model(self):
        if self._mega is None:
            from triton_distributed_tpu.megakernel import MegaQwen3

            self._mega = MegaQwen3(self.model, cfg=self.mega_cfg)
        return self._mega

    def _decode_step(self, tok, cache):
        if self.mode == "mega":
            return self._mega_model().decode_step(tok, cache)
        return self.model.decode_step(tok, cache, self.mode)


class Engine(MegaDispatch):
    """Parity: reference ``Engine`` (``models/engine.py:37``)."""

    def __init__(
        self,
        model: Qwen3,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        mode: EngineMode = "xla",
        verbose: bool = False,
        seed: int = 0,
        paged: bool = False,
        page_size: int = 128,
        mega_cfg=None,
    ):
        self.model = model
        self.temperature = temperature
        self.top_p = top_p
        self.mode = mode
        self.mega_cfg = mega_cfg
        self.verbose = verbose
        self.key = jax.random.key(seed)
        self.last_stats: dict = {}
        # Paged serving (parity: the reference megakernel's page-pool
        # cache): prefill runs dense per sequence, pages are scattered
        # through the table, decode attends the pool directly.
        self.paged = paged
        self.page_size = page_size
        # Page-pool free list, populated by the first paged serve();
        # continuous-batching admission/eviction draws from it.
        self._pool = None
        # Jitted sampled-noise wrappers, keyed by (b, s_max, NS): a
        # fresh closure per serve() would retrace + recompile the
        # megakernel program every call.
        self._sampled_multi: dict = {}

    def _sample(self, logits: jax.Array) -> jax.Array:
        if self.temperature <= 0.0:
            return sampling.greedy(logits)
        self.key, sub = jax.random.split(self.key)
        return sampling.sample(logits, sub, self.temperature, self.top_p)

    def serve(
        self,
        input_ids,  # [B, S] int32 (list/np/jnp)
        gen_len: int,
        max_length: int | None = None,
        profile: str | None = None,
        prompt_start: list | np.ndarray | None = None,
    ) -> np.ndarray:
        """Generate ``gen_len`` tokens for each sequence; returns
        ``[B, S + gen_len]`` (parity: ``Engine.serve``). ``profile``
        names a trace directory for the decode loop (parity: the
        reference Engine's 64-step decode profile, ``engine.py:151-177``).

        ``prompt_start[i]`` marks where row i's real prompt begins
        (everything before it is client left-padding, e.g. for tp
        divisibility). Rows are rolled so pads sit on the RIGHT, where
        causal masking makes them inert, and the real length rides to
        ``prefill(true_len=...)`` — pad tokens never influence output.
        """
        input_ids = np.asarray(input_ids, np.int32)
        b, s = input_ids.shape
        n = self.model.ctx.axis_size(self.model.axis)
        starts = np.zeros(b, np.int64) if prompt_start is None else (
            np.asarray(prompt_start, np.int64)
        )
        if starts.shape != (b,) or (starts < 0).any() or (starts >= s).any():
            raise ValueError(
                f"prompt_start must be [batch={b}] ints in [0, {s}); got "
                f"{starts.tolist()}"
            )
        max_length = max_length or self.model.cfg.max_length

        # Batched prefill (one jitted program for all rows — the
        # reference engine loops rows from host, engine.py:113). Client
        # left-padding rolls to the right where causal masking makes it
        # inert; tp divisibility is met by further right-padding.
        t0 = time.perf_counter()
        rows = np.stack(
            [np.roll(input_ids[i], -int(starts[i])) for i in range(b)]
        )
        pad = (-s) % n
        if pad:
            rows = np.concatenate(
                [rows, np.zeros((b, pad), np.int32)], axis=1
            )
        true_lens = (s - starts).astype(np.int32)
        # Capacity guards: the prefill writes s + pad cache rows, and
        # decode appends gen_len - 1 KV rows past each row's REAL
        # prompt (kv_len starts at true_len, not the padded width s).
        # Past max_length the dynamic_update_slice append would clamp
        # and silently overwrite cached rows (corrupt tokens, no
        # error). Left-padded rows therefore only need true_len +
        # gen_len - 1 to fit, not s + gen_len - 1.
        if s + pad > max_length:
            raise ValueError(
                f"padded prompt width ({s} + {pad}) exceeds "
                f"max_length={max_length}; raise max_length or shorten"
            )
        if int(true_lens.max()) + gen_len - 1 > max_length:
            raise ValueError(
                f"longest real prompt ({int(true_lens.max())}) + gen_len "
                f"({gen_len}) exceeds max_length={max_length}; raise "
                f"max_length or shorten"
            )
        if self.paged:
            from triton_distributed_tpu.models.paged_kv_cache import (
                init_paged_cache,
                write_prefill,
            )

            cache, self._pool = init_paged_cache(
                self.model.cfg, b, self.model.ctx, self.model.axis,
                max_length=max_length, page_size=self.page_size,
            )
            # One batch-1 dense scratch, reused per row then scattered
            # into pages — a full-batch dense cache alongside the pool
            # would double peak KV memory, defeating paging.
            dense1 = self.model.new_cache(1, max_length)
            last_logits = []
            for i in range(b):
                logits_i, dense1 = self.model.prefill_batched(
                    jnp.asarray(rows[i : i + 1]), dense1, self._prefill_mode,
                    jnp.asarray(true_lens[i : i + 1]),
                )
                cache = write_prefill(
                    cache, i, dense1.k, dense1.v, int(true_lens[i])
                )
                last_logits.append(logits_i[0])
            logits = jnp.stack(last_logits)
        else:
            cache = self.model.new_cache(b, max_length)
            logits, cache = self.model.prefill_batched(
                jnp.asarray(rows), cache, self._prefill_mode,
                jnp.asarray(true_lens),
            )
        t_prefill = time.perf_counter() - t0

        out = [input_ids]
        tok = self._sample(logits)
        out.append(np.asarray(tok)[:, None])

        from triton_distributed_tpu.runtime.profiling import group_profile

        NS = 8  # multi-step launch width
        if self.paged:
            s_max = int(cache.page_table.shape[1]) * self.page_size
        else:
            s_max = int(cache.k.shape[3])
        # Capacity: the furthest row holds max(true_lens) cached tokens
        # and gains one per decode step; a multi launch appends NS rows
        # at once, so it must not start within NS of s_max (a clamped
        # dynamic_update_slice would silently overwrite cached rows).
        kv_high = int(true_lens.max())
        # Sampling composes with multi-step via the Gumbel-max trick
        # (argmax over logits + T*gumbel == categorical(logits/T)) as
        # long as no top-p filter truncates the distribution.
        # Sampled+paged is the one uncovered combination.
        sampled = self.temperature > 0.0
        multi_launches = 0
        if (
            self.mode == "mega"
            and (not sampled or (self.top_p >= 1.0 and not self.paged))
        ):
            multi_launches = min(
                (gen_len - 1) // NS, max(s_max - kv_high, 0) // NS
            )
        t0 = time.perf_counter()
        with group_profile(profile, do_prof=profile is not None):
            left = gen_len - 1
            if multi_launches:
                # Multi-step fast path: NS steps per kernel launch
                # (in-kernel argmax — Gumbel-perturbed when sampling),
                # amortizing per-launch cost; the remainder runs
                # through the single-step kernel rather than paying a
                # full extra megakernel build per distinct tail length.
                v_pad = self.model.params.lm_head.shape[1]
                base_fn = self._mega_model().decode_multi_fn(
                    b, s_max, NS, sampled=sampled,
                    page=self.page_size if self.paged else 0,
                )
                if sampled:
                    # Draw the Gumbel noise INSIDE the jit so each rank
                    # materializes only its vocab shard — an eager
                    # host-side draw would commit a [NS, b, V_pad] f32
                    # array to one device and reshard it every launch.
                    # Cached per shape: a fresh closure per serve()
                    # would retrace + recompile the megakernel program.
                    wkey = (b, s_max, NS)
                    fn = self._sampled_multi.get(wkey)
                    if fn is None:
                        def fn(params, tok, cache, key, temp):
                            noise = temp * jax.random.gumbel(
                                key, (NS, b, v_pad), jnp.float32
                            )
                            return base_fn(params, tok, cache, noise)

                        fn = jax.jit(fn, donate_argnums=(2,))
                        self._sampled_multi[wkey] = fn
                else:
                    fn = base_fn
                for _ in range(multi_launches):
                    if sampled:
                        self.key, sub = jax.random.split(self.key)
                        extra = (sub, jnp.float32(self.temperature))
                    else:
                        extra = ()
                    toks, logits, cache = fn(
                        # _step_params: the Q8Params pytree under
                        # MegaConfig(wq8=True), model.params otherwise.
                        self._mega_model()._step_params(), tok, cache,
                        *extra,
                    )
                    toks = np.asarray(toks)  # [NS, b]
                    out.append(toks.T)
                    tok = jnp.asarray(toks[-1])
                    left -= NS
            for _ in range(left):
                logits, cache = self._decode_step(tok, cache)
                tok = self._sample(logits)
                out.append(np.asarray(tok)[:, None])
        t_decode = time.perf_counter() - t0

        self.last_stats = {
            "prefill_s": t_prefill,
            "decode_s": t_decode,
            "decode_ms_per_step": (
                t_decode / max(gen_len - 1, 1) * 1e3
            ),
            "tokens_per_s": b * max(gen_len - 1, 1) / max(t_decode, 1e-9),
        }
        if self.verbose:
            print(f"[engine] {self.last_stats}")
        return np.concatenate(out, axis=1)

