"""Serving engine: prefill + jitted decode loop.

Parity: reference ``models/engine.py`` — ``Engine.serve``:113 (prefill →
switch to dist kernels → CUDA-graph capture :75-105 → decode loop
:164-169 with per-step sampling and KV offset bump).

TPU translation: the CUDA graph is ``jax.jit`` of the whole decode step
(trace once, replay per token); the cache is donated so decode is
in-place at the XLA level. The decode loop stays a host loop (the
reference replays its graph from host too), keeping sampling/stopping
logic in Python while each step is a single device program.
"""

from __future__ import annotations

import time
import weakref
from collections import deque
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import sampling
from triton_distributed_tpu.models.kv_cache import KVCache
from triton_distributed_tpu.models.qwen import Mode, Qwen3
from triton_distributed_tpu.models.stats import STAT_METRICS
from triton_distributed_tpu.obs import metrics as obs_metrics

# Engine modes: the model's xla/pallas decode paths plus the megakernel
# ("mega"): whole-step single-kernel decode, with a multi-step fast
# path (several steps per launch, in-kernel argmax — cross-rank
# exchanged under TP, Gumbel-perturbed for temperature sampling) when
# the cache is dense and no top-p filter truncates the distribution.
EngineMode = Literal["xla", "pallas", "mega"]


class _PrefixState:
    """Engine's cross-serve prefix-cache state: the pool-backed cache
    arrays, their pool, and the radix tree over it. ``dirty`` is set for
    the duration of a serve — a crash mid-serve leaves it set, and the
    next serve rebuilds instead of reusing donated cache buffers /
    permanently pinned pages."""

    __slots__ = ("key", "cache", "pool", "tree", "dirty")

    def __init__(self, key, cache, pool, tree):
        self.key = key
        self.cache = cache
        self.pool = pool
        self.tree = tree
        self.dirty = False


def prefill_suffix_chunks(
    model,
    cache,
    slot: int,
    prompt,
    start: int,
    chunk_width: int,
    mode,
    between_chunks=None,
):
    """Chunk-prefill ``prompt[start:]`` of one slot/row over the paged
    cache — the prefix-cache suffix path, shared by ``Engine`` and
    ``ContinuousEngine`` so the chunk-width rounding and the
    offset/new_len/last_idx alignment live in exactly one place.

    ``chunk_width=0`` runs the whole suffix as one (rounded) chunk.
    ``between_chunks(cache, new_len)`` runs after every non-final chunk
    (the continuous engine interleaves a decode step there) and must
    return the cache to keep threading. Returns
    ``(last-token logits [V], cache, chunks_run)``.
    """
    from triton_distributed_tpu.models.paged_kv_cache import gather_bucket
    from triton_distributed_tpu.models.prefix_cache import round_chunk
    from triton_distributed_tpu.runtime.profiling import trace_span

    s = len(prompt)
    c = round_chunk(chunk_width) if chunk_width else round_chunk(s - start)
    page = int(cache.k_pages.shape[3])
    pps = int(cache.page_table.shape[1])
    logits, off, chunks = None, start, 0
    while off < s:
        take = min(c, s - off)
        buf = np.zeros(c, np.int32)
        buf[:take] = prompt[off : off + take]
        # Gather bucket: enough table entries to cover the chunk's last
        # (padded) position, rounded to a power of two so at most
        # log2(pages_per_seq) programs compile per chunk width — a short
        # suffix never gathers the full max_length KV view.
        kv_pages = gather_bucket(off + c, page, pps)
        with trace_span("prefix_cache:chunk", slot=slot, offset=off,
                        take=take):
            logits, cache = model.prefill_paged_chunk(
                buf, slot, off, off + take, take - 1, cache, mode,
                kv_pages=kv_pages,
            )
        chunks += 1
        off += take
        if off < s and between_chunks is not None:
            cache = between_chunks(cache, off)
    return logits, cache, chunks


class MegaDispatch:
    """Shared megakernel-mode dispatch (Engine + ContinuousEngine):
    lazy MegaQwen3 construction, xla prefill fallback, mega-vs-model
    decode routing, and the device task tracer's host plumbing.
    Expects ``self.model`` and ``self.mode``; ``self.mega_cfg`` (an
    optional ``MegaConfig``, e.g. a sweep-tuned one —
    ``MegaConfig.from_spec(...)`` parses the ``perf/MEGA_TUNED.json``
    config strings) customizes the kernel."""

    _mega = None
    mega_cfg = None

    # -- device task tracer (docs/observability.md) ----------------------

    def _init_kernel_trace(self, kernel_trace: bool, mode: str) -> None:
        """Ctor-time tracer state, shared by both engines: validates
        the knob (the tracer rides the megakernel's trace-ring
        operand; xla/pallas decode paths have no device ring) and sets
        up the bounded launch ledger."""
        if kernel_trace and mode != "mega":
            raise ValueError(
                "kernel_trace=True requires mode='mega' (the tracer "
                "rides the megakernel's trace-ring operand; the "
                "xla/pallas decode paths have no device ring)"
            )
        self.kernel_trace = bool(kernel_trace)
        self._kernel_traces: "deque" = deque(maxlen=8)
        self._trace_launch_n = 0

    def _record_kernel_trace(
        self, ring, t0: float, wall_s: float, nsteps: int,
        trace_ids: dict | None = None, doorbell: int | None = None,
    ) -> None:
        """Fold one launch's device ring into telemetry: the inline
        work is vectorized over the raw ring (gap check, per-opcode
        durations, measured overlap → registry); the launch is kept
        (bounded deque) with the ring attached, records decoding
        lazily for ``kernel_trace_summary`` and the merged timeline.
        ``doorbell`` carries the work-ring doorbell published for a
        resident round — ``validate_ring`` checks the RING_POLL task
        observed exactly it (no stale ring snapshot)."""
        from triton_distributed_tpu.obs import kernel_trace as _kt

        self._trace_launch_n += 1
        launch = _kt.KernelTraceLaunch(
            wall_s=wall_s, t0=t0, trace_ids=trace_ids or {},
            nsteps=nsteps, launch=self._trace_launch_n,
            ring=np.asarray(ring), doorbell=doorbell,
        )
        self._kernel_traces.append(launch)
        _kt.observe_launch(launch)

    def kernel_trace_launches(self) -> list:
        """Recent traced launches (``KernelTraceLaunch``), oldest
        first — what ``obs.kernel_trace.merge_with_host_profile``
        takes to add device task rows to the merged chrome timeline."""
        return list(self._kernel_traces)

    def kernel_trace_summary(self) -> dict:
        """JSON-ready device-tracer state for the server's
        ``{"cmd": "kernel_trace"}`` verb: knob, launch count (process
        lifetime), and the recent launches' per-opcode tick totals +
        measured overlap + request trace ids."""
        return {
            "enabled": self.kernel_trace,
            "mode": self.mode,
            "launches": self._trace_launch_n,
            "recent": [ln.summary() for ln in self._kernel_traces],
        }

    @property
    def _prefill_mode(self) -> Mode:
        # The mega prefill path is single-sequence; batched serving
        # prefills through the model's own path.
        return "xla" if self.mode == "mega" else self.mode

    def _mega_model(self):
        if self._mega is None:
            from triton_distributed_tpu.megakernel import MegaQwen3
            from triton_distributed_tpu.megakernel.code_generator import (
                MegaConfig,
            )

            cfg = self.mega_cfg
            if cfg is None:
                # Serving default (docs/megakernel.md "Serving fast
                # path"): fused norms + cross-task tile-0 prefetch +
                # split send-early/wait-late allreduces. All three are
                # token-exact vs the plain build (tested individually
                # and composed); overlap_ar only pays with
                # cross_prefetch on and the weight stream directly
                # after AR_WAIT, which is what fuse_norms arranges.
                cfg = MegaConfig(
                    fuse_norms=True, cross_prefetch=True, overlap_ar=True
                )
            self._mega = MegaQwen3(self.model, cfg=cfg)
        return self._mega

    def _decode_step(self, tok, cache):
        if self.mode == "mega":
            return self._mega_model().decode_step(tok, cache)
        return self.model.decode_step(tok, cache, self.mode)


class Engine(MegaDispatch):
    """Parity: reference ``Engine`` (``models/engine.py:37``)."""

    # Live engines, auditable by the shared pytest fixture
    # (tests/conftest.py) after every test.
    _live: "weakref.WeakSet[Engine]" = weakref.WeakSet()

    def __init__(
        self,
        model: Qwen3,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        top_k: int = 0,
        mode: EngineMode = "xla",
        verbose: bool = False,
        seed: int = 0,
        paged: bool = False,
        page_size: int = 128,
        mega_cfg=None,
        prefix_cache: bool = False,
        prefill_chunk: int = 0,
        speculative: int = 0,
        spec_width: int = 4,
        kv_dtype: str | None = None,
        kernel_trace: bool = False,
    ):
        self.model = model
        self.temperature = temperature
        self.top_p = top_p
        self.top_k = top_k
        self.mode = mode
        self.mega_cfg = mega_cfg
        self.verbose = verbose
        self.key = jax.random.key(seed)
        self.last_stats: dict = {}
        # Paged serving (parity: the reference megakernel's page-pool
        # cache): prefill runs dense per sequence, pages are scattered
        # through the table, decode attends the pool directly.
        self.paged = paged
        self.page_size = page_size
        # Paged geometry must tile exactly: a ragged final page would
        # make every ceil-divide table bound (``pps``, ``gather_bucket``
        # widths, per-slot page counts) silently disagree with the
        # device cache shape. Checked after the knob-composition
        # refusals below — a caller holding two mistakes should hear
        # about the flag conflict before the geometry.
        geometry_error = None
        if paged and model.cfg.max_length % page_size != 0:
            geometry_error = (
                f"max_length={model.cfg.max_length} is not a multiple "
                f"of page_size={page_size}; paged serving needs the "
                "context to tile into whole pages"
            )
        # Quantized KV storage (docs/serving.md "Quantized KV cache"):
        # int8 pool + per-page-per-head scales, dequantized inside the
        # attention kernels. The explicit knob wins over the model
        # config's ``kv_dtype``.
        self.kv_dtype = kv_dtype if kv_dtype is not None else (
            model.cfg.kv_dtype
        )
        # kv_dtype composes with every mode incl. 'mega' (the fused
        # decode dequantizes the int8 pool in-kernel via its per-page
        # scales — docs/megakernel.md "Serving fast path").
        if self.kv_dtype is not None and not paged:
            raise ValueError(
                "kv_dtype requires paged=True (scales live on the "
                "page pool; the dense cache has no pages)"
            )
        # Prefix-cache mode (requires paged): pool + cache + radix tree
        # persist ACROSS serve() calls, finished rows retire their pages
        # into the tree, and later calls prefill only uncached suffixes
        # (docs/serving.md). ``prefill_chunk`` bounds each chunk program.
        if prefix_cache and not paged:
            raise ValueError(
                "prefix_cache=True requires paged=True (the radix tree "
                "shares pool pages; a dense cache has none)"
            )
        self.prefix_cache = prefix_cache
        self.prefill_chunk = prefill_chunk
        # Speculative decoding (docs/serving.md): draft up to
        # ``speculative`` tokens per row from its own n-gram history and
        # verify them in ONE chunked paged-prefill forward; rejected
        # tokens roll the KV back. Needs the paged cache (verify chunks
        # run over the page pool) and the model decode paths (the
        # megakernel's multi-step launch already amortizes what
        # speculation would).
        if speculative:
            if not paged:
                raise ValueError(
                    "speculative=K requires paged=True (verify chunks "
                    "run through the paged chunk-prefill path)"
                )
            if mode == "mega":
                raise ValueError(
                    "speculative=K composes with mode='xla'/'pallas', "
                    "not the megakernel"
                )
        if geometry_error:
            raise ValueError(geometry_error)
        self.speculative = int(speculative)
        # Tree speculation (docs/serving.md "Speculative decoding"):
        # multi-branch draft tries verified in one chunk forward.
        # Full-width pools only — the row-move commit cannot preserve
        # quantized rows (scale reset at page offset 0), so int8 pools
        # keep width-1 chains.
        self.spec_width = max(int(spec_width), 1)
        self._spec_tree = (
            bool(speculative) and self.spec_width > 1
            and self.kv_dtype is None
        )
        # Device task tracer (docs/observability.md "Device task
        # tracer"): multi-step mega launches in serve() carry the
        # in-kernel trace ring; decoded launches feed
        # tdt_mega_task_seconds/_overlap_exposure and are kept
        # (bounded) for kernel_trace_summary / the merged timeline.
        self._init_kernel_trace(kernel_trace, mode)
        self._prefix_state: _PrefixState | None = None
        # Page-pool free list, populated by the first paged serve();
        # continuous-batching admission/eviction draws from it.
        self._pool = None
        # Jitted sampled-noise wrappers, keyed by (b, s_max, NS): a
        # fresh closure per serve() would retrace + recompile the
        # megakernel program every call.
        self._sampled_multi: dict = {}
        # Registry handles resolved ONCE (the ContinuousEngine
        # `_metric_handles` convention): serve() then increments
        # without paying a name lookup under the global registry lock.
        self._metric_handles = {
            "decode_steps": obs_metrics.counter(
                *STAT_METRICS["decode_steps"]),
            "prefill_tokens": obs_metrics.counter(
                *STAT_METRICS["prefill_tokens"]),
            "generated_tokens": obs_metrics.counter(
                *STAT_METRICS["generated_tokens"]),
            "serve_seconds": obs_metrics.histogram(
                "tdt_engine_serve_seconds",
                "Wall time of one fixed-batch serve() call."),
        }
        Engine._live.add(self)

    def audit(self, *, raise_on_violation: bool = False) -> list[str]:
        """Pool/radix invariant audit of the cross-serve prefix state
        (parity with :meth:`ContinuousEngine.audit`): between serves
        every page is either free or tree-owned (finished rows retired
        their pages), no page has two owners, and no pins are left
        behind. A ``dirty`` state (aborted serve) is skipped — it is
        rebuilt, not reused, on the next serve. Returns violation
        strings; raises ``PoolAuditError`` instead when asked."""
        state = self._prefix_state
        if state is None or state.dirty:
            return []
        from triton_distributed_tpu.models.paged_kv_cache import (
            PoolAuditError,
            audit_pool,
        )

        problems = state.tree.audit()
        for node in state.tree.walk():
            if node.refcount:
                problems.append(
                    f"idle tree node page {node.page} still pinned "
                    f"(refcount {node.refcount}) between serves"
                )
        problems += audit_pool(
            state.pool, state.pool.num_pages,
            {"tree": [n.page for n in state.tree.walk()]}, reserved=(0,),
        )
        if problems and raise_on_violation:
            raise PoolAuditError("; ".join(problems))
        return problems

    def _sample(self, logits: jax.Array) -> jax.Array:
        if self.temperature <= 0.0:
            return sampling.greedy(logits)
        self.key, sub = jax.random.split(self.key)
        return sampling.sample(
            logits, sub, self.temperature, self.top_p, self.top_k
        )

    def serve(
        self,
        input_ids,  # [B, S] int32 (list/np/jnp)
        gen_len: int,
        max_length: int | None = None,
        profile: str | None = None,
        prompt_start: list | np.ndarray | None = None,
        ns: int = 8,
    ) -> np.ndarray:
        """Generate ``gen_len`` tokens for each sequence; returns
        ``[B, S + gen_len]`` (parity: ``Engine.serve``). ``profile``
        names a trace directory for the decode loop (parity: the
        reference Engine's 64-step decode profile, ``engine.py:151-177``).

        ``prompt_start[i]`` marks where row i's real prompt begins
        (everything before it is client left-padding, e.g. for tp
        divisibility). Rows are rolled so pads sit on the RIGHT, where
        causal masking makes them inert, and the real length rides to
        ``prefill(true_len=...)`` — pad tokens never influence output.

        ``ns`` is the megakernel multi-step launch width (mode='mega'
        only; perf/mega_serve_bench.py sweeps it — wider launches
        amortize more host dispatch per token against a longer
        host-blind stretch).
        """
        input_ids = np.asarray(input_ids, np.int32)
        b, s = input_ids.shape
        n = self.model.ctx.axis_size(self.model.axis)
        starts = np.zeros(b, np.int64) if prompt_start is None else (
            np.asarray(prompt_start, np.int64)
        )
        if starts.shape != (b,) or (starts < 0).any() or (starts >= s).any():
            raise ValueError(
                f"prompt_start must be [batch={b}] ints in [0, {s}); got "
                f"{starts.tolist()}"
            )
        max_length = max_length or self.model.cfg.max_length
        if self.paged and max_length % self.page_size != 0:
            raise ValueError(
                f"max_length={max_length} is not a multiple of "
                f"page_size={self.page_size}; paged serving needs the "
                "context to tile into whole pages"
            )

        # Batched prefill (one jitted program for all rows — the
        # reference engine loops rows from host, engine.py:113). Client
        # left-padding rolls to the right where causal masking makes it
        # inert; tp divisibility is met by further right-padding.
        t0 = time.perf_counter()
        rows = np.stack(
            [np.roll(input_ids[i], -int(starts[i])) for i in range(b)]
        )
        pad = (-s) % n
        if pad:
            rows = np.concatenate(
                [rows, np.zeros((b, pad), np.int32)], axis=1
            )
        true_lens = (s - starts).astype(np.int32)
        # Capacity guards: the prefill writes s + pad cache rows, and
        # decode appends gen_len - 1 KV rows past each row's REAL
        # prompt (kv_len starts at true_len, not the padded width s).
        # Past max_length the dynamic_update_slice append would clamp
        # and silently overwrite cached rows (corrupt tokens, no
        # error). Left-padded rows therefore only need true_len +
        # gen_len - 1 to fit, not s + gen_len - 1.
        if s + pad > max_length:
            raise ValueError(
                f"padded prompt width ({s} + {pad}) exceeds "
                f"max_length={max_length}; raise max_length or shorten"
            )
        if int(true_lens.max()) + gen_len - 1 > max_length:
            raise ValueError(
                f"longest real prompt ({int(true_lens.max())}) + gen_len "
                f"({gen_len}) exceeds max_length={max_length}; raise "
                f"max_length or shorten"
            )
        if self.speculative and gen_len > 1:
            from triton_distributed_tpu.models.prefix_cache import round_chunk

            # Every verify chunk pads to round_chunk(·) ≥ 16 and its pad
            # rows write KV too — the furthest row's final chunk must
            # still fit under max_length or the page table runs out of
            # entries mid-verify (there is no batched fallback inside
            # the per-row speculative loop).
            pad = round_chunk(1)
            if int(true_lens.max()) + gen_len - 2 + pad > max_length:
                raise ValueError(
                    f"speculative serve pads verify chunks to {pad} "
                    f"tokens; longest prompt ({int(true_lens.max())}) + "
                    f"gen_len ({gen_len}) + {pad - 1} exceeds "
                    f"max_length={max_length} — raise max_length or "
                    f"shorten"
                )
        row_meta = None
        if self.paged and self.prefix_cache:
            logits, cache, row_meta = self._prefix_prefill(
                rows, true_lens, gen_len, max_length
            )
        elif self.paged:
            from triton_distributed_tpu.models.paged_kv_cache import (
                init_paged_cache,
                write_prefill,
            )

            cache, self._pool = init_paged_cache(
                self.model.cfg, b, self.model.ctx, self.model.axis,
                max_length=max_length, page_size=self.page_size,
                kv_dtype=self.kv_dtype,
            )
            # One batch-1 dense scratch, reused per row then scattered
            # into pages — a full-batch dense cache alongside the pool
            # would double peak KV memory, defeating paging.
            dense1 = self.model.new_cache(1, max_length)
            last_logits = []
            for i in range(b):
                logits_i, dense1 = self.model.prefill_batched(
                    jnp.asarray(rows[i : i + 1]), dense1, self._prefill_mode,
                    jnp.asarray(true_lens[i : i + 1]),
                )
                cache = write_prefill(
                    cache, i, dense1.k, dense1.v, int(true_lens[i])
                )
                last_logits.append(logits_i[0])
            logits = jnp.stack(last_logits)
        else:
            cache = self.model.new_cache(b, max_length)
            logits, cache = self.model.prefill_batched(
                jnp.asarray(rows), cache, self._prefill_mode,
                jnp.asarray(true_lens),
            )
        t_prefill = time.perf_counter() - t0

        out = [input_ids]
        tok = self._sample(logits)
        out.append(np.asarray(tok)[:, None])

        from triton_distributed_tpu.runtime.profiling import group_profile

        NS = int(ns)  # multi-step launch width
        if NS < 1:
            raise ValueError(f"ns must be >= 1, got {ns}")
        if self.paged:
            s_max = int(cache.page_table.shape[1]) * self.page_size
        else:
            s_max = int(cache.k.shape[3])
        # Capacity: the furthest row holds max(true_lens) cached tokens
        # and gains one per decode step; a multi launch appends NS rows
        # at once, so it must not start within NS of s_max (a clamped
        # dynamic_update_slice would silently overwrite cached rows).
        kv_high = int(true_lens.max())
        # Sampling composes with multi-step via the Gumbel-max trick
        # (argmax over logits + T*gumbel == categorical(logits/T));
        # top-p/top-k truncation now ALSO composes on single-rank
        # builds — the in-kernel bisection filter restricts that
        # argmax to the host filter_logits keep-set
        # (docs/megakernel.md "Resident decode"). Sharded LM heads
        # fall back to single steps for filtered sampling.
        V = self.model.cfg.vocab_size
        sampled = self.temperature > 0.0
        need_filter = sampled and (
            0 < self.top_k < V or self.top_p < 1.0
        )
        filtered = need_filter and n == 1 and NS > 1
        multi_launches = 0
        if (
            self.mode == "mega"
            and not self.speculative
            and (not need_filter or filtered)
        ):
            multi_launches = min(
                (gen_len - 1) // NS, max(s_max - kv_high, 0) // NS
            )
        t0 = time.perf_counter()
        spec_counters = None
        with group_profile(profile, do_prof=profile is not None):
            if self.speculative and gen_len > 1:
                tail, cache, spec_counters = self._spec_decode(
                    cache, np.asarray(tok), rows, true_lens, gen_len,
                    max_length,
                )
                out.append(tail)
            left = 0 if self.speculative else gen_len - 1
            if multi_launches:
                # Multi-step fast path: NS steps per kernel launch
                # (in-kernel argmax — Gumbel-perturbed when sampling),
                # amortizing per-launch cost; the remainder runs
                # through the single-step kernel rather than paying a
                # full extra megakernel build per distinct tail length.
                v_pad = self.model.params.lm_head.shape[1]
                quant = self.paged and self.kv_dtype is not None
                base_fn = self._mega_model().decode_multi_fn(
                    b, s_max, NS, sampled=sampled,
                    page=self.page_size if self.paged else 0,
                    kv_quant=quant,
                    num_pages=(
                        int(cache.k_pages.shape[1]) if self.paged else 0
                    ),
                    trace=self.kernel_trace,
                    filtered=filtered,
                )
                sampcfg = None
                if filtered:
                    # Per-row [1/T, top-k window, top-p, enable] the
                    # in-kernel bisection filter consumes — identical
                    # rows here (serve()'s knobs are engine-global).
                    t, k, p = self.temperature, self.top_k, self.top_p
                    sampcfg = jnp.asarray(np.tile(np.asarray(
                        [[1.0 / t, float(k) if 0 < k < V else float(V),
                          min(max(p, 1e-6), 1.0), 1.0]], np.float32,
                    ), (b, 1)))
                if sampled:
                    # Draw the Gumbel noise INSIDE the jit so each rank
                    # materializes only its vocab shard — an eager
                    # host-side draw would commit a [NS, b, V_pad] f32
                    # array to one device and reshard it every launch.
                    # Cached per shape: a fresh closure per serve()
                    # would retrace + recompile the megakernel program.
                    wkey = (b, s_max, NS, self.paged, quant, filtered)
                    fn = self._sampled_multi.get(wkey)
                    if fn is None:
                        def fn(params, tok, cache, key, temp, cfg):
                            noise = temp * jax.random.gumbel(
                                key, (NS, b, v_pad), jnp.float32
                            )
                            tail = (noise, cfg) if filtered else (noise,)
                            return base_fn(params, tok, cache, *tail)

                        fn = jax.jit(fn, donate_argnums=(2,))
                        self._sampled_multi[wkey] = fn
                else:
                    fn = base_fn
                for _ in range(multi_launches):
                    if sampled:
                        self.key, sub = jax.random.split(self.key)
                        extra = (
                            sub, jnp.float32(self.temperature), sampcfg,
                        )
                    else:
                        extra = ()
                    t_launch = time.monotonic()
                    launch_outs = fn(
                        # _step_params: the Q8Params pytree under
                        # MegaConfig(wq8=True), model.params otherwise.
                        self._mega_model()._step_params(), tok, cache,
                        *extra,
                    )
                    if self.kernel_trace:
                        toks, logits, cache, ring = launch_outs
                        toks = np.asarray(toks)  # also fences the wall
                        self._record_kernel_trace(
                            ring, t_launch,
                            time.monotonic() - t_launch, NS,
                        )
                    else:
                        toks, logits, cache = launch_outs
                        toks = np.asarray(toks)  # [NS, b]
                    out.append(toks.T)
                    tok = jnp.asarray(toks[-1])
                    left -= NS
            for _ in range(left):
                logits, cache = self._decode_step(tok, cache)
                tok = self._sample(logits)
                out.append(np.asarray(tok)[:, None])
        t_decode = time.perf_counter() - t0

        result = np.concatenate(out, axis=1)
        # Core serving-stats keys (models/stats.py): one schema both
        # engines expose, so dashboards never fork on engine type.
        # decode_steps counts batched decode programs ONLY — under
        # speculation the verify-chunk forwards ride spec_verify_steps
        # and target_steps, matching the continuous engine's ledger
        # (target_steps == decode_steps + spec_verify_steps).
        steps = max(gen_len - 1, 0)
        if spec_counters is not None:
            steps = spec_counters["spec_decode_steps"]
        # Work DONE, not accepted: prefix-cache serves count only the
        # suffix tokens actually prefilled (hits ride prefix_hit_tokens).
        prefill_toks = int(true_lens.sum())
        if row_meta is not None:
            prefill_toks = self._prefix_counters["prefill_tokens"]
        self.last_stats = {
            "prefill_s": t_prefill,
            "decode_s": t_decode,
            "decode_ms_per_step": (
                t_decode / max(gen_len - 1, 1) * 1e3
            ),
            "tokens_per_s": b * max(gen_len - 1, 1) / max(t_decode, 1e-9),
            "decode_steps": steps,
            "prefill_tokens": prefill_toks,
            "generated_tokens": int(b * gen_len),
        }
        if self.kernel_trace:
            self.last_stats["mega_trace_launches"] = self._trace_launch_n
        if obs_metrics.default_registry().enabled:
            h = self._metric_handles
            h["decode_steps"].inc(steps)
            h["prefill_tokens"].inc(prefill_toks)
            h["generated_tokens"].inc(
                self.last_stats["generated_tokens"]
            )
            h["serve_seconds"].observe(t_prefill + t_decode)
        if self.paged:
            from triton_distributed_tpu.models.paged_kv_cache import (
                kv_bytes_per_token,
            )

            self.last_stats["kv_bytes_per_token"] = kv_bytes_per_token(cache)
            self.last_stats["kv_dtype"] = (
                self.kv_dtype or str(jnp.dtype(cache.k_pages.dtype))
            )
        else:
            L, _b, H, _s, hd = cache.k.shape
            self.last_stats["kv_bytes_per_token"] = float(
                2 * L * H * hd * cache.k.dtype.itemsize
            )
            self.last_stats["kv_dtype"] = str(jnp.dtype(cache.k.dtype))
        if spec_counters is not None:
            self.last_stats.update(spec_counters)
        if getattr(self.model.cfg, "num_experts", 0):
            # MoE serving ledger (docs/serving.md "MoE serving"): the
            # fixed-batch engine computes the routed count once —
            # prefilled positions plus every decode-phase position, ×
            # top_k assignments each. Under speculation the decode
            # positions are what the verify/decode forwards actually
            # routed (Σ(draft+1) per verify chunk = draft_tokens +
            # verify_steps, plus b per batched step), matching the
            # ContinuousEngine's per-site bumps so the shared
            # tdt_moe_routed_tokens_total counter ties out across
            # engines. a2a_dropped mirrors ``DispatchState.num_dropped``
            # (ops/moe/ep_a2a.py): this engine's forward is lossless
            # (full-expert streaming), so it is 0 by construction here;
            # capacity-mode EP paths surface their detected drops
            # through the same key (perf/moe_serve_bench.py).
            k = self.model.cfg.num_experts_per_tok
            if spec_counters is not None:
                decode_pos = (
                    spec_counters["spec_draft_tokens"]
                    + spec_counters["spec_verify_steps"]
                    + b * spec_counters["spec_decode_steps"]
                )
            else:
                decode_pos = b * max(gen_len - 1, 0)
            self.last_stats["moe_routed_tokens"] = (
                (prefill_toks + decode_pos) * k
            )
            self.last_stats["a2a_dropped"] = 0
            self.last_stats["num_experts"] = self.model.cfg.num_experts
            self.last_stats["experts_per_tok"] = k
        if row_meta is not None:
            self._prefix_retire(
                result, rows, true_lens, gen_len, cache, row_meta
            )
        if self.verbose:
            print(f"[engine] {self.last_stats}")
        return result

    # -- speculative decode ------------------------------------------------

    def _spec_decode(self, cache, first_toks, rows, true_lens, gen_len,
                     max_length):
        """Per-row speculative decode over the paged cache: each row
        drafts from its own n-gram history, verifies the draft in one
        chunked forward (``spec_verify_slot``), and rolls rejected KV
        back (``rollback_kv``). Rows advance at their own pace — a row
        with a hot draft emits K+1 tokens per target step while a
        chaotic row emits 1 — until every row holds ``gen_len`` tokens.
        Returns ``(tail [b, gen_len-1], cache, counters)`` where tail
        excludes the prefill-sampled first token (already appended by
        ``serve``)."""
        from triton_distributed_tpu.models.paged_kv_cache import rollback_kv
        from triton_distributed_tpu.models.prefix_cache import round_chunk
        from triton_distributed_tpu.models.speculative import (
            SpecState,
            TreeDraft,
            cap_draft,
            commit_tree_path,
            spec_verify_slot,
            spec_verify_tree,
        )
        from triton_distributed_tpu.runtime.profiling import trace_span

        b = len(first_toks)
        kv = true_lens.astype(np.int64).copy()
        outs, states = [], []
        for i in range(b):
            st = SpecState(
                self.speculative,
                w_max=self.spec_width if self._spec_tree else 1,
            )
            st.observe(rows[i][: int(true_lens[i])])
            st.observe([int(first_toks[i])])
            states.append(st)
            outs.append([int(first_toks[i])])
        # Radix continuations feed the draft tries when the cross-serve
        # prefix state exists — previous serves' finished chains are
        # exactly the re-ask population tree speculation wins on.
        radix = (
            self._prefix_state.tree
            if self._spec_tree and self._prefix_state is not None
            else None
        )
        counters = {
            "spec_verify_steps": 0,
            "spec_decode_steps": 0,
            "spec_draft_tokens": 0,
            "spec_accepted_tokens": 0,
            "spec_rollback_tokens": 0,
            "spec_tree_rounds": 0,
            "spec_tree_nodes": 0,
            "spec_tree_depth": 0,
            "spec_tree_branch_accepts": 0,
        }

        def verify_row(i, draft, cache):
            emitted, cache, a, self.key = spec_verify_slot(
                self.model, cache, i, outs[i][-1], draft, int(kv[i]),
                self._prefill_mode, key=self.key,
                temperature=self.temperature, top_p=self.top_p,
                top_k=self.top_k,
            )
            if emitted is None:
                # Non-finite verify logits: the fixed-batch engine has
                # no per-request failure channel — fail the serve loud
                # (prefix state, if any, is marked dirty and rebuilt).
                from triton_distributed_tpu.models.sampling import (
                    NonFiniteLogitsError,
                )

                raise NonFiniteLogitsError(
                    f"non-finite logits in speculative verify chunk "
                    f"(row {i})", slot=i,
                )
            counters["spec_verify_steps"] += 1
            counters["spec_draft_tokens"] += len(draft)
            counters["spec_accepted_tokens"] += a
            states[i].record(len(draft), a)
            new_kv = int(kv[i]) + a + 1
            if a < len(draft):
                counters["spec_rollback_tokens"] += len(draft) - a
                with trace_span("spec:rollback", slot=i,
                                tokens=len(draft) - a):
                    cache = rollback_kv(cache, i, new_kv)
            kv[i] = new_kv
            states[i].observe(emitted)
            outs[i].extend(emitted)
            return cache

        def verify_tree_row(i, tr, cache):
            def nk():
                self.key, sub = jax.random.split(self.key)
                return sub

            emitted, cache, path = spec_verify_tree(
                self.model, cache, i, tr, int(kv[i]),
                self._prefill_mode, next_key=nk,
                temperature=self.temperature, top_p=self.top_p,
                top_k=self.top_k,
            )
            if emitted is None:
                from triton_distributed_tpu.models.sampling import (
                    NonFiniteLogitsError,
                )

                raise NonFiniteLogitsError(
                    f"non-finite logits in speculative tree-verify "
                    f"chunk (row {i})", slot=i,
                )
            a = len(path)
            counters["spec_verify_steps"] += 1
            counters["spec_tree_rounds"] += 1
            counters["spec_tree_nodes"] += tr.num_drafted
            counters["spec_tree_depth"] += tr.max_depth
            if any(int(n) != j + 1 for j, n in enumerate(path)):
                counters["spec_tree_branch_accepts"] += 1
            counters["spec_draft_tokens"] += tr.num_drafted
            counters["spec_accepted_tokens"] += a
            states[i].record_tree(tr.num_drafted, tr.max_depth, a)
            # Commit the accepted branch's rows into linear positions
            # BEFORE the rollback truncates kv_len past them.
            cache = commit_tree_path(cache, i, int(kv[i]), path)
            new_kv = int(kv[i]) + a + 1
            if a < tr.num_drafted:
                counters["spec_rollback_tokens"] += tr.num_drafted - a
                with trace_span("spec:rollback", slot=i,
                                tokens=tr.num_drafted - a):
                    cache = rollback_kv(cache, i, new_kv)
            kv[i] = new_kv
            states[i].observe(emitted)
            outs[i].extend(emitted)
            return cache

        def plan_row(i, k):
            """One row's draft for a ``k``-token budget: a TreeDraft
            when the candidate set genuinely branches, else a linear
            token list (possibly radix-sourced), else None."""
            if k <= 0:
                return None
            if radix is not None and states[i].width > 1:
                paths = radix.propose_continuations(
                    states[i].draft.history,
                    width=states[i].width, depth=k,
                )
                ng = states[i].propose(k)
                if ng:
                    paths.append(ng)
                if paths:
                    tr = TreeDraft(outs[i][-1])
                    for p in paths:
                        tr.add_path(p[:k], budget=round_chunk(k + 1))
                    if not tr.is_chain:
                        return tr
                    return tr.chain_tokens() or None
                return None
            return states[i].propose(k) or None

        while True:
            live = [i for i in range(b) if len(outs[i]) < gen_len]
            if not live:
                break
            drafts = {}
            for i in live:
                budget = gen_len - len(outs[i])
                k = cap_draft(
                    states[i].k, int(kv[i]), budget, max_length
                )
                assert k >= 0, "speculative capacity guard violated"
                d = plan_row(i, k)
                if d is not None:
                    drafts[i] = d
            for i, draft in drafts.items():
                if isinstance(draft, TreeDraft):
                    cache = verify_tree_row(i, draft, cache)
                else:
                    cache = verify_row(i, draft, cache)
            undrafted = [i for i in live if i not in drafts]
            if not undrafted:
                continue
            if all(len(o) < gen_len for o in outs):
                # Undraftable rows share ONE batched decode step (the
                # rollback left every row's device kv_len exact, so the
                # per-row appends land right even though rows are
                # desynced); just-verified rows simply advance one more
                # token. This keeps the no-match case as cheap as plain
                # serving instead of paying per-row padded chunks.
                pending = jnp.asarray(
                    [o[-1] for o in outs], jnp.int32
                )
                logits, cache = self._decode_step(pending, cache)
                toks = np.asarray(self._sample(logits))
                counters["spec_decode_steps"] += 1
                for i in range(b):
                    t = int(toks[i])
                    outs[i].append(t)
                    states[i].observe((t,))
                    kv[i] += 1
            else:
                # Some row already finished: a batched step would append
                # KV past its budgeted pages, so the stragglers step
                # through zero-draft verify chunks instead.
                for i in undrafted:
                    cache = verify_row(i, [], cache)
        counters["spec_accept_rate"] = (
            counters["spec_accepted_tokens"]
            / max(counters["spec_draft_tokens"], 1)
        )
        counters["target_steps"] = (
            counters["spec_verify_steps"] + counters["spec_decode_steps"]
        )
        counters["spec_tokens_per_step"] = (
            b * (gen_len - 1) / max(counters["target_steps"], 1)
        )
        tail = np.asarray([o[1:] for o in outs], np.int32)
        return tail, cache, counters

    # -- prefix-cache paged serving ---------------------------------------

    def _ensure_prefix_state(self, b: int, max_length: int) -> _PrefixState:
        """Pool + pool-backed cache + radix tree persisted across
        serve() calls (that persistence IS the prefix cache). Rebuilt —
        dropping all cached prefixes — when the batch geometry changes,
        or when the previous serve aborted mid-flight (its cache buffers
        were donated to device programs and its match pins never
        released; reuse would fail on deleted arrays / pinned pages)."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            init_paged_cache,
        )
        from triton_distributed_tpu.models.prefix_cache import PrefixCache

        key = (b, max_length, self.page_size, self.kv_dtype)
        state = self._prefix_state
        if state is None or state.key != key or state.dirty:
            pps = max_length // self.page_size
            cache, pool = init_paged_cache(
                self.model.cfg, b, self.model.ctx, self.model.axis,
                max_length=max_length, page_size=self.page_size,
                # +1: page 0 reserved as the trash page unused table
                # entries point at (same convention as ContinuousEngine).
                num_pages=b * pps + 1, assign_pages=False,
                kv_dtype=self.kv_dtype,
            )
            pool.free = [p for p in pool.free if p != 0]
            self._prefix_state = _PrefixState(
                key, cache, pool, PrefixCache(pool, self.page_size)
            )
            self._pool = pool
        return self._prefix_state

    def _prefix_prefill(self, rows, true_lens, gen_len: int, max_length: int):
        """Admission for every batch row: longest-prefix match against
        the radix tree, map matched pages into the row's table, COW-clone
        a partially matched tail, chunk-prefill only the suffix. Returns
        ``(last-token logits [b, V], cache, row_meta)``."""
        import dataclasses

        from triton_distributed_tpu.models.paged_kv_cache import copy_page
        from triton_distributed_tpu.runtime.profiling import trace_span

        b = rows.shape[0]
        state = self._ensure_prefix_state(b, max_length)
        cache, tree = state.cache, state.tree
        state.dirty = True  # in-flight; cleared by _prefix_retire
        pps = max_length // self.page_size
        table = np.zeros((b, pps), np.int32)
        row_meta = []
        matches = []
        for i in range(b):
            prompt = rows[i][: int(true_lens[i])]
            m = tree.match(prompt)
            # Positions written: the prompt plus gen_len - 1 decode
            # appends (the final sampled token is never fed back) — the
            # same bound serve()'s capacity guard enforces, so ``total``
            # never exceeds pages_per_seq.
            total = -(
                -(int(true_lens[i]) + gen_len - 1) // self.page_size
            )
            new_pages = tree.allocate(total - len(m.nodes))
            if new_pages is None and m.cow_node is not None:
                # A COW pin holds a page WITHOUT covering any of this
                # row's budget (unlike full-shared nodes), so it alone
                # can starve the pool — drop it and retry before
                # degrading further. The dropped span was counted as a
                # hit at match time; un-count what won't be reused.
                tree.release_node(m.cow_node)
                tree.stats["hit_tokens"] -= m.cow_len
                m.cow_node, m.cow_len = None, 0
                new_pages = tree.allocate(total - len(m.nodes))
            if new_pages is None:
                # Degrade to a cold row: with nothing pinned by this
                # row, full eviction always covers ≤ pages_per_seq.
                tree.release_match(m)
                new_pages = tree.allocate(total)
            assert new_pages is not None, "prefix pool sizing violated"
            pages = m.pages + new_pages
            table[i, : len(pages)] = pages
            if m.cow_len:
                # Clone the partially matched page into this row's first
                # private page NOW and drop the pin: a COW pin held
                # across later rows' allocations would shrink their
                # evictable set below the capacity argument above.
                cache = copy_page(cache, m.cow_node.page, new_pages[0])
            matches.append(m)
            row_meta.append([pages, list(m.nodes), m.matched_len,
                             m.cow_len])
            tree.finish_cow(m)
        cache = dataclasses.replace(
            cache,
            page_table=jnp.asarray(table),
            kv_len=jnp.zeros((b,), jnp.int32),
        )

        hit_tokens = prefill_tokens = cow_pages = 0
        last_logits = []
        for i in range(b):
            m = matches[i]
            s = int(true_lens[i])
            start = m.matched_len
            cow_pages += 1 if row_meta[i][3] else 0
            hit_tokens += start
            prompt = rows[i][:s]
            with trace_span(
                "prefix_cache:admit", row=i, prompt_len=s, matched=start
            ):
                logits_i, cache, _ = prefill_suffix_chunks(
                    self.model, cache, i, prompt, start,
                    self.prefill_chunk, self._prefill_mode,
                )
            prefill_tokens += s - start
            last_logits.append(logits_i)
        self.last_stats = {}  # populated by serve(); stash counters now
        self._prefix_counters = {
            "prefix_hit_tokens": hit_tokens,
            "prefill_tokens": prefill_tokens,
            "pages_cow_copied": cow_pages,
            "prefix_hit_rate": tree.hit_rate,
            "tree_pages": tree.node_count,
        }
        return jnp.stack(last_logits), cache, row_meta

    def _prefix_retire(
        self, result, rows, true_lens, gen_len: int, cache, row_meta
    ) -> None:
        """Retire every finished row's pages into the radix tree (valid
        KV covers prompt + gen_len - 1 fed-back tokens) and persist the
        cache arrays for the next serve() call."""
        state = self._prefix_state
        tree = state.tree
        s = result.shape[1] - gen_len
        gen = result[:, s:]
        for i, (pages, nodes, _matched, _cow) in enumerate(row_meta):
            toks = np.concatenate(
                [rows[i][: int(true_lens[i])],
                 gen[i, : gen_len - 1].astype(np.int32)]
            )
            tree.retire_sequence(toks, pages, nodes)
        state.cache = cache
        state.dirty = False  # clean: safe to reuse next serve()
        self.last_stats.update(self._prefix_counters)
        self.last_stats["prefix_cache"] = dict(tree.stats)

