"""Qwen3-MoE tensor-parallel model.

Parity: reference ``models/qwen_moe.py`` (206 LoC) — Qwen3 architecture
with the dense MLP swapped for the top-k routed expert FFN
(``TP_MoE``-backed, ``tp_moe.py:48``); same attention / norms / decode
flow as the dense model.

Weights follow HF ``Qwen3MoeForCausalLM`` naming
(``mlp.gate.weight`` router, ``mlp.experts.N.{gate,up,down}_proj``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.tp_attn import TPAttnParams
from triton_distributed_tpu.layers.tp_moe import TPMoEParams, tp_moe_fwd
from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.models.qwen import (
    Qwen3,
    Qwen3LayerParams,
    Qwen3Params,
    _fuse_by_shard,
)


class Qwen3MoE(Qwen3):
    """Dense Qwen3 skeleton with routed-expert MLPs (parity:
    reference ``Qwen3MoE``)."""

    def _mlp_fwd(self, mlp_params: TPMoEParams, h: jax.Array, mode) -> jax.Array:
        return tp_moe_fwd(
            mlp_params, h, self.cfg.num_experts_per_tok,
            axis=self.axis, mode=mode, ctx=self.ctx,
            norm_topk_prob=self.cfg.norm_topk_prob,
        )

    @property
    def param_specs(self) -> Qwen3Params:
        specs = super().param_specs
        specs.layers.mlp = TPMoEParams(
            w_router=P(),
            w1=P(None, None, None, self.axis),  # [L, E, d, 2*f]
            w2=P(None, None, self.axis, None),  # [L, E, f, d]
        )
        return specs

    def init_params(self, key: jax.Array) -> Qwen3Params:
        cfg = self.cfg
        if not cfg.num_experts:
            raise ValueError("Qwen3MoE needs cfg.num_experts > 0")
        n = self.ctx.axis_size(self.axis)
        L, d = cfg.num_layers, cfg.hidden_size
        e, f = cfg.num_experts, cfg.moe_intermediate_size
        dt = cfg.dtype
        hd = cfg.head_dim

        def build(key):
            ks = iter(jax.random.split(key, 12))

            def rnd(kk, *shape, scale):
                return (
                    jax.random.normal(kk, shape, jnp.float32) * scale
                ).astype(dt)

            wq = rnd(next(ks), L, d, cfg.num_q_heads * hd, scale=d**-0.5)
            wk = rnd(next(ks), L, d, cfg.num_kv_heads * hd, scale=d**-0.5)
            wv = rnd(next(ks), L, d, cfg.num_kv_heads * hd, scale=d**-0.5)
            gate = rnd(next(ks), L, e, d, f, scale=d**-0.5)
            up = rnd(next(ks), L, e, d, f, scale=d**-0.5)
            w1 = jnp.concatenate(
                [
                    gate.reshape(L, e, d, n, f // n),
                    up.reshape(L, e, d, n, f // n),
                ],
                axis=4,
            ).reshape(L, e, d, 2 * f)
            return Qwen3Params(
                embed=rnd(next(ks), cfg.vocab_size, d, scale=0.02),
                layers=Qwen3LayerParams(
                    ln1=jnp.ones((L, d), dt),
                    attn=TPAttnParams(
                        wqkv=_fuse_by_shard([wq, wk, wv], n),
                        wo=rnd(next(ks), L, cfg.num_q_heads * hd, d,
                               scale=(cfg.num_q_heads * hd) ** -0.5),
                        q_norm=jnp.ones((L, hd), dt),
                        k_norm=jnp.ones((L, hd), dt),
                    ),
                    ln2=jnp.ones((L, d), dt),
                    mlp=TPMoEParams(
                        w_router=rnd(next(ks), L, d, e, scale=d**-0.5),
                        w1=w1,
                        w2=rnd(next(ks), L, e, f, d, scale=f**-0.5),
                    ),
                ),
                norm=jnp.ones((d,), dt),
                lm_head=rnd(next(ks), d, cfg.vocab_size, scale=d**-0.5),
            )

        return self._set_params_jit(build, key)


def load_hf_moe_state_dict(
    cfg: ModelConfig, state: dict, n: int
) -> Qwen3Params:
    """Map an HF Qwen3-MoE state dict to :class:`Qwen3Params` with
    :class:`TPMoEParams` MLP leaves."""
    from triton_distributed_tpu.models.qwen import load_hf_state_dict

    L, e, f, d = (
        cfg.num_layers, cfg.num_experts, cfg.moe_intermediate_size,
        cfg.hidden_size,
    )

    def get(name):
        return jnp.asarray(state[name]).astype(cfg.dtype)

    # Reuse the dense loader for everything but the MLP by synthesizing
    # dense-shaped placeholders, then overwrite the MLP leaves. The
    # gate/up placeholders must be n columns wide — the dense loader
    # fuses them by shard (``_fuse_by_shard`` reshapes columns into n
    # groups), and a 1-column dummy is not divisible.
    dense_state = dict(state)
    zero_cols = jnp.zeros((n, d), cfg.dtype)  # torch layout [out, in]
    for i in range(L):
        p = f"model.layers.{i}.mlp."
        dense_state[p + "gate_proj.weight"] = zero_cols
        dense_state[p + "up_proj.weight"] = zero_cols
        dense_state[p + "down_proj.weight"] = zero_cols.T
    params = load_hf_state_dict(cfg, dense_state, n)

    routers, w1s, w2s = [], [], []
    for i in range(L):
        p = f"model.layers.{i}.mlp."
        routers.append(get(p + "gate.weight").T)  # [d, E]
        gates = jnp.stack(
            [get(p + f"experts.{j}.gate_proj.weight").T for j in range(e)]
        )  # [E, d, f]
        ups = jnp.stack(
            [get(p + f"experts.{j}.up_proj.weight").T for j in range(e)]
        )
        downs = jnp.stack(
            [get(p + f"experts.{j}.down_proj.weight").T for j in range(e)]
        )
        w1 = jnp.concatenate(
            [
                gates.reshape(e, d, n, f // n),
                ups.reshape(e, d, n, f // n),
            ],
            axis=3,
        ).reshape(e, d, 2 * f)
        w1s.append(w1)
        w2s.append(downs)
    params.layers.mlp = TPMoEParams(
        w_router=jnp.stack(routers),
        w1=jnp.stack(w1s),
        w2=jnp.stack(w2s),
    )
    return params
