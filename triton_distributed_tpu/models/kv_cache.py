"""KV cache (parity: reference ``models/kv_cache.py:29`` ``KV_Cache``).

Functional pytree: ``k/v [L, B, Hkv_loc, S_max, hd]`` with heads sharded
over ``tp`` (TP attention owns whole sequences of its local heads) and a
shared ``kv_len [B]`` offset — the analog of the reference's
``update_kv_cache``/``inc_offset`` torch buffers, but immutable so the
jitted decode step can donate + thread it.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.runtime.mesh import DistContext


@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [L, B, Hkv(_loc), S_max, hd]
    v: jax.Array
    kv_len: jax.Array  # [B] int32 — tokens currently cached


from triton_distributed_tpu.runtime.pytree import register_param_dataclass

register_param_dataclass(KVCache, ["k", "v", "kv_len"])


def init_cache(
    cfg: ModelConfig,
    batch_size: int,
    ctx: DistContext,
    axis: str = "tp",
    max_length: int | None = None,
) -> KVCache:
    """Allocate the sharded cache (parity: ``KV_Cache.__init__``)."""
    s_max = max_length or cfg.max_length
    shape = (cfg.num_layers, batch_size, cfg.num_kv_heads, s_max, cfg.head_dim)
    spec = (None, None, axis, None, None)
    return KVCache(
        k=ctx.shard(jnp.zeros(shape, cfg.dtype), *spec),
        v=ctx.shard(jnp.zeros(shape, cfg.dtype), *spec),
        kv_len=ctx.replicate(jnp.zeros((batch_size,), jnp.int32)),
    )


def cache_specs(axis: str = "tp"):
    """shard_map PartitionSpecs matching :func:`init_cache`."""
    from jax.sharding import PartitionSpec as P

    return KVCache(
        k=P(None, None, axis, None, None),
        v=P(None, None, axis, None, None),
        kv_len=P(),
    )
