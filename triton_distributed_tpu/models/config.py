"""Model configs (parity: reference ``models/config.py:31`` ModelConfig).

The reference keys everything off an HF model name and reads the
architecture from HF configs at load time; here the architecture fields
are explicit (JAX builds the program from static shapes) with presets for
the model families the reference ships (Qwen3 dense + MoE,
``models/__init__.py:32-48``).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    model_name: str = "Qwen/Qwen3-8B"
    vocab_size: int = 151936
    hidden_size: int = 4096
    intermediate_size: int = 12288
    num_layers: int = 36
    num_q_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 1e6
    rms_eps: float = 1e-6
    tie_word_embeddings: bool = False
    # MoE (0 experts = dense)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    # Renormalize top-k router weights to sum to 1 (HF Qwen3MoeConfig
    # field; the official Qwen3-MoE checkpoints set it true, but the HF
    # DEFAULT is false — checkpoint loading must follow the config, not
    # assume).
    norm_topk_prob: bool = True
    # runtime
    max_length: int = 4096
    dtype: jnp.dtype = jnp.bfloat16
    # Paged-KV storage width: None stores the pool in ``dtype``
    # (bit-identical legacy layout); "int8" stores int8 codes plus
    # symmetric per-page-per-head scales, dequantized inside the
    # attention kernels (docs/serving.md "Quantized KV cache").
    kv_dtype: str | None = None


# Architecture presets (numbers from the public HF configs the reference
# loads via AutoLLM; reference models/__init__.py:32-48).
_PRESETS: dict[str, dict] = {
    "Qwen/Qwen3-0.6B": dict(
        hidden_size=1024, intermediate_size=3072, num_layers=28,
        num_q_heads=16, num_kv_heads=8, head_dim=128,
        tie_word_embeddings=True,
    ),
    "Qwen/Qwen3-1.7B": dict(
        hidden_size=2048, intermediate_size=6144, num_layers=28,
        num_q_heads=16, num_kv_heads=8, head_dim=128,
        tie_word_embeddings=True,
    ),
    "Qwen/Qwen3-4B": dict(
        hidden_size=2560, intermediate_size=9728, num_layers=36,
        num_q_heads=32, num_kv_heads=8, head_dim=128,
        tie_word_embeddings=True,
    ),
    "Qwen/Qwen3-8B": dict(
        hidden_size=4096, intermediate_size=12288, num_layers=36,
        num_q_heads=32, num_kv_heads=8, head_dim=128,
    ),
    "Qwen/Qwen3-32B": dict(
        hidden_size=5120, intermediate_size=25600, num_layers=64,
        num_q_heads=64, num_kv_heads=8, head_dim=128,
    ),
    "Qwen/Qwen3-30B-A3B": dict(
        hidden_size=2048, intermediate_size=6144, num_layers=48,
        num_q_heads=32, num_kv_heads=4, head_dim=128,
        num_experts=128, num_experts_per_tok=8, moe_intermediate_size=768,
    ),
    # Tiny configs for tests / CPU-simulator runs.
    "tiny": dict(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_q_heads=8, num_kv_heads=4, head_dim=32, max_length=128,
        dtype=jnp.float32,
    ),
    "tiny-moe": dict(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_q_heads=8, num_kv_heads=4, head_dim=32, max_length=128,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=64,
        dtype=jnp.float32,
    ),
}


def get_config(model_name: str, **overrides) -> ModelConfig:
    if model_name not in _PRESETS:
        raise ValueError(
            f"unknown model {model_name!r}; presets: {sorted(_PRESETS)}"
        )
    fields = dict(_PRESETS[model_name])
    fields.update(overrides)
    return ModelConfig(model_name=model_name, **fields)
