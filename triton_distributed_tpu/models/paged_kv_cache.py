"""Paged KV cache: fixed-size pages + per-sequence page tables.

Parity: reference ``mega_triton_kernel/models/paged_kv_cache.py`` — a
page-pool cache for the megakernel decode path (pages allocated from a
free list, indirection through a page table).

TPU design: the pool is one array ``[L, num_pages, Hkv_loc, page, hd]``
(pages are just the S axis tiled), the page table is host-side state
(allocation is control-plane work, per sequence not per token), and
appends are jit-safe dynamic-slice writes at ``(page_id, offset)``.
Attention either materializes a dense view (``as_dense`` — gather by
page table, cheap at decode sizes) or consumes pages directly via the
table as a scalar-prefetch operand (future paged flash-decode).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.runtime.faults import fault_point
from triton_distributed_tpu.runtime.mesh import DistContext
from triton_distributed_tpu.runtime.pytree import register_param_dataclass


@dataclasses.dataclass
class PagedKVCache:
    k_pages: jax.Array     # [L, P, Hkv_loc, page_size, hd]
    v_pages: jax.Array
    page_table: jax.Array  # [B, pages_per_seq] int32 — page ids
    kv_len: jax.Array      # [B] int32


register_param_dataclass(
    PagedKVCache, ["k_pages", "v_pages", "page_table", "kv_len"]
)


class PagePool:
    """Host-side free-list allocator (parity: the reference's page pool).

    Page assignment is control-plane state: sequences allocate/free whole
    page lists on admission/eviction, so this stays in Python while the
    data plane (pool arrays + appends) is jitted.
    """

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self.free = list(range(num_pages - 1, -1, -1))

    def allocate(self, n: int) -> list[int]:
        fault_point("pool.allocate", n=n, free=len(self.free))
        if n > len(self.free):
            raise RuntimeError(f"page pool exhausted ({n} > {len(self.free)})")
        return [self.free.pop() for _ in range(n)]

    def release(self, pages: list[int]) -> None:
        self.free.extend(pages)


def init_paged_cache(
    cfg: ModelConfig,
    batch_size: int,
    ctx: DistContext,
    axis: str = "tp",
    *,
    max_length: int | None = None,
    page_size: int = 128,
    num_pages: int | None = None,
    assign_pages: bool = True,
) -> tuple[PagedKVCache, PagePool]:
    """Allocate the pool + page tables for ``batch_size`` sequences.

    ``assign_pages=False`` leaves the pool full and the table zeroed —
    for callers that manage page assignment themselves (continuous
    batching admits/evicts per request, possibly with ``num_pages``
    oversubscribed below ``batch_size * pages_per_seq``).
    """
    s_max = max_length or cfg.max_length
    if s_max % page_size:
        raise ValueError(f"max_length {s_max} not a page multiple")
    pages_per_seq = s_max // page_size
    num_pages = num_pages or batch_size * pages_per_seq
    pool = PagePool(num_pages)
    if assign_pages:
        table = np.asarray(
            [pool.allocate(pages_per_seq) for _ in range(batch_size)],
            np.int32,
        )
    else:
        table = np.zeros((batch_size, pages_per_seq), np.int32)
    shape = (
        cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim
    )
    spec = (None, None, axis, None, None)
    cache = PagedKVCache(
        k_pages=ctx.shard(jnp.zeros(shape, cfg.dtype), *spec),
        v_pages=ctx.shard(jnp.zeros(shape, cfg.dtype), *spec),
        page_table=ctx.replicate(jnp.asarray(table)),
        kv_len=ctx.replicate(jnp.zeros((batch_size,), jnp.int32)),
    )
    return cache, pool


class PoolAuditError(RuntimeError):
    """The pool/radix invariant audit found leaked, double-owned, or
    phantom pages — the serving loop's bookkeeping is corrupt."""


def audit_pool(
    pool: PagePool,
    num_pages: int | None = None,
    owners: dict[str, list[int]] | None = None,
    *,
    shared: dict[str, list[int]] | None = None,
    reserved: tuple[int, ...] = (0,),
) -> list[str]:
    """Cross-check the pool's ownership partition; returns violation
    strings (empty == clean).

    ``owners`` maps an owner name (``"slot3"``, ``"tree"``) to the
    pages it holds EXCLUSIVELY. ``shared`` maps an owner to pages it
    maps *by reference* (a slot's refcounted prefix pages) — those must
    belong to exactly one exclusive owner and never be free. The audit
    proves:

    - free list ∪ exclusive owners ∪ ``reserved`` == all pages
      (nothing leaked, nothing phantom),
    - no page has two exclusive owners, is both owned and free, or is
      a reserved page (the trash page is nobody's),
    - the free list holds no duplicates,
    - every shared mapping targets a live exclusively-owned page.

    Host-side and allocation-free: cheap enough to run after every
    ``run()`` (the continuous engine does) and from every test.
    """
    problems: list[str] = []
    total = pool.num_pages if num_pages is None else int(num_pages)
    free = list(pool.free)
    free_set = set(free)
    if len(free_set) != len(free):
        dup = sorted(p for p in free_set if free.count(p) > 1)
        problems.append(f"free list holds duplicate pages {dup}")
    claimed: dict[int, str] = {}
    for name, pages in (owners or {}).items():
        seen_local: set[int] = set()
        for p in pages:
            p = int(p)
            if p in seen_local:
                problems.append(f"{name} lists page {p} twice")
                continue
            seen_local.add(p)
            if p in claimed:
                problems.append(
                    f"page {p} owned by both {claimed[p]} and {name}"
                )
                continue
            claimed[p] = name
            if p in free_set:
                problems.append(
                    f"page {p} owned by {name} but also on the free list"
                )
            if p in reserved:
                problems.append(f"{name} owns reserved page {p}")
    all_pages = set(range(total))
    accounted = free_set | set(claimed) | set(reserved)
    leaked = all_pages - accounted
    if leaked:
        problems.append(f"leaked pages (no owner, not free): {sorted(leaked)}")
    phantom = accounted - all_pages
    if phantom:
        problems.append(f"unknown page ids: {sorted(phantom)}")
    for name, pages in (shared or {}).items():
        for p in pages:
            p = int(p)
            if p in free_set:
                problems.append(
                    f"{name} maps shared page {p} that is on the free list"
                )
            elif p not in claimed:
                problems.append(
                    f"{name} maps shared page {p} that no owner holds"
                )
    return problems


def gather_bucket(end_pos: int, page_size: int, pages_per_seq: int) -> int:
    """Page-table gather width for a chunk program whose queries/writes
    end at position ``end_pos``: enough table entries to cover it,
    rounded up to a power of two so at most log2(pages_per_seq)
    programs compile per chunk width. Shared by the prefix-cache suffix
    prefill and the speculative verify chunks — one definition, one
    program-key convention."""
    need = -(-int(end_pos) // page_size)
    if need <= 1:
        return 1
    return min(1 << max(need - 1, 0).bit_length(), pages_per_seq)


def rollback_kv(cache: PagedKVCache, slot, new_len) -> PagedKVCache:
    """Truncate ``slot``'s cached length to ``new_len`` (speculative
    decoding's KV rollback: a verify chunk wrote K+1 rows, acceptance
    kept a prefix, and every row past the accepted length becomes
    ordinary garbage-beyond-kv_len — masked by causality, overwritten
    by the next append). The page table is untouched: rejected rows
    live in pages the sequence still owns, so truncation is a length
    write, never an allocator round trip. ``slot``/``new_len`` are
    traced — one compiled program serves every rollback."""
    return dataclasses.replace(
        cache,
        kv_len=_set_len_jit(
            cache.kv_len,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(new_len, jnp.int32),
        ),
    )


# Donated: rollback runs once per rejected verify chunk — an eager
# .at[].set would copy the (small) kv_len array but break the cache
# threading discipline every other cache op follows.
_set_len_jit = jax.jit(
    lambda kv_len, slot, n: kv_len.at[slot].set(n), donate_argnums=(0,)
)


def truncate_pages(
    pool: PagePool,
    pages: list[int],
    keep_tokens: int,
    page_size: int,
    *,
    shared: int = 0,
) -> list[int]:
    """Release every page of ``pages`` lying wholly past ``keep_tokens``
    cached tokens back to ``pool``; returns the retained prefix.

    The first ``shared`` entries are prefix-cache-shared (mapped by
    refcount, owned by the radix tree) and are NEVER freed here
    regardless of ``keep_tokens`` — releasing them would double-free a
    page another sequence still attends. No-ops safely at page
    boundaries: ``keep_tokens`` landing exactly on a boundary keeps
    ``keep_tokens / page_size`` pages, and ``keep_tokens`` beyond the
    page list keeps everything.
    """
    if shared < 0 or shared > len(pages):
        raise ValueError(
            f"shared={shared} out of range for {len(pages)} pages"
        )
    keep = max(-(-max(int(keep_tokens), 0) // page_size), shared)
    if keep >= len(pages):
        return pages
    pool.release(pages[keep:])
    return pages[:keep]


def paged_cache_specs(axis: str = "tp"):
    """shard_map PartitionSpecs matching :func:`init_paged_cache`."""
    from jax.sharding import PartitionSpec as P

    return PagedKVCache(
        k_pages=P(None, None, axis, None, None),
        v_pages=P(None, None, axis, None, None),
        page_table=P(),
        kv_len=P(),
    )


def write_prefill(
    cache: PagedKVCache,
    b_idx: int,
    k_dense: jax.Array,  # [L, 1, Hkv_loc, S, hd] — one filled sequence
    v_dense: jax.Array,
    true_len: int,
) -> PagedKVCache:
    """Scatter a dense-prefilled sequence into its pages (host-level;
    pages are contiguous S tiles so each page is one slice copy).
    ``true_len`` is a static prompt length; ceil(true_len/page) pages
    are written (the dense source must cover that many positions)."""
    page = cache.k_pages.shape[3]
    npages = -(-int(true_len) // page)
    if k_dense.shape[3] < npages * page:
        raise ValueError(
            f"dense prefill holds {k_dense.shape[3]} positions; "
            f"{npages * page} needed for true_len={true_len}"
        )

    row = cache.page_table[b_idx]
    return PagedKVCache(
        k_pages=_scatter_jit(cache.k_pages, k_dense, row, npages, page),
        v_pages=_scatter_jit(cache.v_pages, v_dense, row, npages, page),
        page_table=cache.page_table,
        kv_len=cache.kv_len.at[b_idx].set(jnp.asarray(true_len, jnp.int32)),
    )


def _scatter(pages, dense, table_row, npages: int, page: int):
    for j in range(npages):
        pid = table_row[j]
        chunk = jax.lax.dynamic_slice_in_dim(
            dense, j * page, page, axis=3
        )[:, 0][:, None]
        pages = jax.lax.dynamic_update_slice(
            pages, chunk.astype(pages.dtype), (0, pid, 0, 0, 0)
        )
    return pages


# Donated + jitted: the page-by-page scatter updates the pool in place;
# eager dynamic_update_slices would copy the whole (GB-scale) pool once
# per page.
_scatter_jit = jax.jit(_scatter, static_argnums=(3, 4), donate_argnums=(0,))


def copy_page(cache: PagedKVCache, src: int, dst: int) -> PagedKVCache:
    """Copy one pool page (both K and V, all layers) — the prefix
    cache's copy-on-write: a partially matched shared page is cloned
    into the new sequence's private page before its suffix writes into
    it. ``src``/``dst`` are traced, so one compiled program serves every
    COW."""
    s = jnp.asarray(src, jnp.int32)
    d = jnp.asarray(dst, jnp.int32)
    return PagedKVCache(
        k_pages=_copy_page_jit(cache.k_pages, s, d),
        v_pages=_copy_page_jit(cache.v_pages, s, d),
        page_table=cache.page_table,
        kv_len=cache.kv_len,
    )


# Donated for the same reason as _scatter_jit: an eager update would
# copy the whole pool to move one page.
_copy_page_jit = jax.jit(
    lambda pages, s, d: jax.lax.dynamic_update_slice_in_dim(
        pages, jax.lax.dynamic_slice_in_dim(pages, s, 1, axis=1), d, axis=1
    ),
    donate_argnums=(0,),
)


def append(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, Hkv_loc, hd] — one token per sequence
    v_new: jax.Array,
) -> PagedKVCache:
    """Append one token per sequence at ``kv_len`` (jit-safe); the
    NS=1 case of :func:`append_n` (one scatter per pool)."""
    return append_n(cache, k_new[:, :, :, None, :], v_new[:, :, :, None, :])


def append_n(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, Hkv_loc, NS, hd] — NS tokens per sequence
    v_new: jax.Array,
) -> PagedKVCache:
    """Append ``NS`` tokens per sequence at ``kv_len`` in ONE scatter.

    The multi-step megakernel emits NS rows per launch; appending them
    row-by-row would pay the per-op dispatch tax NS times (the very
    cost multi-step exists to amortize). One advanced-index scatter
    per pool handles all (b, step) rows — page-boundary crossings fall
    out of the per-row (page_id, offset) computation.

    Caller contract: ``kv_len[b] + NS`` stays within the page table's
    capacity for every row.
    """
    page = cache.k_pages.shape[3]
    L, B, H, NS, hd = k_new.shape
    pos = cache.kv_len[:, None] + jnp.arange(NS, dtype=jnp.int32)[None]
    pids = jnp.take_along_axis(cache.page_table, pos // page, axis=1)
    flat_p = pids.reshape(-1)        # [B*NS]
    flat_o = (pos % page).reshape(-1)

    def write(pages, new):
        # Two advanced indices split by slices → advanced axes move to
        # the front: the indexed view is [B*NS, L, H, hd]. NO
        # unique_indices: inactive/finished rows all route to the trash
        # page (identical indices), where duplicate writes are fine
        # under scatter's last-write-wins but UB if claimed unique.
        upd = new.transpose(1, 3, 0, 2, 4).reshape(B * NS, L, H, hd)
        return pages.at[:, flat_p, :, flat_o, :].set(upd.astype(pages.dtype))

    return PagedKVCache(
        k_pages=write(cache.k_pages, k_new),
        v_pages=write(cache.v_pages, v_new),
        page_table=cache.page_table,
        kv_len=cache.kv_len + NS,
    )


def as_dense(cache: PagedKVCache, layer=None):
    """Materialize contiguous ``[L?, B, Hkv_loc, S_max, hd]`` views by
    gathering pages through the table (decode feeds this to
    ``flash_decode``; the page gather is a take on the page axis)."""
    from triton_distributed_tpu.ops.attention.flash_decode import (
        pages_to_dense,
    )

    kp = cache.k_pages if layer is None else cache.k_pages[layer]
    vp = cache.v_pages if layer is None else cache.v_pages[layer]
    return (
        pages_to_dense(kp, cache.page_table),
        pages_to_dense(vp, cache.page_table),
    )
