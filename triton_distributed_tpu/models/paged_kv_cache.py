"""Paged KV cache: fixed-size pages + per-sequence page tables.

Parity: reference ``mega_triton_kernel/models/paged_kv_cache.py`` — a
page-pool cache for the megakernel decode path (pages allocated from a
free list, indirection through a page table).

TPU design: the pool is one array ``[L, num_pages, Hkv_loc, page, hd]``
(pages are just the S axis tiled), the page table is host-side state
(allocation is control-plane work, per sequence not per token), and
appends are jit-safe dynamic-slice writes at ``(page_id, offset)``.
Attention either materializes a dense view (``as_dense`` — gather by
page table, cheap at decode sizes) or consumes pages directly via the
table as a scalar-prefetch operand (future paged flash-decode).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.runtime.mesh import DistContext
from triton_distributed_tpu.runtime.pytree import register_param_dataclass


@dataclasses.dataclass
class PagedKVCache:
    k_pages: jax.Array     # [L, P, Hkv_loc, page_size, hd]
    v_pages: jax.Array
    page_table: jax.Array  # [B, pages_per_seq] int32 — page ids
    kv_len: jax.Array      # [B] int32


register_param_dataclass(
    PagedKVCache, ["k_pages", "v_pages", "page_table", "kv_len"]
)


class PagePool:
    """Host-side free-list allocator (parity: the reference's page pool).

    Page assignment is control-plane state: sequences allocate/free whole
    page lists on admission/eviction, so this stays in Python while the
    data plane (pool arrays + appends) is jitted.
    """

    def __init__(self, num_pages: int):
        self.free = list(range(num_pages - 1, -1, -1))

    def allocate(self, n: int) -> list[int]:
        if n > len(self.free):
            raise RuntimeError(f"page pool exhausted ({n} > {len(self.free)})")
        return [self.free.pop() for _ in range(n)]

    def release(self, pages: list[int]) -> None:
        self.free.extend(pages)


def init_paged_cache(
    cfg: ModelConfig,
    batch_size: int,
    ctx: DistContext,
    axis: str = "tp",
    *,
    max_length: int | None = None,
    page_size: int = 128,
    num_pages: int | None = None,
) -> tuple[PagedKVCache, PagePool]:
    """Allocate the pool + page tables for ``batch_size`` sequences."""
    s_max = max_length or cfg.max_length
    if s_max % page_size:
        raise ValueError(f"max_length {s_max} not a page multiple")
    pages_per_seq = s_max // page_size
    num_pages = num_pages or batch_size * pages_per_seq
    pool = PagePool(num_pages)
    table = np.asarray(
        [pool.allocate(pages_per_seq) for _ in range(batch_size)], np.int32
    )
    shape = (
        cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim
    )
    spec = (None, None, axis, None, None)
    cache = PagedKVCache(
        k_pages=ctx.shard(jnp.zeros(shape, cfg.dtype), *spec),
        v_pages=ctx.shard(jnp.zeros(shape, cfg.dtype), *spec),
        page_table=ctx.replicate(jnp.asarray(table)),
        kv_len=ctx.replicate(jnp.zeros((batch_size,), jnp.int32)),
    )
    return cache, pool


def append(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, Hkv_loc, hd] — one token per sequence
    v_new: jax.Array,
) -> PagedKVCache:
    """Append one token per sequence at ``kv_len`` (jit-safe)."""
    page_size = cache.k_pages.shape[3]
    b = k_new.shape[1]

    def write(pages, new):
        def one(pages, b_idx):
            pos = cache.kv_len[b_idx]
            pid = cache.page_table[b_idx, pos // page_size]
            upd = new[:, b_idx][:, None, :, None, :]  # [L, 1, H, 1, hd]
            return jax.lax.dynamic_update_slice(
                pages, upd.astype(pages.dtype),
                (0, pid, 0, pos % page_size, 0),
            )

        for i in range(b):
            pages = one(pages, i)
        return pages

    return PagedKVCache(
        k_pages=write(cache.k_pages, k_new),
        v_pages=write(cache.v_pages, v_new),
        page_table=cache.page_table,
        kv_len=cache.kv_len + 1,
    )


def as_dense(cache: PagedKVCache, layer=None):
    """Materialize contiguous ``[L?, B, Hkv_loc, S_max, hd]`` views by
    gathering pages through the table (decode feeds this to
    ``flash_decode``; the page gather is a take on the page axis)."""
    kp = cache.k_pages if layer is None else cache.k_pages[layer]
    vp = cache.v_pages if layer is None else cache.v_pages[layer]

    def gather(pages):
        # pages [..., P, H, page, hd]; table [B, pps] → [..., B, H, S, hd]
        g = jnp.take(pages, cache.page_table, axis=-4)  # [..., B, pps, H, pg, hd]
        g = jnp.swapaxes(g, -4, -3)                     # [..., B, H, pps, pg, hd]
        s = g.shape
        return g.reshape(*s[:-3], s[-3] * s[-2], s[-1])

    return gather(kp), gather(vp)
