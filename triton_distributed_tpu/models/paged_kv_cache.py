"""Paged KV cache: fixed-size pages + per-sequence page tables.

Parity: reference ``mega_triton_kernel/models/paged_kv_cache.py`` — a
page-pool cache for the megakernel decode path (pages allocated from a
free list, indirection through a page table).

TPU design: the pool is one array ``[L, num_pages, Hkv_loc, page, hd]``
(pages are just the S axis tiled), the page table is host-side state
(allocation is control-plane work, per sequence not per token), and
appends are jit-safe dynamic-slice writes at ``(page_id, offset)``.
Attention either materializes a dense view (``as_dense`` — gather by
page table, cheap at decode sizes) or consumes pages directly via the
table as a scalar-prefetch operand (future paged flash-decode).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.runtime.faults import fault_point
from triton_distributed_tpu.runtime.mesh import DistContext
from triton_distributed_tpu.runtime.pytree import register_param_dataclass


@dataclasses.dataclass
class PagedKVCache:
    k_pages: jax.Array     # [L, P, Hkv_loc, page_size, hd]
    v_pages: jax.Array
    page_table: jax.Array  # [B, pages_per_seq] int32 — page ids
    kv_len: jax.Array      # [B] int32
    # Symmetric per-page-per-head dequantization scales ``[L, P,
    # Hkv_loc]`` f32 — present iff the pool stores int8
    # (``kv_dtype="int8"``). ``None`` keeps the full-width layout (and
    # every code path over it) bit-identical to the unquantized build.
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


register_param_dataclass(
    PagedKVCache,
    ["k_pages", "v_pages", "page_table", "kv_len", "k_scale", "v_scale"],
)


# -- int8 quantization ----------------------------------------------------
#
# Storage mode ``kv_dtype="int8"``: the pool keeps int8 codes plus ONE
# symmetric scale per (layer, page, kv head) — ``x ≈ code * scale`` with
# ``scale = amax / 127`` over the page's (page_size, head_dim) block.
# Scales are *monotone within a page's lifetime*: a fresh write at page
# offset 0 sets the scale absolutely (the page has no valid prior rows),
# later appends grow it via max and re-quantize the already-stored codes
# under the grown scale (ratio 1 → value-exact no-op when the scale did
# not move). Decode/prefill kernels dequantize in-register
# (``ops/attention/flash_decode.py`` / ``flash_attention.py``), so
# full-width KV never materializes in HBM.

_Q_MAX = 127.0
# Safe-division floor: an all-zero page has amax 0 → scale 0; dividing
# by the floor instead maps 0/eps → 0 rather than NaN.
_SCALE_EPS = 1e-30


def page_scales(x: jax.Array) -> jax.Array:
    """Symmetric per-page-per-head scale of ``x [..., page, hd]``:
    amax over the trailing (page, hd) block / 127."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(-2, -1)) / _Q_MAX


def quantize_page(x: jax.Array, scale: jax.Array) -> jax.Array:
    """Quantize ``x [..., page, hd]`` under ``scale [...]`` (int8,
    round-to-nearest, clipped symmetric at ±127)."""
    s = jnp.maximum(scale, _SCALE_EPS)[..., None, None]
    q = jnp.round(x.astype(jnp.float32) / s)
    return jnp.clip(q, -_Q_MAX, _Q_MAX).astype(jnp.int8)


def dequantize_page(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_page` (f32)."""
    return q.astype(jnp.float32) * scale[..., None, None]


def quantize_pages(pages: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One-shot pool quantization (tests/benches): ``[..., page, hd]``
    → ``(int8 codes, per-page-per-head scales [...])``."""
    scale = page_scales(pages)
    return quantize_page(pages, scale), scale


def quantized_row_scatter(pages, scales, rows, pids, offs):
    """Scatter ``rows [C, H, hd]`` into a ONE-LAYER int8 pool
    ``pages [P, H, page, hd]`` at ``(pids[c], offs[c])``: grow each
    touched page's ``scales [P, H]`` to cover its new rows (reset, not
    grown, when a row lands at page offset 0 — a fresh page has no
    valid prior rows, and a stale tenant's scale must not survive page
    recycling), re-quantize the touched pages' existing codes under the
    grown scale (value-exact when it did not move), then write the rows
    as int8.

    THE one implementation of the scale protocol: the chunk-prefill
    scatter and the decode append (``layers/tp_attn.py``) call it
    directly (C = chunk width / batch), and :func:`append_n` vmaps it
    over the layer axis — any change to the reset/grow/requant rule
    lands in every write path at once.

    Duplicate ``pids`` (several rows in one page, trash-page fan-in)
    are safe: scatter-min/max are associative and duplicate requant
    writes are identical."""
    rows = rows.astype(jnp.float32)
    row_sc = jnp.max(jnp.abs(rows), axis=-1) / _Q_MAX  # [C, H]
    clear = jnp.broadcast_to(
        jnp.where(offs == 0, 0.0, jnp.inf)[:, None], row_sc.shape
    )
    new_scales = scales.at[pids].min(clear)
    new_scales = new_scales.at[pids].max(row_sc)
    old_sc = jnp.take(scales, pids, axis=0)      # [C, H]
    new_sc = jnp.take(new_scales, pids, axis=0)

    def requant(pages):
        ratio = old_sc / jnp.maximum(new_sc, _SCALE_EPS)
        got = jnp.take(pages, pids, axis=0).astype(jnp.float32)
        req = jnp.clip(
            jnp.round(got * ratio[..., None, None]), -_Q_MAX, _Q_MAX
        ).astype(jnp.int8)
        return pages.at[pids].set(req)

    # Steady-state decode rarely moves a scale (a page's amax settles
    # after its first rows): skip the page-sized read+rewrite entirely
    # when every touched scale is unchanged — the requant would be a
    # value-exact no-op, but its HBM traffic is real. (Under append_n's
    # layer vmap the cond lowers to a select and both branches run;
    # that path is the mega/test batch append, not the decode loop.)
    pages = jax.lax.cond(
        jnp.any(new_sc != old_sc), requant, lambda p: p, pages
    )
    q_rows = jnp.clip(
        jnp.round(rows / jnp.maximum(new_sc[..., None], _SCALE_EPS)),
        -_Q_MAX, _Q_MAX,
    ).astype(jnp.int8)
    pages = pages.at[pids, :, offs, :].set(q_rows)
    return pages, new_scales


# vmap of the row scatter over a leading layer axis — append_n's
# quantized write ([L, P, H, page, hd] pools, rows [L, C, H, hd],
# shared (pids, offs)).
_row_scatter_layers = jax.vmap(
    quantized_row_scatter, in_axes=(0, 0, 0, None, None)
)


class PagePool:
    """Host-side free-list allocator (parity: the reference's page pool).

    Page assignment is control-plane state: sequences allocate/free whole
    page lists on admission/eviction, so this stays in Python while the
    data plane (pool arrays + appends) is jitted.
    """

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self.free = list(range(num_pages - 1, -1, -1))

    def allocate(self, n: int) -> list[int]:
        fault_point("pool.allocate", n=n, free=len(self.free))
        if n > len(self.free):
            raise RuntimeError(f"page pool exhausted ({n} > {len(self.free)})")
        return [self.free.pop() for _ in range(n)]

    def release(self, pages: list[int]) -> None:
        self.free.extend(pages)


def init_paged_cache(
    cfg: ModelConfig,
    batch_size: int,
    ctx: DistContext,
    axis: str = "tp",
    *,
    max_length: int | None = None,
    page_size: int = 128,
    num_pages: int | None = None,
    assign_pages: bool = True,
    kv_dtype: str | None = None,
) -> tuple[PagedKVCache, PagePool]:
    """Allocate the pool + page tables for ``batch_size`` sequences.

    ``assign_pages=False`` leaves the pool full and the table zeroed —
    for callers that manage page assignment themselves (continuous
    batching admits/evicts per request, possibly with ``num_pages``
    oversubscribed below ``batch_size * pages_per_seq``).

    ``kv_dtype="int8"`` (or ``cfg.kv_dtype``; the explicit argument
    wins) allocates the pool as int8 plus per-page-per-head
    ``k_scale``/``v_scale`` arrays — roughly half the bf16 pool's HBM
    bytes, dequantized inside the attention kernels. Unset keeps the
    full-width ``cfg.dtype`` pool bit-identical to the unquantized
    build.
    """
    resolved_kv = kv_dtype if kv_dtype is not None else cfg.kv_dtype
    if resolved_kv not in (None, "int8"):
        raise ValueError(
            f"kv_dtype={resolved_kv!r} unsupported; expected None or 'int8'"
        )
    s_max = max_length or cfg.max_length
    if s_max % page_size:
        raise ValueError(f"max_length {s_max} not a page multiple")
    pages_per_seq = s_max // page_size
    num_pages = num_pages or batch_size * pages_per_seq
    pool = PagePool(num_pages)
    if assign_pages:
        table = np.asarray(
            [pool.allocate(pages_per_seq) for _ in range(batch_size)],
            np.int32,
        )
    else:
        table = np.zeros((batch_size, pages_per_seq), np.int32)
    shape = (
        cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim
    )
    spec = (None, None, axis, None, None)
    pool_dtype = jnp.int8 if resolved_kv == "int8" else cfg.dtype
    if resolved_kv == "int8":
        scale_shape = (cfg.num_layers, num_pages, cfg.num_kv_heads)
        k_scale = ctx.shard(jnp.zeros(scale_shape, jnp.float32),
                            None, None, axis)
        v_scale = ctx.shard(jnp.zeros(scale_shape, jnp.float32),
                            None, None, axis)
    else:
        k_scale = v_scale = None
    cache = PagedKVCache(
        k_pages=ctx.shard(jnp.zeros(shape, pool_dtype), *spec),
        v_pages=ctx.shard(jnp.zeros(shape, pool_dtype), *spec),
        page_table=ctx.replicate(jnp.asarray(table)),
        kv_len=ctx.replicate(jnp.zeros((batch_size,), jnp.int32)),
        k_scale=k_scale,
        v_scale=v_scale,
    )
    return cache, pool


def kv_bytes_per_token(cache: PagedKVCache) -> float:
    """HBM bytes one cached token costs across K+V pools (+ scale
    overhead when quantized) — the quantity steady-state decode streams
    per token per step. Computed from the GLOBAL array shapes (the pool
    is head-sharded; shapes here are pre-shard)."""
    L, _p, H, page, hd = cache.k_pages.shape
    per = (
        cache.k_pages.dtype.itemsize + cache.v_pages.dtype.itemsize
    ) * L * H * hd
    if cache.quantized:
        per += (
            cache.k_scale.dtype.itemsize + cache.v_scale.dtype.itemsize
        ) * L * H / page
    return float(per)


class PoolAuditError(RuntimeError):
    """The pool/radix invariant audit found leaked, double-owned, or
    phantom pages — the serving loop's bookkeeping is corrupt."""


def audit_pool(
    pool: PagePool,
    num_pages: int | None = None,
    owners: dict[str, list[int]] | None = None,
    *,
    shared: dict[str, list[int]] | None = None,
    reserved: tuple[int, ...] = (0,),
) -> list[str]:
    """Cross-check the pool's ownership partition; returns violation
    strings (empty == clean).

    ``owners`` maps an owner name (``"slot3"``, ``"tree"``) to the
    pages it holds EXCLUSIVELY. ``shared`` maps an owner to pages it
    maps *by reference* (a slot's refcounted prefix pages) — those must
    belong to exactly one exclusive owner and never be free. The audit
    proves:

    - free list ∪ exclusive owners ∪ ``reserved`` == all pages
      (nothing leaked, nothing phantom),
    - no page has two exclusive owners, is both owned and free, or is
      a reserved page (the trash page is nobody's),
    - the free list holds no duplicates,
    - every shared mapping targets a live exclusively-owned page.

    Host-side and allocation-free: cheap enough to run after every
    ``run()`` (the continuous engine does) and from every test.
    """
    problems: list[str] = []
    total = pool.num_pages if num_pages is None else int(num_pages)
    free = list(pool.free)
    free_set = set(free)
    if len(free_set) != len(free):
        dup = sorted(p for p in free_set if free.count(p) > 1)
        problems.append(f"free list holds duplicate pages {dup}")
    claimed: dict[int, str] = {}
    for name, pages in (owners or {}).items():
        seen_local: set[int] = set()
        for p in pages:
            p = int(p)
            if p in seen_local:
                problems.append(f"{name} lists page {p} twice")
                continue
            seen_local.add(p)
            if p in claimed:
                problems.append(
                    f"page {p} owned by both {claimed[p]} and {name}"
                )
                continue
            claimed[p] = name
            if p in free_set:
                problems.append(
                    f"page {p} owned by {name} but also on the free list"
                )
            if p in reserved:
                problems.append(f"{name} owns reserved page {p}")
    all_pages = set(range(total))
    accounted = free_set | set(claimed) | set(reserved)
    leaked = all_pages - accounted
    if leaked:
        problems.append(f"leaked pages (no owner, not free): {sorted(leaked)}")
    phantom = accounted - all_pages
    if phantom:
        problems.append(f"unknown page ids: {sorted(phantom)}")
    for name, pages in (shared or {}).items():
        for p in pages:
            p = int(p)
            if p in free_set:
                problems.append(
                    f"{name} maps shared page {p} that is on the free list"
                )
            elif p not in claimed:
                problems.append(
                    f"{name} maps shared page {p} that no owner holds"
                )
    return problems


def gather_bucket(end_pos: int, page_size: int, pages_per_seq: int) -> int:
    """Page-table gather width for a chunk program whose queries/writes
    end at position ``end_pos``: enough table entries to cover it,
    rounded up to a power of two so at most log2(pages_per_seq)
    programs compile per chunk width. Shared by the prefix-cache suffix
    prefill and the speculative verify chunks — one definition, one
    program-key convention."""
    need = -(-int(end_pos) // page_size)
    if need <= 1:
        return 1
    return min(1 << max(need - 1, 0).bit_length(), pages_per_seq)


def rollback_kv(cache: PagedKVCache, slot, new_len) -> PagedKVCache:
    """Truncate ``slot``'s cached length to ``new_len`` (speculative
    decoding's KV rollback: a verify chunk wrote K+1 rows, acceptance
    kept a prefix, and every row past the accepted length becomes
    ordinary garbage-beyond-kv_len — masked by causality, overwritten
    by the next append). The page table is untouched: rejected rows
    live in pages the sequence still owns, so truncation is a length
    write, never an allocator round trip. ``slot``/``new_len`` are
    traced — one compiled program serves every rollback.

    Quantized pools roll back in lockstep for free: scales are
    per-page, not per-row, and monotone within a page's lifetime, so
    the scale that covered the rejected rows still upper-bounds every
    retained row — truncating ``kv_len`` leaves the codes/scale pair
    exact for the live prefix (the next append may grow it again;
    a later write at page offset 0 resets it)."""
    return dataclasses.replace(
        cache,
        kv_len=_set_len_jit(
            cache.kv_len,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(new_len, jnp.int32),
        ),
    )


# Donated: rollback runs once per rejected verify chunk — an eager
# .at[].set would copy the (small) kv_len array but break the cache
# threading discipline every other cache op follows.
_set_len_jit = jax.jit(
    lambda kv_len, slot, n: kv_len.at[slot].set(n), donate_argnums=(0,)
)


def move_kv_rows(
    cache: PagedKVCache,
    slot: int,
    src: list[int],
    dst: list[int],
) -> PagedKVCache:
    """Move token rows of ``slot`` from absolute positions ``src`` to
    ``dst`` (both K and V, all layers) — the tree-speculation COMMIT:
    a verify chunk wrote the draft tree's nodes at DFS storage
    positions ``kv + i``, acceptance picked one root path, and its
    nodes compact to the contiguous positions ``kv+1 .. kv+a`` a linear
    decode would have written (the subsequent ``kv_len`` rollback then
    makes every losing branch ordinary garbage-beyond-kv_len). Rows are
    gathered BEFORE any scatter, so overlapping src/dst are safe; DFS
    order guarantees a node's storage index is ≥ its depth, so every
    move is leftward (``dst[i] <= src[i]``) and distinct dst never
    collide. Moved rows are bit-identical to linearly-written rows:
    K/V content depends only on the token and its rope position, and
    tree nodes rope at their DEPTH, not their storage slot.

    Full-width pools only: a quantized pool's per-page scales make a
    row hop between pages a requantization event whose rounding depends
    on move order — the engine keeps quantized pools on width-1 chains,
    which never need moves. Positions are traced (one compiled program
    per move-count bucket); the move list is right-padded to a power of
    two with position-0 self-moves (position 0 is a prompt row, never a
    real src or dst, and duplicate identical writes are benign).
    """
    if cache.quantized:
        raise ValueError(
            "move_kv_rows is full-width-pool only; quantized pools run "
            "width-1 speculation chains (no row moves)"
        )
    if len(src) != len(dst):
        raise ValueError(f"src/dst length mismatch ({len(src)} vs {len(dst)})")
    pairs = [(int(s), int(d)) for s, d in zip(src, dst) if int(s) != int(d)]
    if not pairs:
        return cache
    m = 1 << (len(pairs) - 1).bit_length()
    pairs += [(0, 0)] * (m - len(pairs))
    src_a = jnp.asarray([p[0] for p in pairs], jnp.int32)
    dst_a = jnp.asarray([p[1] for p in pairs], jnp.int32)
    page = int(cache.k_pages.shape[3])
    k_pages, v_pages = _move_rows_jit(
        cache.k_pages, cache.v_pages, cache.page_table[slot],
        src_a, dst_a, page,
    )
    return dataclasses.replace(cache, k_pages=k_pages, v_pages=v_pages)


def _move_rows(kp, vp, table_row, src, dst, page: int):
    ps, so = jnp.take(table_row, src // page), src % page
    pd, do = jnp.take(table_row, dst // page), dst % page
    # Two advanced indices split by slices → advanced axes lead: the
    # gathered rows are [m, L, H, hd]. Gather both pools before either
    # scatter so overlapping positions read pre-move content.
    rows_k = kp[:, ps, :, so, :]
    rows_v = vp[:, ps, :, so, :]
    kp = kp.at[:, pd, :, do, :].set(rows_k)
    vp = vp.at[:, pd, :, do, :].set(rows_v)
    return kp, vp


# Donated like the other pool writers (an eager scatter would copy the
# pool to move a handful of rows); one program per move-count bucket.
_move_rows_jit = jax.jit(
    _move_rows, static_argnums=(5,), donate_argnums=(0, 1)
)


def truncate_pages(
    pool: PagePool,
    pages: list[int],
    keep_tokens: int,
    page_size: int,
    *,
    shared: int = 0,
) -> list[int]:
    """Release every page of ``pages`` lying wholly past ``keep_tokens``
    cached tokens back to ``pool``; returns the retained prefix.

    The first ``shared`` entries are prefix-cache-shared (mapped by
    refcount, owned by the radix tree) and are NEVER freed here
    regardless of ``keep_tokens`` — releasing them would double-free a
    page another sequence still attends. No-ops safely at page
    boundaries: ``keep_tokens`` landing exactly on a boundary keeps
    ``keep_tokens / page_size`` pages, and ``keep_tokens`` beyond the
    page list keeps everything.
    """
    if shared < 0 or shared > len(pages):
        raise ValueError(
            f"shared={shared} out of range for {len(pages)} pages"
        )
    keep = max(-(-max(int(keep_tokens), 0) // page_size), shared)
    if keep >= len(pages):
        return pages
    pool.release(pages[keep:])
    return pages[:keep]


def paged_cache_specs(axis: str = "tp", quantized: bool = False):
    """shard_map PartitionSpecs matching :func:`init_paged_cache`.
    ``quantized`` adds the per-page-per-head scale specs (head-sharded
    like the pool); unset matches the scale-less pytree exactly."""
    from jax.sharding import PartitionSpec as P

    return PagedKVCache(
        k_pages=P(None, None, axis, None, None),
        v_pages=P(None, None, axis, None, None),
        page_table=P(),
        kv_len=P(),
        k_scale=P(None, None, axis) if quantized else None,
        v_scale=P(None, None, axis) if quantized else None,
    )


def write_prefill(
    cache: PagedKVCache,
    b_idx: int,
    k_dense: jax.Array,  # [L, 1, Hkv_loc, S, hd] — one filled sequence
    v_dense: jax.Array,
    true_len: int,
) -> PagedKVCache:
    """Scatter a dense-prefilled sequence into its pages (host-level;
    pages are contiguous S tiles so each page is one slice copy).
    ``true_len`` is a static prompt length; ceil(true_len/page) pages
    are written (the dense source must cover that many positions)."""
    page = cache.k_pages.shape[3]
    npages = -(-int(true_len) // page)
    if k_dense.shape[3] < npages * page:
        raise ValueError(
            f"dense prefill holds {k_dense.shape[3]} positions; "
            f"{npages * page} needed for true_len={true_len}"
        )

    row = cache.page_table[b_idx]
    kv_len = cache.kv_len.at[b_idx].set(jnp.asarray(true_len, jnp.int32))
    if cache.quantized:
        from triton_distributed_tpu.runtime.profiling import trace_span

        tl = jnp.asarray(true_len, jnp.int32)
        with trace_span("kv:quant", op="write_prefill", pages=2 * npages):
            k_pages, k_scale = _scatter_q_jit(
                cache.k_pages, cache.k_scale, k_dense, row, tl, npages, page
            )
            v_pages, v_scale = _scatter_q_jit(
                cache.v_pages, cache.v_scale, v_dense, row, tl, npages, page
            )
        return PagedKVCache(
            k_pages=k_pages, v_pages=v_pages, page_table=cache.page_table,
            kv_len=kv_len, k_scale=k_scale, v_scale=v_scale,
        )
    return PagedKVCache(
        k_pages=_scatter_jit(cache.k_pages, k_dense, row, npages, page),
        v_pages=_scatter_jit(cache.v_pages, v_dense, row, npages, page),
        page_table=cache.page_table,
        kv_len=kv_len,
    )


def _scatter(pages, dense, table_row, npages: int, page: int):
    for j in range(npages):
        pid = table_row[j]
        chunk = jax.lax.dynamic_slice_in_dim(
            dense, j * page, page, axis=3
        )[:, 0][:, None]
        pages = jax.lax.dynamic_update_slice(
            pages, chunk.astype(pages.dtype), (0, pid, 0, 0, 0)
        )
    return pages


def _scatter_q(pages, scales, dense, table_row, true_len, npages: int,
               page: int):
    """Quantized :func:`_scatter`: every written page is a FRESH full
    write, so its scale is set absolutely from the page's amax (never
    grown from a previous tenant's stale scale). Dense rows at
    positions ≥ ``true_len`` are ZEROED before quantization: the dense
    scratch is reused across prefills, so the last partial page would
    otherwise fold a PREVIOUS request's stale KV into this page's amax
    — inflating the scale and making the codes depend on admission
    order."""
    for j in range(npages):
        pid = table_row[j]
        chunk = jax.lax.dynamic_slice_in_dim(
            dense, j * page, page, axis=3
        )[:, 0]  # [L, H, page, hd]
        pos = j * page + jnp.arange(page, dtype=jnp.int32)
        chunk = jnp.where(
            (pos < true_len)[None, None, :, None],
            chunk.astype(jnp.float32), 0.0,
        )
        sc = page_scales(chunk)  # [L, H]
        pages = jax.lax.dynamic_update_slice(
            pages, quantize_page(chunk, sc)[:, None], (0, pid, 0, 0, 0)
        )
        scales = jax.lax.dynamic_update_slice(
            scales, sc[:, None], (0, pid, 0)
        )
    return pages, scales


# Donated + jitted: the page-by-page scatter updates the pool in place;
# eager dynamic_update_slices would copy the whole (GB-scale) pool once
# per page.
_scatter_jit = jax.jit(_scatter, static_argnums=(3, 4), donate_argnums=(0,))
_scatter_q_jit = jax.jit(
    _scatter_q, static_argnums=(5, 6), donate_argnums=(0, 1)
)


def copy_page(cache: PagedKVCache, src: int, dst: int) -> PagedKVCache:
    """Copy one pool page (both K and V, all layers) — the prefix
    cache's copy-on-write: a partially matched shared page is cloned
    into the new sequence's private page before its suffix writes into
    it. ``src``/``dst`` are traced, so one compiled program serves every
    COW."""
    s = jnp.asarray(src, jnp.int32)
    d = jnp.asarray(dst, jnp.int32)
    return PagedKVCache(
        k_pages=_copy_page_jit(cache.k_pages, s, d),
        v_pages=_copy_page_jit(cache.v_pages, s, d),
        page_table=cache.page_table,
        kv_len=cache.kv_len,
        # COW on a quantized pool clones the scale WITH the codes — the
        # pair is the page's content; cloning one without the other
        # would dequantize the copy under the wrong amax.
        k_scale=(
            None if cache.k_scale is None
            else _copy_page_jit(cache.k_scale, s, d)
        ),
        v_scale=(
            None if cache.v_scale is None
            else _copy_page_jit(cache.v_scale, s, d)
        ),
    )


# Donated for the same reason as _scatter_jit: an eager update would
# copy the whole pool to move one page. Shape-polymorphic over the
# trailing dims, so the same program body serves pools AND their
# [L, P, H] scale arrays (jit re-specializes per shape).
_copy_page_jit = jax.jit(
    lambda pages, s, d: jax.lax.dynamic_update_slice_in_dim(
        pages, jax.lax.dynamic_slice_in_dim(pages, s, 1, axis=1), d, axis=1
    ),
    donate_argnums=(0,),
)


def append(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, Hkv_loc, hd] — one token per sequence
    v_new: jax.Array,
) -> PagedKVCache:
    """Append one token per sequence at ``kv_len`` (jit-safe); the
    NS=1 case of :func:`append_n` (one scatter per pool)."""
    return append_n(cache, k_new[:, :, :, None, :], v_new[:, :, :, None, :])


def append_n(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, Hkv_loc, NS, hd] — NS tokens per sequence
    v_new: jax.Array,
    n_valid: jax.Array | None = None,  # [B] i32 — rows kept per sequence
) -> PagedKVCache:
    """Append ``NS`` tokens per sequence at ``kv_len`` in ONE scatter.

    The multi-step megakernel emits NS rows per launch; appending them
    row-by-row would pay the per-op dispatch tax NS times (the very
    cost multi-step exists to amortize). One advanced-index scatter
    per pool handles all (b, step) rows — page-boundary crossings fall
    out of the per-row (page_id, offset) computation.

    ``n_valid[b]`` (None → NS) routes row's ``>= n_valid[b]`` to the
    trash page instead of the sequence's own pages: a serving launch
    whose row finishes mid-launch emits guaranteed-overshoot rows
    (``gen_len`` bound, known at launch time), and on an int8 pool
    those rows would otherwise GROW the final page's scale before the
    page retires into the radix tree — quantization noise paid by
    every later request reusing that prefix. Trash-routed rows are
    discarded either way; this keeps retired pages' scales covering
    real rows only. (eos can still finish earlier than ``gen_len``;
    those rare rows land in owned pages as ordinary
    garbage-beyond-kv_len, same as the single-step path.)

    Caller contract: ``kv_len[b] + NS`` stays within the page table's
    capacity for every row.
    """
    page = cache.k_pages.shape[3]
    L, B, H, NS, hd = k_new.shape
    pos = cache.kv_len[:, None] + jnp.arange(NS, dtype=jnp.int32)[None]
    pids = jnp.take_along_axis(cache.page_table, pos // page, axis=1)
    if n_valid is not None:
        step = jnp.arange(NS, dtype=jnp.int32)[None]
        pids = jnp.where(step < n_valid[:, None], pids, 0)
    flat_p = pids.reshape(-1)        # [B*NS]
    flat_o = (pos % page).reshape(-1)

    def write(pages, new):
        # Two advanced indices split by slices → advanced axes move to
        # the front: the indexed view is [B*NS, L, H, hd]. NO
        # unique_indices: inactive/finished rows all route to the trash
        # page (identical indices), where duplicate writes are fine
        # under scatter's last-write-wins but UB if claimed unique.
        upd = new.transpose(1, 3, 0, 2, 4).reshape(B * NS, L, H, hd)
        return pages.at[:, flat_p, :, flat_o, :].set(upd.astype(pages.dtype))

    if cache.quantized:
        # Quantized append: ONE scale-protocol implementation
        # (:func:`quantized_row_scatter` — reset at offset 0, grow +
        # requant otherwise), vmapped over the layer axis and applied
        # STEP BY STEP (lax.fori over NS, one C=B scatter per step)
        # rather than as one B·NS-row batch: a batch scatter would grow
        # each touched page's scale ONCE to cover all NS rows, while
        # the single-step serving path grows it row-by-row with a
        # requant at each growth — a different rounding-event order
        # that leaves different codes behind. Serving shares retired
        # pages across requests through the radix tree, so the fused
        # NS-launch must leave the pool BIT-IDENTICAL to NS unfused
        # steps over the same tokens; sequencing the scatters inside
        # the one program keeps that while still dispatching once.
        pids_q = pids  # [B, NS] (trash-routed where n_valid caps)
        offs_q = pos % page

        def step_scatter(s, carry):
            kp, ks, vp, vs = carry
            rows_k = jax.lax.dynamic_index_in_dim(k_new, s, axis=3)[
                :, :, :, 0, :]  # [L, B, H, hd]
            rows_v = jax.lax.dynamic_index_in_dim(v_new, s, axis=3)[
                :, :, :, 0, :]
            p_s = jax.lax.dynamic_index_in_dim(pids_q, s, axis=1)[:, 0]
            o_s = jax.lax.dynamic_index_in_dim(offs_q, s, axis=1)[:, 0]
            kp, ks = _row_scatter_layers(kp, ks, rows_k, p_s, o_s)
            vp, vs = _row_scatter_layers(vp, vs, rows_v, p_s, o_s)
            return kp, ks, vp, vs

        k_pages, k_scale, v_pages, v_scale = jax.lax.fori_loop(
            0, NS, step_scatter,
            (cache.k_pages, cache.k_scale, cache.v_pages, cache.v_scale),
        )
        return PagedKVCache(
            k_pages=k_pages, v_pages=v_pages,
            page_table=cache.page_table, kv_len=cache.kv_len + NS,
            k_scale=k_scale, v_scale=v_scale,
        )
    return PagedKVCache(
        k_pages=write(cache.k_pages, k_new),
        v_pages=write(cache.v_pages, v_new),
        page_table=cache.page_table,
        kv_len=cache.kv_len + NS,
    )


def gather_pages(cache: PagedKVCache, page_ids: list[int]):
    """Gather the listed pool pages to HOST arrays — the slot-migration
    export path (``models/slot_state.py``): one ``take`` per pool on the
    page axis, fetched as-is (int8 codes stay codes, scales ride along),
    so the snapshot is byte-exact with respect to the pool it came from.
    Returns ``(k_pages, v_pages, k_scale, v_scale)`` numpy arrays —
    ``[L, n, Hkv, page, hd]`` pools and ``[L, n, Hkv]`` scales (scales
    are None on an unquantized pool)."""
    ids = jnp.asarray([int(p) for p in page_ids], jnp.int32)
    k = np.asarray(_gather_pages_jit(cache.k_pages, ids))
    v = np.asarray(_gather_pages_jit(cache.v_pages, ids))
    if cache.quantized:
        ks = np.asarray(_gather_pages_jit(cache.k_scale, ids))
        vs = np.asarray(_gather_pages_jit(cache.v_scale, ids))
    else:
        ks = vs = None
    return k, v, ks, vs


# NOT donated (unlike every writer above): the gather is a pure read —
# the pool stays live for the decode loop that owns it. jit
# re-specializes per (pool shape, id count); migration exports reuse a
# handful of shapes per engine.
_gather_pages_jit = jax.jit(lambda pages, ids: jnp.take(pages, ids, axis=1))


def write_page(cache: PagedKVCache, pid: int, k_page, v_page,
               k_scale=None, v_scale=None) -> PagedKVCache:
    """Write one page's full content (both pools, all layers) into pool
    page ``pid`` — the slot-migration import path: the payload arrays
    come from :func:`gather_pages` on another engine and are written
    VERBATIM (int8 codes + their scale as a pair), so a migrated slot's
    dequantized KV is bit-identical to the source's. ``k_page``/
    ``v_page`` are ``[L, Hkv, page, hd]``; scales ``[L, Hkv]`` (required
    iff the pool is quantized)."""
    if cache.quantized != (k_scale is not None):
        raise ValueError(
            "page payload and pool disagree on quantization "
            f"(pool quantized={cache.quantized}, scales "
            f"{'present' if k_scale is not None else 'absent'})"
        )
    p = jnp.asarray(pid, jnp.int32)
    kp = _write_page_jit(cache.k_pages, p,
                         jnp.asarray(k_page, cache.k_pages.dtype))
    vp = _write_page_jit(cache.v_pages, p,
                         jnp.asarray(v_page, cache.v_pages.dtype))
    ks, vs = cache.k_scale, cache.v_scale
    if cache.quantized:
        ks = _write_page_jit(ks, p, jnp.asarray(k_scale, jnp.float32))
        vs = _write_page_jit(vs, p, jnp.asarray(v_scale, jnp.float32))
    return dataclasses.replace(
        cache, k_pages=kp, v_pages=vp, k_scale=ks, v_scale=vs
    )


# Donated like _scatter_jit (an eager update would copy the pool to
# move one page); shape-polymorphic over trailing dims so the same body
# serves pools and their [L, P, H] scale arrays.
_write_page_jit = jax.jit(
    lambda pages, pid, data: jax.lax.dynamic_update_slice_in_dim(
        pages, data[:, None], pid, axis=1
    ),
    donate_argnums=(0,),
)


def as_dense(cache: PagedKVCache, layer=None):
    """Materialize contiguous ``[L?, B, Hkv_loc, S_max, hd]`` views by
    gathering pages through the table (decode feeds this to
    ``flash_decode``; the page gather is a take on the page axis).
    Quantized pools dequantize the gathered view (f32) — this is the
    tests/fallback path, never the serving hot path, which reads int8
    codes straight through the kernels."""
    from triton_distributed_tpu.ops.attention.flash_decode import (
        pages_to_dense,
        scales_to_dense,
    )

    kp = cache.k_pages if layer is None else cache.k_pages[layer]
    vp = cache.v_pages if layer is None else cache.v_pages[layer]
    k = pages_to_dense(kp, cache.page_table)
    v = pages_to_dense(vp, cache.page_table)
    if cache.quantized:
        page = cache.k_pages.shape[3]
        ks = cache.k_scale if layer is None else cache.k_scale[layer]
        vs = cache.v_scale if layer is None else cache.v_scale[layer]
        k = k.astype(jnp.float32) * scales_to_dense(
            ks, cache.page_table, page
        )[..., None]
        v = v.astype(jnp.float32) * scales_to_dense(
            vs, cache.page_table, page
        )[..., None]
    return k, v
