"""Portable in-flight request state: export/import one engine slot.

PR 9 made replica death survivable, but recovery was replay-from-prompt
— a drained or SIGKILL'd replica's in-flight KV was simply lost. This
module makes a live request's full generation state a first-class,
portable object (docs/scale-out.md "Slot migration & handoff"): a
:class:`SlotSnapshot` captures everything one
:class:`~triton_distributed_tpu.models.continuous.ContinuousEngine`
slot needs to continue on a *different* engine bit-exactly —

- the gathered KV pages (bf16, or int8 codes **plus** their per-page
  ``k_scale``/``v_scale`` — codes and scale travel as a pair, so the
  dequantized values are byte-identical on the target),
- the page-table geometry (``kv_len``, page size, kv dtype),
- the prompt and every generated token so far,
- the per-request sampling knobs AND the per-request PRNG key + draw
  counter (``Request.key``/``key_step`` — seeded-sampled continuations
  replay the exact draws the un-migrated run would have made),
- the speculative accept ledger (``SpecState`` counters + adaptive K;
  the n-gram drafter rebuilds from the token history, which the
  snapshot already carries),
- the deadline budget and ``trace_id``.

**Prefix delta** (the DistServe/Splitwise-style disaggregation seed):
``export_slot(..., target_digest=...)`` scores the snapshot's cached
token chain against the *target's* radix digest
(``prefix_cache.digest_match_len``) and omits the payload of fully
covered pages — only the non-shared page suffix ships. Import then
pins exactly those pages out of the target's tree (refcounted, COW
discipline untouched); if the target evicted them in the meantime,
import raises :class:`SnapshotStaleError` and the engine falls back to
a full replay from the prompt (correct, just slower).

Everything serializes to line-JSON (:meth:`SlotSnapshot.to_wire`)
because snapshots ride the existing wire protocol: the server's
``export_slots`` verb, the ``snapshots`` key of a ``requests``
payload, and the supervisor's periodic snapshot pulls all speak it.

Fault seams (``runtime/faults.py``): ``migrate.export`` fires before
any state is read, ``migrate.import`` before any page is allocated —
a kill at either end leaves both engines' pool/radix audits clean
(export is a pure read; import tears down via the engine's crash-safe
``_admit_failure``/fallback path).
"""

from __future__ import annotations

import base64
import dataclasses
import time

import jax
import numpy as np

from triton_distributed_tpu.models.paged_kv_cache import (
    gather_pages,
    write_page,
)
from triton_distributed_tpu.runtime.faults import fault_point

SNAPSHOT_VERSION = 1


class SnapshotError(RuntimeError):
    """A snapshot cannot be imported into this engine (geometry or
    dtype mismatch, malformed payload). The admission path falls back
    to a full replay from the prompt."""


class SnapshotStaleError(SnapshotError):
    """A prefix-delta snapshot's omitted pages are no longer covered by
    the target's radix tree (evicted between export and import) — the
    payload that was never shipped cannot be reconstructed. Fallback:
    full replay (or re-export without ``target_digest``)."""


@dataclasses.dataclass
class SlotSnapshot:
    """One slot's portable generation state (see module docstring)."""

    prompt: np.ndarray            # [S] int32
    out: list[int]                # tokens generated so far (out[-1] is
    gen_len: int                  # the pending, not-yet-appended token)
    kv_len: int                   # valid KV rows: kv_len == S+len(out)-1
    page_size: int
    kv_dtype: str | None
    # Page payloads for pages [from_prefix_pages, ceil(kv_len/page)):
    # [L, n_ship, Hkv, page, hd] pools, [L, n_ship, Hkv] scales.
    k_pages: np.ndarray | None = None
    v_pages: np.ndarray | None = None
    k_scale: np.ndarray | None = None
    v_scale: np.ndarray | None = None
    # Leading fully-cached pages whose payload was omitted because the
    # target's digest already covered them (prefix delta).
    from_prefix_pages: int = 0
    # Per-request sampling state.
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    key_data: np.ndarray | None = None  # jax.random.key_data raw words
    key_step: int = 0
    # Speculative accept ledger (drafter rebuilds from prompt+out).
    spec: dict | None = None
    deadline_s: float | None = None
    trace_id: str | None = None
    exported_at: float = 0.0      # wall clock (time.time) at export
    version: int = SNAPSHOT_VERSION

    @property
    def chain(self) -> list[int]:
        """The token chain whose KV the snapshot covers: positions
        ``[0, kv_len)`` — the prompt plus every generated token already
        fed back (the pending ``out[-1]`` has no KV row yet)."""
        toks = [int(t) for t in self.prompt]
        toks += [int(t) for t in self.out[: self.kv_len - len(toks)]]
        return toks

    @property
    def valid_pages(self) -> int:
        return -(-int(self.kv_len) // int(self.page_size))

    def payload_bytes(self) -> int:
        """Bytes of page payload this snapshot ships (the quantity the
        ``tdt_migration_bytes`` histogram observes; prefix-delta
        exports ship strictly less)."""
        total = 0
        for arr in (self.k_pages, self.v_pages, self.k_scale,
                    self.v_scale):
            if arr is not None:
                total += arr.nbytes
        return total

    # -- wire codec -------------------------------------------------------

    def to_wire(self) -> dict:
        """Line-JSON-safe dict (arrays ride base64 with dtype+shape)."""
        d = {
            "version": self.version,
            "prompt": [int(t) for t in self.prompt],
            "out": [int(t) for t in self.out],
            "gen_len": int(self.gen_len),
            "kv_len": int(self.kv_len),
            "page_size": int(self.page_size),
            "kv_dtype": self.kv_dtype,
            "from_prefix_pages": int(self.from_prefix_pages),
            "temperature": self.temperature,
            "top_p": self.top_p,
            "top_k": self.top_k,
            "key_step": int(self.key_step),
            "spec": self.spec,
            "deadline_s": self.deadline_s,
            "trace_id": self.trace_id,
            "exported_at": float(self.exported_at),
        }
        for name in ("k_pages", "v_pages", "k_scale", "v_scale",
                     "key_data"):
            d[name] = _arr_to_wire(getattr(self, name))
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "SlotSnapshot":
        try:
            return cls(
                prompt=np.asarray(d["prompt"], np.int32),
                out=[int(t) for t in d["out"]],
                gen_len=int(d["gen_len"]),
                kv_len=int(d["kv_len"]),
                page_size=int(d["page_size"]),
                kv_dtype=d.get("kv_dtype"),
                k_pages=_arr_from_wire(d.get("k_pages")),
                v_pages=_arr_from_wire(d.get("v_pages")),
                k_scale=_arr_from_wire(d.get("k_scale")),
                v_scale=_arr_from_wire(d.get("v_scale")),
                from_prefix_pages=int(d.get("from_prefix_pages", 0)),
                temperature=d.get("temperature"),
                top_p=d.get("top_p"),
                top_k=d.get("top_k"),
                key_data=_arr_from_wire(d.get("key_data")),
                key_step=int(d.get("key_step", 0)),
                spec=d.get("spec"),
                deadline_s=d.get("deadline_s"),
                trace_id=d.get("trace_id"),
                exported_at=float(d.get("exported_at", 0.0)),
                version=int(d.get("version", SNAPSHOT_VERSION)),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise SnapshotError(
                f"malformed snapshot: {type(e).__name__}: {e}"
            ) from e


def _arr_to_wire(arr: np.ndarray | None) -> dict | None:
    if arr is None:
        return None
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "b64": base64.b64encode(np.ascontiguousarray(arr).tobytes())
        .decode("ascii"),
    }


def _arr_from_wire(d: dict | None) -> np.ndarray | None:
    if d is None:
        return None
    try:
        # bfloat16 resolves through ml_dtypes, which numpy picks up via
        # jax's registration of the extended dtypes.
        import ml_dtypes  # noqa: F401

        dtype = np.dtype(d["dtype"])
        raw = base64.b64decode(d["b64"])
        return np.frombuffer(raw, dtype=dtype).reshape(d["shape"]).copy()
    except (KeyError, TypeError, ValueError) as e:
        raise SnapshotError(
            f"malformed snapshot array: {type(e).__name__}: {e}"
        ) from e


# -- export ---------------------------------------------------------------


def _export_plan(engine, slot: int, target_digest):
    """The read-only half of an export that decides WHAT ships:
    ``(req, kv_len, skip, ship_ids)`` — the live request, its valid KV
    rows, the prefix-delta page count, and the pool page ids whose
    payload must travel."""
    req = engine._slots[slot]
    if req is None:
        raise SnapshotError(f"slot {slot} has no active request")
    kv_len = int(engine._kv_len[slot])
    page = int(engine.page_size)
    valid = -(-kv_len // page)
    skip = 0
    if target_digest:
        from triton_distributed_tpu.models.prefix_cache import (
            digest_match_len,
        )

        chain = [int(t) for t in req.prompt]
        chain += [int(t) for t in req.out[: kv_len - len(chain)]]
        matched = digest_match_len(target_digest, chain)
        # Only FULLY covered, fully cached pages may be omitted — the
        # import pins full tree pages, never a partial (COW) match.
        skip = min(matched // page, valid)
    ship_ids = [int(p) for p in req.pages[skip:valid]]
    return req, kv_len, skip, ship_ids


def _build_snapshot(engine, req, kv_len: int, skip: int,
                    k, v, ks, vs) -> SlotSnapshot:
    """Assemble one :class:`SlotSnapshot` from a plan plus its gathered
    page payloads (``k``/``v``/``ks``/``vs`` may be views into a larger
    batched gather — ``to_wire`` re-contiguifies)."""
    spec = None
    if req.spec is not None:
        spec = {
            "k_max": req.spec.k_max, "k_min": req.spec.k_min,
            "k": req.spec.k, "proposed": req.spec.proposed,
            "accepted": req.spec.accepted,
            # Tree-speculation width ledger (absent on pre-tree
            # snapshots; import defaults cover it).
            "width": req.spec.width, "w_max": req.spec.w_max,
        }
    key_data = None
    if req.key is not None:
        key_data = np.asarray(jax.random.key_data(req.key))
    return SlotSnapshot(
        prompt=np.asarray(req.prompt, np.int32),
        out=[int(t) for t in req.out],
        gen_len=int(req.gen_len),
        kv_len=kv_len,
        page_size=int(engine.page_size),
        kv_dtype=engine.kv_dtype,
        k_pages=k, v_pages=v, k_scale=ks, v_scale=vs,
        from_prefix_pages=skip,
        temperature=req.temperature, top_p=req.top_p, top_k=req.top_k,
        key_data=key_data, key_step=int(req.key_step),
        spec=spec,
        deadline_s=req.deadline_s,
        trace_id=req.trace_id,
        exported_at=time.time(),
    )


def _export_sharded(engine, slot: int, ls) -> SlotSnapshot:
    """Gather-stitch export of a SHARDED long-context slot
    (docs/scale-out.md "Sharded-slot migration"): the resident pages
    come off the device with the usual ``gather_pages``, the cold pages
    fault back from the KV tier, and the two stitch — in absolute
    token order, cold prefix first — into one PLAIN snapshot. The
    importer needs no sharding support: the snapshot is
    indistinguishable from one exported off a big-pool engine, so any
    replica with the capacity admits it as an ordinary slot.

    The prefix delta is deliberately skipped (full payload ships): a
    sharded slot's leading pages live in the tier, not the tree, so
    digest cover cannot be pinned on import."""
    from triton_distributed_tpu.models import kv_tier

    req = engine._slots[slot]
    if req is None:
        raise SnapshotError(f"slot {slot} has no active request")
    kv_len = int(engine._kv_len[slot])
    page = int(engine.page_size)
    valid = -(-kv_len // page)
    cold = int(ls.cold)
    n_res = valid - cold
    if not 0 <= n_res <= len(req.pages):
        raise SnapshotError(
            f"sharded slot {slot}: {valid} valid pages, {cold} cold, "
            f"{len(req.pages)} resident — geometry is inconsistent"
        )
    ship_ids = [int(p) for p in req.pages[:n_res]]
    if ship_ids:
        k_res, v_res, ks_res, vs_res = gather_pages(engine.cache,
                                                    ship_ids)
    else:
        k_res = v_res = ks_res = vs_res = None
    k_cold, v_cold, ks_cold, vs_cold = [], [], [], []
    for i in range(cold):
        key = f"{ls.uid}:{i}"
        payload = engine.tier.get(kv_tier.LONGCTX_KIND, key)
        if payload is None:
            raise SnapshotError(
                f"sharded slot {slot}: cold page {key} missing from "
                "the KV tier"
            )
        _chain, _ps, _dt, k1, v1, ks1, vs1 = (
            kv_tier.decode_prefix_payload(payload)
        )
        k_cold.append(k1)
        v_cold.append(v1)
        ks_cold.append(ks1)
        vs_cold.append(vs1)
    parts_k = ([np.stack(k_cold, axis=1)] if cold else []) \
        + ([k_res] if k_res is not None else [])
    parts_v = ([np.stack(v_cold, axis=1)] if cold else []) \
        + ([v_res] if v_res is not None else [])
    k = np.concatenate(parts_k, axis=1) if parts_k else None
    v = np.concatenate(parts_v, axis=1) if parts_v else None
    ks = vs = None
    if engine.cache.quantized:
        parts_ks = ([np.stack(ks_cold, axis=1)] if cold else []) \
            + ([ks_res] if ks_res is not None else [])
        parts_vs = ([np.stack(vs_cold, axis=1)] if cold else []) \
            + ([vs_res] if vs_res is not None else [])
        ks = np.concatenate(parts_ks, axis=1) if parts_ks else None
        vs = np.concatenate(parts_vs, axis=1) if parts_vs else None
    return _build_snapshot(engine, req, kv_len, 0, k, v, ks, vs)


def export_slot(engine, slot: int, *, target_digest=None) -> SlotSnapshot:
    """Snapshot ``slot``'s live request from ``engine`` (pure read — the
    slot keeps decoding; teardown is the caller's decision). Call at a
    scheduling-round boundary on the engine's own thread: that is where
    host tables, ``out``, and the device cache agree.

    ``target_digest`` (a :meth:`PrefixCache.prefix_digest` forest from
    the intended target) turns on the prefix delta: payload for leading
    pages fully covered by the digest is omitted and
    ``from_prefix_pages`` records how many the import must instead pin
    from its own tree."""
    fault_point("migrate.export", slot=slot)
    ls = getattr(engine, "_longctx", {}).get(slot)
    if ls is not None:
        return _export_sharded(engine, slot, ls)
    req, kv_len, skip, ship_ids = _export_plan(engine, slot,
                                               target_digest)
    if ship_ids:
        k, v, ks, vs = gather_pages(engine.cache, ship_ids)
    else:
        k = v = ks = vs = None
    return _build_snapshot(engine, req, kv_len, skip, k, v, ks, vs)


def export_slots_batch(engine, slots, *,
                       target_digest=None) -> dict:
    """Snapshot several active slots in ONE device gather: slot →
    :class:`SlotSnapshot`, bit-identical to per-slot
    :func:`export_slot` calls (the gather is per-page, so splitting a
    concatenated gather per slot reproduces each slot's pages exactly).

    What changes is the cost shape: a drain sweep or a prefill burst
    exporting ``n`` slots pays one ``jnp.take`` launch + one
    device→host fetch over the concatenated ship lists instead of
    ``n`` serial round trips (docs/scale-out.md "Disaggregated pools &
    autoscaling"; ``perf/pools_bench.py`` measures the delta). A slot
    with no live request raises :class:`SnapshotError`, same as the
    serial path — filter first when sweeping."""
    plans = []
    out: dict = {}
    longctx = getattr(engine, "_longctx", {})
    for slot in slots:
        fault_point("migrate.export", slot=slot)
        ls = longctx.get(slot)
        if ls is not None:
            # Sharded slots stitch resident + tier pages per slot — no
            # batched gather to amortize; route them individually.
            out[slot] = _export_sharded(engine, slot, ls)
            continue
        plans.append((slot, *_export_plan(engine, slot, target_digest)))
    all_ids: list[int] = []
    for _slot, _req, _kv_len, _skip, ship_ids in plans:
        all_ids.extend(ship_ids)
    if all_ids:
        k_all, v_all, ks_all, vs_all = gather_pages(engine.cache,
                                                    all_ids)
    else:
        k_all = v_all = ks_all = vs_all = None
    off = 0
    for slot, req, kv_len, skip, ship_ids in plans:
        n = len(ship_ids)
        if n:
            sl = slice(off, off + n)
            k, v = k_all[:, sl], v_all[:, sl]
            ks = None if ks_all is None else ks_all[:, sl]
            vs = None if vs_all is None else vs_all[:, sl]
        else:
            k = v = ks = vs = None
        off += n
        out[slot] = _build_snapshot(engine, req, kv_len, skip,
                                    k, v, ks, vs)
    return out


def prefix_delta(snap: SlotSnapshot, target_digest) -> SlotSnapshot:
    """Shrink ``snap`` against a target's radix digest: payload for
    leading pages the digest fully covers is dropped and
    ``from_prefix_pages`` grows to match — the import pins those pages
    from the target's own tree. Returns ``snap`` unchanged when the
    digest covers nothing new. This is the transfer-time half of the
    prefix delta (``export_slot(target_digest=...)`` is the
    export-time half): a snapshot exported in full can still ship
    thin once the target is known."""
    from triton_distributed_tpu.models.prefix_cache import (
        digest_match_len,
    )

    matched = digest_match_len(target_digest, snap.chain)
    skip = min(matched // snap.page_size, snap.valid_pages)
    if skip <= snap.from_prefix_pages:
        return snap
    drop = skip - snap.from_prefix_pages
    return dataclasses.replace(
        snap,
        from_prefix_pages=skip,
        k_pages=None if snap.k_pages is None else snap.k_pages[:, drop:],
        v_pages=None if snap.v_pages is None else snap.v_pages[:, drop:],
        k_scale=None if snap.k_scale is None else snap.k_scale[:, drop:],
        v_scale=None if snap.v_scale is None else snap.v_scale[:, drop:],
    )


# -- import ---------------------------------------------------------------


def import_slot(engine, req, snap: SlotSnapshot, slot: int) -> None:
    """Restore ``snap`` into ``slot`` of ``engine``, resuming ``req``
    mid-generation: pin prefix-delta pages from the target's tree,
    allocate the rest (gen-headroom included), write the shipped page
    payloads verbatim, and register the slot so the next scheduling
    round continues decoding exactly where the source stopped.

    Raises :class:`SnapshotError` (geometry/dtype mismatch, malformed
    payload) or :class:`SnapshotStaleError` (prefix delta no longer
    covered); the engine's admission path catches these and falls back
    to a full replay from the prompt. On ANY failure after allocation,
    ``req.slot``/``req.pages``/``req.shared_nodes`` are already set, so
    the standard crash-safe teardown releases everything."""
    fault_point("migrate.import", slot=slot)
    if int(snap.page_size) != int(engine.page_size):
        raise SnapshotError(
            f"page_size mismatch: snapshot {snap.page_size}, "
            f"engine {engine.page_size}"
        )
    if snap.kv_dtype != engine.kv_dtype:
        raise SnapshotError(
            f"kv_dtype mismatch: snapshot {snap.kv_dtype!r}, "
            f"engine {engine.kv_dtype!r}"
        )
    s = len(snap.prompt)
    if not snap.out or snap.kv_len != s + len(snap.out) - 1:
        raise SnapshotError(
            f"inconsistent snapshot: kv_len={snap.kv_len}, "
            f"prompt={s}, out={len(snap.out)}"
        )
    if len(snap.out) >= int(snap.gen_len):
        raise SnapshotError("snapshot is already complete")
    page = int(engine.page_size)
    valid = snap.valid_pages
    skip = int(snap.from_prefix_pages)
    n_ship = valid - skip
    for arr in (snap.k_pages, snap.v_pages):
        got = 0 if arr is None else int(arr.shape[1])
        if got != n_ship:
            raise SnapshotError(
                f"snapshot ships {got} pages; geometry needs {n_ship}"
            )
    total = engine._needed_pages(s, int(snap.gen_len))

    # Prefix-delta pages come from the TARGET's own tree, pinned with
    # the exact discipline _admit_prefix uses (full pages only).
    shared_nodes: list = []
    m = None
    if skip:
        if engine.prefix is None:
            raise SnapshotStaleError(
                "snapshot omits prefix pages but the engine has no "
                "prefix cache"
            )
        # match() caps at len(tokens)-1 (admission must keep one
        # suffix token to prefill); an import restores the WHOLE chain,
        # so a sentinel lifts the cap — it can never match a cached
        # chunk (token ids are non-negative).
        m = engine.prefix.match(snap.chain + [-1])
        if len(m.nodes) < skip:
            engine.prefix.release_match(m)
            raise SnapshotStaleError(
                f"target tree covers {len(m.nodes)} pages; snapshot "
                f"omitted {skip}"
            )
        shared_nodes = m.nodes[:skip]
        # Pins beyond what the delta needs (and any COW pin) go back —
        # the shipped payload is the source of truth for those pages.
        for node in m.nodes[skip:]:
            engine.prefix.release_node(node)
        if m.cow_node is not None:
            engine.prefix.release_node(m.cow_node)
            m.cow_node = None
        m.nodes = []
    try:
        n_new = total - skip
        if engine.prefix is not None:
            new_pages = engine.prefix.allocate(n_new)
            if new_pages is None:
                raise SnapshotError(
                    f"pool cannot cover {n_new} pages for import"
                )
        else:
            new_pages = engine.pool.allocate(n_new)
    except Exception:
        for node in shared_nodes:
            engine.prefix.release_node(node)
        raise
    # From here on the request owns its state: any failure unwinds
    # through the engine's standard slot teardown (pages + pins).
    req.slot = slot
    req.pages = [n.page for n in shared_nodes] + new_pages
    req.shared_nodes = shared_nodes
    for j in range(n_ship):
        engine.cache = write_page(
            engine.cache, req.pages[skip + j],
            snap.k_pages[:, j], snap.v_pages[:, j],
            None if snap.k_scale is None else snap.k_scale[:, j],
            None if snap.v_scale is None else snap.v_scale[:, j],
        )
    engine._table[slot] = 0
    engine._table[slot, : len(req.pages)] = req.pages
    engine._kv_len[slot] = int(snap.kv_len)
    req.out = [int(t) for t in snap.out]
    engine._tok[slot] = req.out[-1]
    if snap.key_data is not None:
        req.key = jax.random.wrap_key_data(
            jax.numpy.asarray(snap.key_data)
        )
    req.key_step = int(snap.key_step)
    if snap.trace_id and req.trace_id is None:
        req.trace_id = snap.trace_id
    if engine.speculative:
        from triton_distributed_tpu.models.speculative import SpecState

        if hasattr(engine, "_new_spec_state"):
            st = engine._new_spec_state()
        else:
            st = SpecState(engine.speculative)
        sp = snap.spec or {}
        st.k = int(sp.get("k", st.k))
        st.proposed = int(sp.get("proposed", 0))
        st.accepted = int(sp.get("accepted", 0))
        # Width rides the snapshot (pre-tree snapshots omit it);
        # clamped to THIS engine's ceiling — a full-width exporter's
        # wide ledger must not make a quantized importer draft trees.
        st.width = max(min(int(sp.get("width", st.width)), st.w_max), 1)
        st.observe(req.prompt)
        st.observe(req.out)
        req.spec = st
    engine._slots[slot] = req
